"""Windowed utilization ledger: a live roofline over the pipeline's
own counters, and THE bottleneck verdict both bench and operators read.

ROADMAP's postmortem is blunt: five PRs bought safety and visibility,
not speed, and the only bottleneck diagnosis in the system —
``pipeline_bound_by`` in bench.py — was an offline, once-per-round
verdict. Nothing live could say which ceiling (decode, link, compute,
serve coalesce) binds *right now* or how much headroom remains. The
tf.data paper (PAPERS.md, arxiv 2101.12127) makes the case directly:
input-pipeline bottleneck attribution must be a continuous runtime
signal, because it is what drives both autotuning and operator action.

The ledger turns the counters the hot paths already feed into
per-window *rates* and utilization fractions against measured
per-host ceilings:

* **feeds** (always-on monotonic counters, recorded by the hot paths
  themselves — the registry's one-sink discipline):
  ``engine.busy_seconds`` (host decode/stage busy time, LocalEngine),
  ``device.run_seconds`` (runner dispatch+drain wall),
  ``ship.bytes_shipped`` (input bytes handed to device dispatch),
  ``ship.transfer_wait_seconds_total`` (device_get drain waits),
  ``serve.coalesce_wait_seconds`` (the micro-batcher's fill window);
* **windows** (default 2 s, ``SPARKDL_TPU_LEDGER_WINDOW_S``,
  typo-degrade): each :meth:`UtilizationLedger.tick` snapshots the
  feeds, deltas them against the previous window, and divides:
  time-shaped lanes (decode / compute / serve) become busy fractions
  of the window wall; the link lane becomes measured bytes/s over the
  probed host↔device bandwidth — the live generalization of bench's
  ``host_fed_ceiling_ips`` math — degrading to the transfer-wait
  fraction when no probe is available (``link_basis`` says which);
* **ceilings** (:func:`probe_ceilings`): one-shot ``measure_link``
  (the same ``utils/measure`` machinery tools/measure_transfer.py and
  bench.py share), cached to ``SPARKDL_TPU_LEDGER_PROBE_FILE`` so a
  steady-state process never re-pays the probe; a corrupt or missing
  cache degrades to a fresh probe (counted, never silent). Probing is
  always DELIBERATE (an explicit call, or bench injecting its own
  measurement): a tick reads memory or the cache file only — a
  scrape or flight dump on a wedged device must never block on a
  device probe;
* **verdict** (:func:`attribute`): ``bound_by`` = the max-utilization
  stage, ``headroom_pct`` = what remains under its ceiling. ONE code
  path: bench.py's offline ``pipeline_bound_by`` and the live
  ``ledger.bound_by`` gauge are both this function, so the two
  verdicts cannot drift onto different math.

Published per window (registry gauges → ``/metricsz``):
``ledger.util.{decode,link,compute,serve}``, ``ledger.bound_by``
(:data:`STAGE_CODES` — Prometheus gauges are numbers; the string
verdict rides ``/statusz``, flight bundles, and bench), and
``ledger.headroom_pct``; plus counters ``ledger.windows``,
``ledger.windows_evicted`` (ring evictions — bounded, never silent)
and ``ledger.counter_resets`` (a feed counter that moved backwards —
registry cleared/re-created — reads as an empty delta, not a negative
rate).

Arming (``SPARKDL_TPU_LEDGER=1`` or ``ledger().arm()``): the hot-path
:func:`ledger_poll` (runner.run epilogue, serve dispatcher — the
``autotune.poll`` precedent) advances windows under live traffic.
Reader-driven windows need no arming at all: ``/metricsz`` /
``/statusz`` scrapes and flight-bundle dumps call :meth:`tick_due`,
so any scrape gets a fresh window. Disarmed, ``ledger_poll`` is one
armed-check — the tracer's shared-no-op regime, pinned <10 µs in
``tests/test_ledger.py``.

Pickle discipline (StageMetrics precedent): the lock and the history
ring drop on the wire — windows measured in one process are that
process's record; configuration (window length, probed ceilings,
armed-ness) travels.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Mapping, Optional

from sparkdl_tpu.obs.registry import default_registry

logger = logging.getLogger(__name__)

_TRUE = ("1", "true", "yes", "on")

#: the four roofline lanes, in doc/report order
STAGES = ("decode", "link", "compute", "serve")

#: ``ledger.bound_by`` gauge coding (gauges are numbers; the string
#: verdict rides /statusz, flight bundles, and bench's "bound" block)
STAGE_CODES = {"idle": -1, "decode": 0, "link": 1, "compute": 2,
               "serve": 3}

#: feed counters, stage → registry key (the hot paths record these)
FEEDS = {
    "decode": "engine.busy_seconds",
    "compute": "device.run_seconds",
    "serve": "serve.coalesce_wait_seconds",
}
LINK_WAIT_FEED = "ship.transfer_wait_seconds_total"
#: NET link traffic: runs with the device-resident infeed ring engaged
#: feed only the bytes that actually crossed the link this run
#: (record_run_feeds(shipped_bytes=...) — ring hits re-use resident
#: HBM slabs and are counted in ship.bytes_resident instead), so
#: ledger.util.link reflects the wire, not the input size
LINK_BYTES_FEED = "ship.bytes_shipped"
#: executed-FLOPs feed (runtime/runner.py record_run_feeds, populated
#: when the compile log recorded the program's cost_analysis) — lifts
#: the compute lane from a generic busy fraction to a model-specific
#: roofline when a device_gflops ceiling exists (compute_basis names
#: which)
COMPUTE_FLOPS_FEED = "device.flops_total"

#: default window length (seconds) when SPARKDL_TPU_LEDGER_WINDOW_S
#: is unset — long enough to smooth per-batch jitter, short enough
#: that an operator watching /metricsz sees the pipeline move
DEFAULT_WINDOW_S = 2.0

#: default history-ring capacity (windows) when
#: SPARKDL_TPU_LEDGER_HISTORY is unset — a few minutes of 2 s windows
DEFAULT_HISTORY = 64

#: bytes the one-shot link probe ships (small on purpose: the probe is
#: a ceiling estimate, not a benchmark; bench injects its own measured
#: link instead of re-paying this)
PROBE_MB = 4

#: probe-cache schema tag — bump when the layout changes incompatibly
PROBE_SCHEMA = "sparkdl-ledger-probe/1"

_MB = 1024.0 * 1024.0


def _env_float(name: str, default: float) -> float:
    """Parse a positive float env var, typo-degrading to the default
    with one warning (the SPARKDL_TPU_TRACE_BUFFER precedent: a config
    typo must not make the module unusable)."""
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        val = float(raw)
        if val <= 0:
            raise ValueError(val)
        return val
    except ValueError:
        logger.warning("%s=%r is not a positive number; using the "
                       "default %s", name, raw, default)
        default_registry().counter("ledger.config_errors").add()
        return default


def _env_int(name: str, default: int) -> int:
    """Parse a positive int env var with the same typo-degrade
    contract as :func:`_env_float` — the module-level singleton parses
    these at import time, so a fractional or garbage value must warn
    and default, never make ``import sparkdl_tpu`` fail."""
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        val = int(raw)
        if val < 1:
            raise ValueError(val)
        return val
    except ValueError:
        logger.warning("%s=%r is not a positive int; using the "
                       "default %s", name, raw, default)
        default_registry().counter("ledger.config_errors").add()
        return default


def _env_armed() -> bool:
    return os.environ.get("SPARKDL_TPU_LEDGER", "").lower() in _TRUE


def attribute(util: Mapping[str, float]) -> Dict[str, Any]:
    """THE bottleneck verdict over per-stage utilization fractions —
    the one code path bench.py's offline ``pipeline_bound_by`` and the
    live ``ledger.bound_by`` gauge both call, so the two verdicts
    cannot drift.

    ``bound_by`` is the max-utilization stage (ties break
    alphabetically-first, deterministically); ``headroom_pct`` is what
    remains under that stage's ceiling, floored at 0 (a value measured
    above its ceiling — the link moved between measurements — reads as
    zero headroom, not negative). An empty or all-zero ``util`` is an
    idle window: ``bound_by="idle"``, full headroom."""
    items = sorted(((k, float(v)) for k, v in util.items()),
                   key=lambda kv: (-kv[1], kv[0]))
    if not items or items[0][1] <= 0.0:
        return {"bound_by": "idle", "headroom_pct": 100.0,
                "util": {k: round(float(v), 4) for k, v in util.items()}}
    name, frac = items[0]
    return {"bound_by": name,
            "headroom_pct": round(max(0.0, (1.0 - frac) * 100.0), 1),
            "util": {k: round(float(v), 4) for k, v in util.items()}}


def _default_probe_file() -> str:
    env = os.environ.get("SPARKDL_TPU_LEDGER_PROBE_FILE", "")
    if env:
        return env
    import tempfile
    return os.path.join(tempfile.gettempdir(),
                        "sparkdl_tpu_ledger_probe.json")


def _valid_probe(data: Any) -> bool:
    return (isinstance(data, dict)
            and data.get("schema") == PROBE_SCHEMA
            and isinstance(data.get("link_h2d_MBps"), (int, float))
            and data["link_h2d_MBps"] > 0)


def probe_ceilings(path: Optional[str] = None, force: bool = False,
                   measure=None) -> Dict[str, Any]:
    """The per-host ceilings the ledger divides by: host↔device link
    bandwidth from a one-shot :func:`~sparkdl_tpu.utils.measure.measure_link`
    (the same machinery tools/measure_transfer.py and bench.py use),
    cached to ``path`` (default ``SPARKDL_TPU_LEDGER_PROBE_FILE``) so
    steady state never re-pays the probe.

    Degrade ladder, every rung counted (``ledger.probe_errors``) and
    none silent: a corrupt/missing/stale-schema cache file → fresh
    probe (rewriting the cache); a failing probe (no backend) →
    ``{"error": ...}`` — the ledger then falls back to transfer-wait
    attribution for the link lane; a cache that cannot be written →
    the fresh probe is still returned."""
    path = path if path is not None else _default_probe_file()
    # a missing cache is the normal first run (probe below); an
    # existing-but-unusable one is a degrade, counted and re-probed
    if not force and os.path.exists(path):
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
            if _valid_probe(data):
                return data
            logger.warning("ledger: probe cache %s is invalid; "
                           "re-probing", path)
            default_registry().counter("ledger.probe_errors").add()
        except (OSError, json.JSONDecodeError) as e:
            logger.warning("ledger: probe cache %s unreadable (%s); "
                           "re-probing", path, e)
            default_registry().counter("ledger.probe_errors").add()
    if measure is None:
        from sparkdl_tpu.utils.measure import measure_link
        measure = measure_link
    try:
        link = measure(PROBE_MB)
    except Exception as e:
        default_registry().counter("ledger.probe_errors").add()
        logger.warning("ledger: link probe failed (%s); the link lane "
                       "degrades to transfer-wait attribution", e)
        return {"schema": PROBE_SCHEMA, "error": f"{type(e).__name__}: {e}"}
    probe = {
        "schema": PROBE_SCHEMA,
        "link_h2d_MBps": float(link["h2d_MBps"]),
        "link_d2h_MBps": float(link.get("d2h_MBps", 0.0)),
        "probe_mb": PROBE_MB,
        "source": "probe_ceilings",
        # wall-clock stamp so an operator can judge the cache's age
        # across restarts; window math stays on perf_counter (H5)
        "probed_unix": time.time(),  # sparkdl-lint: allow[H5] -- probe-cache freshness stamp for operators, not span/latency math
    }
    try:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(probe, f)
    except OSError as e:
        default_registry().counter("ledger.probe_errors").add()
        logger.warning("ledger: cannot write probe cache %s (%s); "
                       "this process keeps the probe in memory", path, e)
    return probe


class UtilizationLedger:
    """Windowed roofline accounting over the feed counters (module
    docstring). One process-wide instance (:func:`ledger`); standalone
    instances exist for tests."""

    # sparkdl-lint H3 contract: ticks can race (hot-path poll vs a
    # scrape vs a flight dump) — the window baseline and ring
    # bookkeeping hold self._lock
    _lock_guards = ("windows", "evicted")

    def __init__(self, window_s: Optional[float] = None,
                 history: Optional[int] = None,
                 probe_file: Optional[str] = None):
        self.window_s = (window_s if window_s is not None
                         else _env_float("SPARKDL_TPU_LEDGER_WINDOW_S",
                                         DEFAULT_WINDOW_S))
        if self.window_s <= 0:
            raise ValueError(
                f"window_s must be positive, got {self.window_s}")
        cap = (history if history is not None
               else _env_int("SPARKDL_TPU_LEDGER_HISTORY",
                             DEFAULT_HISTORY))
        if cap <= 0:
            raise ValueError(f"history must be positive, got {cap}")
        self.history_capacity = cap
        self.probe_file = probe_file
        # None → follow the env; True/False → programmatic override
        self._override: Optional[bool] = None
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=cap)
        self.windows = 0            # lifetime ticks that produced one
        self.evicted = 0            # ring evictions — never silent
        self._last_t: Optional[float] = None
        self._last: Optional[Dict[str, float]] = None
        self._ceilings: Optional[Dict[str, Any]] = None
        self._epoch = time.perf_counter()

    # -- arming (the hot-path poll only; ticks always work) ------------------

    @property
    def armed(self) -> bool:
        ov = self._override
        if ov is not None:
            return ov
        return _env_armed()

    def arm(self) -> None:
        """Advance windows from the hot-path poll regardless of
        ``SPARKDL_TPU_LEDGER``."""
        self._override = True

    def disarm(self) -> None:
        self._override = False

    def arm_from_env(self) -> None:
        self._override = None

    # -- ceilings ------------------------------------------------------------

    def ensure_ceilings(self, probe: Optional[Dict[str, Any]] = None
                        ) -> Dict[str, Any]:
        """The cached per-host ceilings, probing on first need. An
        explicit ``probe`` dict (bench.py injects its own measured
        link so the probe is never paid twice in one process) replaces
        the cache and is persisted to the probe file."""
        if probe is not None:
            probe = dict(probe)
            probe.setdefault("schema", PROBE_SCHEMA)
            with self._lock:
                self._ceilings = probe
            if _valid_probe(probe):
                try:
                    with open(self.probe_file or _default_probe_file(),
                              "w", encoding="utf-8") as f:
                        json.dump(probe, f)
                except OSError as e:
                    default_registry().counter(
                        "ledger.probe_errors").add()
                    logger.warning("ledger: cannot persist injected "
                                   "ceilings (%s)", e)
            return probe
        with self._lock:
            if self._ceilings is not None:
                return self._ceilings
        probed = probe_ceilings(path=self.probe_file)
        with self._lock:
            if self._ceilings is None:
                self._ceilings = probed
            return self._ceilings

    def _ceilings_for_tick(self) -> Dict[str, Any]:
        """The ceilings a TICK may use: whatever is already in memory,
        else a cheap READ of the probe cache file — never a measured
        probe. Ticks run inside scrape handlers, flight dumps (where
        the device may be exactly the thing that is wedged), and the
        hot-path poll; a blocking device_put probe must never ride
        those paths. With no ceilings anywhere the link lane degrades
        to transfer-wait attribution; a deliberate probe is an
        explicit :meth:`ensure_ceilings` / :func:`probe_ceilings`
        call (bench injects its own measured link)."""
        with self._lock:
            if self._ceilings is not None:
                return self._ceilings
        path = self.probe_file or _default_probe_file()
        cached: Dict[str, Any] = {}
        if os.path.exists(path):
            try:
                with open(path, encoding="utf-8") as f:
                    data = json.load(f)
                if _valid_probe(data):
                    cached = data
            except (OSError, json.JSONDecodeError) as e:
                default_registry().counter("ledger.probe_errors").add()
                logger.warning("ledger: probe cache %s unreadable "
                               "(%s); ticking without ceilings", path,
                               e)
        if cached:
            with self._lock:
                if self._ceilings is None:
                    self._ceilings = cached
                return self._ceilings
        return {}

    # -- windowing -----------------------------------------------------------

    @staticmethod
    def _read_feeds() -> Dict[str, float]:
        reg = default_registry()
        vals = {stage: reg.counter(key).value
                for stage, key in FEEDS.items()}
        vals["link_wait"] = reg.counter(LINK_WAIT_FEED).value
        vals["link_bytes"] = reg.counter(LINK_BYTES_FEED).value
        vals["compute_flops"] = reg.counter(COMPUTE_FLOPS_FEED).value
        return vals

    def baseline(self, now: Optional[float] = None) -> None:
        """Reset the window baseline to the current feed totals —
        bench.py calls this right before its measured pass so the
        first tick covers exactly that pass. Also drains the host
        pipeline's pooled-worker window peak (data/pipeline.py): a
        pooled experiment that finished BEFORE this baseline must not
        leak its worker count into the next window's decode ceiling
        (a serial decode-saturated pass divided by stale workers
        under-reads, and the decode-bound prior never fires)."""
        now = time.perf_counter() if now is None else now
        cur = self._read_feeds()
        from sparkdl_tpu.data.pipeline import consume_workers_peak
        consume_workers_peak()
        from sparkdl_tpu.inputsvc import client as _inputsvc
        _inputsvc.consume_workers_peak()
        with self._lock:
            self._last_t, self._last = now, cur

    def tick(self, now: Optional[float] = None, min_dt: float = 0.0
             ) -> Optional[Dict[str, Any]]:
        """Close one window: delta the feeds against the previous
        baseline, compute utilization fractions, publish the
        ``ledger.*`` gauges, append to the history ring, and return
        the window dict. Returns ``None`` without advancing anything
        for a window shorter than ``min_dt`` — including the
        zero-duration case (two ticks at one instant must not divide
        by zero or corrupt the baseline) and the racing-readers case
        (``tick_due`` passes the window length, so the loser of a
        scrape/poll race re-verifies dueness under the lock instead
        of closing a junk microsecond window over the winner's) —
        and for the very first tick (which only establishes the
        baseline). Ceilings come from memory or the cache file only
        (:meth:`_ceilings_for_tick`) — a tick never runs a measured
        probe."""
        ceilings = self._ceilings_for_tick()
        now = time.perf_counter() if now is None else now
        cur = self._read_feeds()
        with self._lock:
            if self._last_t is None:
                self._last_t, self._last = now, cur
                return None
            dt = now - self._last_t
            if dt <= 0.0 or dt < min_dt:
                return None
            last = self._last
            self._last_t, self._last = now, cur
        deltas = {k: cur.get(k, 0.0) - last.get(k, 0.0) for k in cur}
        resets = sum(1 for v in deltas.values() if v < 0)
        deltas = {k: max(0.0, v) for k, v in deltas.items()}
        # the decode lane's pooled-worker ceiling (data/pipeline.py):
        # with N host-pipeline workers live, the lane can earn N busy
        # seconds per wall second (0/1 = serial, the busy-fraction
        # ceiling unchanged). The WINDOW PEAK — max(live gauge, max
        # since the previous tick) — not an instantaneous read: a
        # pooled stream that ended mid-window already banked its N
        # busy-seconds, and dividing them by a serial ceiling would
        # fabricate a saturated decode verdict right as PipelineTarget
        # reads it as the deepen-workers prior.
        from sparkdl_tpu.data.pipeline import consume_workers_peak
        decode_workers = max(
            default_registry().gauge("pipeline.workers").value,
            consume_workers_peak())
        # the disaggregated decode fleet ADDS lanes on top of the
        # host's own (sparkdl_tpu/inputsvc): N live remote workers
        # ship N workers' busy-seconds home per wall second, beyond
        # whatever the local pool (or serial path) contributes — so
        # the ceiling is local peak + remote peak, same window-peak
        # reasoning as above (docs/DATA_SERVICE.md)
        from sparkdl_tpu.inputsvc import client as _inputsvc
        decode_workers = decode_workers + \
            _inputsvc.consume_workers_peak()
        util, link_basis, compute_basis, decode_basis = self._utils(
            deltas, dt, ceilings, decode_workers)
        verdict = attribute(util)
        window = {
            "t_s": round(now - self._epoch, 3),
            "dt_s": round(dt, 4),
            "util": verdict["util"],
            "bound_by": verdict["bound_by"],
            "headroom_pct": verdict["headroom_pct"],
            "link_basis": link_basis,
            "compute_basis": compute_basis,
            "decode_basis": decode_basis,
            "decode_workers": max(1, int(decode_workers or 0)),
            "ship_MBps": round(deltas["link_bytes"] / dt / _MB, 3),
            "counter_resets": resets,
        }
        with self._lock:
            evicting = len(self._ring) == self._ring.maxlen
            if evicting:
                self.evicted += 1
            self._ring.append(window)
            self.windows += 1
        reg = default_registry()
        for stage in STAGES:
            reg.gauge(f"ledger.util.{stage}").set(util.get(stage, 0.0))
        reg.gauge("ledger.bound_by").set(
            STAGE_CODES.get(verdict["bound_by"], -1))
        reg.gauge("ledger.headroom_pct").set(verdict["headroom_pct"])
        reg.counter("ledger.windows").add()
        if resets:
            reg.counter("ledger.counter_resets").add(resets)
        if evicting:
            # the bounded ring evicts its oldest window — counted,
            # never silent (the tracer drop-note discipline)
            reg.counter("ledger.windows_evicted").add()
        # HBM accounting rides the window cadence: per-device
        # memory_stats() → hbm.* gauges with high-watermark tracking
        # (obs/compile_log.py; degrades internally — CPU devices
        # report nothing and hbm.devices_reporting says so)
        try:
            from sparkdl_tpu.obs.compile_log import publish_hbm
            publish_hbm(reg)
        except Exception as e:
            reg.counter("ledger.config_errors").add()
            logger.debug("ledger: hbm publish failed (%s)", e)
        return window

    @staticmethod
    def _utils(deltas: Dict[str, float], dt: float,
               ceilings: Dict[str, Any],
               decode_workers: float = 0.0) -> tuple:
        """(utilization fractions, link basis, compute basis, decode
        basis) for one window. Time lanes are busy fractions of the
        window wall; the link lane is shipped bytes/s over the probed
        bandwidth, degrading to the transfer-wait fraction when no
        probe is available; the compute lane is executed FLOPs/s over
        the model-calibrated device ceiling (``device_gflops`` in the
        ceilings — bench injects it from its device-resident pass ×
        the compile log's cost_analysis) when BOTH the ceiling and the
        flops feed exist, degrading to the dispatch+drain busy
        fraction (``compute_basis`` names which — the ``link_basis``
        mirror). The DECODE lane has the same two-tier shape
        (``decode_basis``): with N host-pipeline workers live at any
        point in the window (the window peak of the
        ``pipeline.workers`` gauge, data/pipeline.py) the ceiling is
        N busy-seconds per wall second — N workers each fully busy IS
        the lane's roofline — degrading to the plain busy fraction
        when the pipeline runs serial."""
        clamp = lambda v: min(1.0, max(0.0, v))  # noqa: E731
        util = {stage: clamp(deltas[stage] / dt) for stage in FEEDS}
        workers = max(1.0, float(decode_workers or 0.0))
        if workers > 1.0:
            util["decode"] = clamp(
                deltas["decode"] / (dt * workers))
            decode_basis = "busy/pooled-workers"
        else:
            decode_basis = "busy-time"
        bw = ceilings.get("link_h2d_MBps") if ceilings else None
        if isinstance(bw, (int, float)) and bw > 0:
            util["link"] = clamp(
                (deltas["link_bytes"] / dt) / (bw * _MB))
            basis = "bytes/probed-bandwidth"
        else:
            util["link"] = clamp(deltas["link_wait"] / dt)
            basis = "transfer-wait"
        gflops = ceilings.get("device_gflops") if ceilings else None
        flops = deltas.get("compute_flops", 0.0)
        if isinstance(gflops, (int, float)) and gflops > 0 and flops > 0:
            util["compute"] = clamp(
                (flops / dt) / (gflops * 1e9))
            compute_basis = "flops/model-ceiling"
        else:
            compute_basis = "busy-time"
        return util, basis, compute_basis, decode_basis

    def tick_due(self, now: Optional[float] = None
                 ) -> Optional[Dict[str, Any]]:
        """Tick iff a full window has elapsed since the last one (or
        no baseline exists yet). The reader-driven entry point —
        scrapes and flight dumps call this, so a hammered ``/metricsz``
        cannot shrink windows below ``window_s``. Racing callers are
        safe: ``min_dt`` makes the loser re-verify dueness inside the
        tick's critical section and back off instead of closing a
        duplicate near-zero window."""
        now = time.perf_counter() if now is None else now
        with self._lock:
            due = (self._last_t is None
                   or (now - self._last_t) >= self.window_s)
        if due:
            # sparkdl-lint: allow[H17] -- window_s is immutable config after __init__; the hold above guards _last_t, window_s just rode inside it
            return self.tick(now=now, min_dt=self.window_s)
        return None

    # -- readout -------------------------------------------------------------

    def history(self) -> List[Dict[str, Any]]:
        """The retained windows, oldest first (bounded ring)."""
        with self._lock:
            return list(self._ring)

    def last_window(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def last_bound(self, max_age_s: Optional[float] = None
                   ) -> Optional[str]:
        """The most recent window's verdict, or ``None`` when no
        window exists (or the last one is older than ``max_age_s`` —
        a stale verdict is no prior at all)."""
        w = self.last_window()
        if w is None:
            return None
        if max_age_s is not None:
            age = (time.perf_counter() - self._epoch) - w["t_s"]
            if age > max_age_s:
                return None
        return w["bound_by"]

    def current_verdict(self) -> Dict[str, Any]:
        """The last window's verdict when one exists, else a
        cumulative attribution over the process lifetime (feed totals
        over seconds since this ledger's epoch) — what
        ``throughput_report`` prints when no windowing ran."""
        w = self.last_window()
        if w is not None:
            return {"bound_by": w["bound_by"],
                    "headroom_pct": w["headroom_pct"],
                    "util": w["util"], "basis": "window"}
        now = time.perf_counter()
        dt = max(now - self._epoch, 1e-9)
        totals = self._read_feeds()
        with self._lock:
            ceilings = self._ceilings or {}
        # cumulative totals include any pooled busy-seconds this
        # process ever banked — divide the decode lane by the
        # process-lifetime worker high-water, not the serial ceiling
        from sparkdl_tpu.data.pipeline import alltime_workers_peak
        from sparkdl_tpu.inputsvc import client as _inputsvc
        util, _basis, _cbasis, _dbasis = self._utils(
            totals, dt, ceilings,
            alltime_workers_peak()
            + _inputsvc.alltime_workers_peak())
        v = attribute(util)
        v["basis"] = "cumulative"
        return v

    def status(self) -> Dict[str, Any]:
        """The scrape-able state (``/statusz``, flight bundles)."""
        with self._lock:
            ceilings = self._ceilings
            last = self._ring[-1] if self._ring else None
            return {
                "armed": self.armed,
                "window_s": self.window_s,
                "windows": self.windows,
                "history_len": len(self._ring),
                "history_capacity": self.history_capacity,
                "evicted": self.evicted,
                "ceilings": ceilings,
                "last": last,
            }

    # -- pickle discipline (StageMetrics precedent) --------------------------

    def __getstate__(self):
        # the lock, baseline, and history ring are process-local
        # (windows measured here are this process's record);
        # configuration — window length, ring capacity, ceilings,
        # armed-ness — travels
        state = self.__dict__.copy()
        del state["_lock"]
        del state["_ring"]
        del state["_last_t"]
        del state["_last"]
        del state["_epoch"]
        state["windows"] = 0
        state["evicted"] = 0
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=self.history_capacity)
        self._last_t = None
        self._last = None
        self._epoch = time.perf_counter()


_LEDGER = UtilizationLedger()


def ledger() -> UtilizationLedger:
    """THE process-wide ledger every reader (scrapes, flight bundles,
    bench, throughput_report) consults."""
    return _LEDGER


def ledger_poll() -> None:
    """The hot-path window advancer (runner.run epilogue, the serve
    dispatcher — the ``autotune.poll`` precedent): when the ledger is
    armed and a window has elapsed, close it. Disarmed this is one
    armed-check — the shared-no-op regime, <10 µs pinned in
    tests/test_ledger.py."""
    led = _LEDGER
    if not led.armed:
        return
    led.tick_due()
