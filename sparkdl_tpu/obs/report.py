"""Critical-path summary of an exported trace.

``python -m sparkdl_tpu.obs report <trace.json>`` reads a
Chrome/Perfetto trace-event file (what ``Tracer.export`` writes — a
bare event list, or a ``{"traceEvents": [...]}`` wrapper) and prints
where the run's microseconds went without opening a UI:

* per-lane busy % — the union of each lane's span intervals over the
  run's wall span: a link-bound pipeline shows the ship lane near 100%
  while engine/device idle, a decode-bound one the reverse; server
  traces (docs/SERVING.md) land on the ``serve`` lane through the same
  machinery — no special-casing;
* top spans by total time — the aggregate cost of each span name;
* stalls — the wait-shaped spans (``device_get``,
  ``collective_lock_wait``, ``device_put``, ``pad_stage``, and the
  serve lane's ``coalesce`` window) broken out, because those are the
  seconds a perf PR can actually claw back.

``report --tails <trace.json>`` adds tail-latency attribution from the
per-request spans the serve layer records when armed
(obs/request_log.py): the request-latency p50/p99 and the p99
specimen's breakdown across the named phases (queue vs coalesce-wait
vs staging vs device vs reassembly) — where the TAIL spends its time,
which a lane-busy summary cannot say.

``report --bound <trace.json>`` renders the trace against the SAME
roofline lanes the live ledger publishes (obs/ledger.py): per-lane
busy fractions of the trace wall (decode = engine-lane spans, link =
the ``device_get``/``device_put`` wire edges, compute = ship-lane
``dispatch``, serve = the ``coalesce`` windows) fed through the same
``ledger.attribute()`` call, so the offline trace verdict and the
live ``ledger.bound_by`` gauge are one code path.

``report --workers [--bundle <flight.json>] <trace.json>`` renders the
per-worker lanes a merged cross-process trace carries (the telemetry
plane, obs/remote.py): per-worker busy % of the trace wall, span and
partition counts, and — joined with a flight bundle's ``workers[]``
section — rows decoded, degrade/fault counts, and dead/stalled flags.

Forward-compat contract (both modes): event TYPES are data too — flow
events (``ph`` s/t/f, how split requests link), counter events, and
``ph`` values this report has never heard of must all be skipped, not
crashed on. Pinned by ``tests/test_request_obs.py``.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

from sparkdl_tpu.obs.registry import nearest_rank
from sparkdl_tpu.obs.request_log import PHASES

#: span names that are waits, not work — the claw-back targets.
#: ``coalesce`` is the serve lane's batching window: time spent
#: holding admitted requests open for more arrivals (docs/SERVING.md)
#: — latency deliberately traded for batch fill, but still a wait.
STALL_NAMES = ("device_get", "collective_lock_wait", "device_put",
               "pad_stage", "coalesce")


def load_events(path: str) -> List[dict]:
    """Read a trace-event file: a bare JSON list or the
    ``{"traceEvents": [...]}`` wrapper both formats allow."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict):
        data = data.get("traceEvents")
    if not isinstance(data, list):
        raise ValueError(
            f"{path}: not a trace-event list (expected a JSON array "
            "or {'traceEvents': [...]})")
    return data


def _merged_length(intervals: List[Tuple[float, float]]) -> float:
    """Total length of the union of [start, end) intervals."""
    total = 0.0
    cur_lo = cur_hi = None
    for lo, hi in sorted(intervals):
        if cur_hi is None or lo > cur_hi:
            if cur_hi is not None:
                total += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        else:
            cur_hi = max(cur_hi, hi)
    if cur_hi is not None:
        total += cur_hi - cur_lo
    return total


def summarize(events: Sequence[dict]) -> str:
    """The text report (also unit-testable without the CLI).

    Forward-compat contract: lanes are DATA, not a schema — a trace
    carrying lanes this report has never heard of (newer
    instrumentation), lane metadata with zero spans (an armed run that
    never exercised a subsystem), or spans whose pid has no metadata
    at all must all summarize, never crash; unknown lanes fall back to
    the span's ``cat`` (or ``?``). Pinned by
    ``tests/test_obs.py::TestReportForwardCompat``."""
    lane_of_pid = {e["pid"]: e.get("args", {}).get("name", "?")
                   for e in events
                   if e.get("ph") == "M"
                   and e.get("name") == "process_name"
                   and "pid" in e}
    spans = [e for e in events
             if e.get("ph") == "X" and "ts" in e and "pid" in e]
    if not spans:
        return "(no spans in trace)"
    t0 = min(e["ts"] for e in spans)
    t1 = max(e["ts"] + e.get("dur", 0.0) for e in spans)
    wall_us = max(t1 - t0, 1e-9)

    by_lane: Dict[str, List[Tuple[float, float]]] = {}
    by_name: Dict[Tuple[str, str], List[float]] = {}
    for e in spans:
        lane = lane_of_pid.get(e["pid"]) or e.get("cat", "?")
        dur = e.get("dur", 0.0)
        by_lane.setdefault(lane, []).append(
            (e["ts"], e["ts"] + dur))
        by_name.setdefault((lane, e.get("name", "?")), []).append(dur)

    lines = [f"trace: {len(spans)} spans over {wall_us / 1e3:.3f} ms "
             f"across lanes {', '.join(sorted(by_lane))}",
             "",
             "lane        busy_ms   busy%   spans"]
    for lane in sorted(by_lane):
        busy = _merged_length(by_lane[lane])
        lines.append(f"{lane.ljust(10)}  {busy / 1e3:8.3f}  "
                     f"{100.0 * busy / wall_us:5.1f}%  "
                     f"{len(by_lane[lane]):5d}")

    agg = sorted(((sum(durs), len(durs), max(durs), lane, name)
                  for (lane, name), durs in by_name.items()),
                 reverse=True)
    lines += ["", "top spans by total time (lane/name, calls, "
                  "total_ms, max_ms)"]
    for total, calls, mx, lane, name in agg[:12]:
        lines.append(f"  {lane}/{name}: {calls} calls, "
                     f"{total / 1e3:.3f} ms total, {mx / 1e3:.3f} ms max")

    stalls = [(total, calls, lane, name)
              for total, calls, _mx, lane, name in agg
              if any(name == s or name.startswith(s) for s in STALL_NAMES)]
    lines += ["", "stalls (wait-shaped spans — the claw-back targets)"]
    if stalls:
        for total, calls, lane, name in stalls:
            lines.append(f"  {lane}/{name}: {total / 1e3:.3f} ms over "
                         f"{calls} calls ({100.0 * total / wall_us:.1f}% "
                         "of wall)")
    else:
        lines.append("  (none recorded)")
    return "\n".join(lines)


#: trace-span → roofline-lane mapping for ``--bound``: the offline
#: twin of the ledger's feed counters (caveat carried in the output:
#: on async backends ship-lane ``dispatch`` times the ENQUEUE, so the
#: compute fraction is a lower bound there)
BOUND_LANES = {
    "decode": "engine-lane spans (decode / stage execution)",
    "link": "device_get/device_put spans (the wire, host-observable)",
    "compute": "ship-lane dispatch spans (enqueue on async backends)",
    "serve": "serve-lane coalesce windows (fill wait)",
}


def bound_summary(events: Sequence[dict]) -> Optional[dict]:
    """Per-roofline-lane busy fractions of the trace wall plus the
    ledger's own ``attribute()`` verdict. Returns ``None`` for a trace
    with no spans. Forward-compat: unknown lanes/names simply don't
    land in any roofline lane."""
    from sparkdl_tpu.obs.ledger import attribute

    lane_of_pid = {e["pid"]: e.get("args", {}).get("name", "?")
                   for e in events
                   if e.get("ph") == "M"
                   and e.get("name") == "process_name"
                   and "pid" in e}
    spans = [e for e in events
             if e.get("ph") == "X" and "ts" in e and "pid" in e]
    if not spans:
        return None
    t0 = min(e["ts"] for e in spans)
    t1 = max(e["ts"] + e.get("dur", 0.0) for e in spans)
    wall_us = max(t1 - t0, 1e-9)

    def stage_of(e: dict) -> Optional[str]:
        lane = lane_of_pid.get(e["pid"]) or e.get("cat", "?")
        name = e.get("name", "?")
        if lane == "engine":
            return "decode"
        if name == "device_get" or name == "device_put":
            return "link"
        if lane == "ship" and name == "dispatch":
            return "compute"
        if lane == "serve" and name == "coalesce":
            return "serve"
        return None

    intervals: Dict[str, List[Tuple[float, float]]] = {}
    for e in spans:
        stage = stage_of(e)
        if stage is not None:
            intervals.setdefault(stage, []).append(
                (e["ts"], e["ts"] + e.get("dur", 0.0)))
    util = {stage: min(1.0, _merged_length(
                intervals.get(stage, [])) / wall_us)
            for stage in BOUND_LANES}
    verdict = attribute(util)
    return {"wall_ms": round(wall_us / 1e3, 3), "spans": len(spans),
            **verdict}


def summarize_bound(events: Sequence[dict]) -> str:
    """The ``--bound`` text section (unit-testable without the CLI)."""
    b = bound_summary(events)
    if b is None:
        return ("(no spans in trace — arm SPARKDL_TPU_TRACE and run "
                "traffic to record a roofline-readable timeline)")
    lines = [f"live roofline, offline (busy fraction of "
             f"{b['wall_ms']:.3f} ms wall over {b['spans']} spans)"]
    for stage, what in BOUND_LANES.items():
        frac = b["util"].get(stage, 0.0)
        lines.append(f"  {stage.ljust(8)} {100.0 * frac:5.1f}%  ({what})")
    lines.append(f"bound by: {b['bound_by']} "
                 f"(headroom {b['headroom_pct']:.1f}%)")
    return "\n".join(lines)


def tails_summary(events: Sequence[dict]) -> Optional[dict]:
    """Tail-latency attribution from the per-request spans
    (obs/request_log.py records one ``request`` span per resolved
    request, its args carrying the phase breakdown in ``phases_s``).
    Returns ``None`` when the trace holds no request spans (disarmed
    run, or pre-request-log trace — forward AND backward compatible).

    The dict: request count, p50/p99 latency (nearest-rank over
    successful requests; failed ones live in the availability stream,
    not the latency population), the p99 specimen's id and per-phase
    milliseconds, and ``attributed_pct`` — how much of the measured
    p99 the named phases account for (the acceptance bar is ≥95%)."""
    reqs = [e for e in events
            if e.get("ph") == "X" and e.get("name") == "request"
            and isinstance(e.get("args"), dict) and "ts" in e]
    if not reqs:
        return None
    pool = [e for e in reqs if e["args"].get("status", "ok") == "ok"]
    if not pool:
        # the latency population is successes ONLY (the separate-
        # population contract) — a trace of pure failures has no
        # percentiles to attribute, and must say so rather than
        # quietly computing them from the excluded population
        return {"requests": 0, "failed_excluded": len(reqs),
                "p50_ms": None, "p99_ms": None,
                "p99_request_id": None, "p99_batches": None,
                "p99_phases_ms": {}, "attributed_pct": None,
                "tail_phase_pct": {}}
    durs = sorted(float(e.get("dur", 0.0)) for e in pool)
    p50_us, p99_us = (nearest_rank(durs, 0.5),
                      nearest_rank(durs, 0.99))
    worst = next(e for e in pool
                 if float(e.get("dur", 0.0)) == p99_us)
    phases_s = worst["args"].get("phases_s") or {}
    total_s = float(worst.get("dur", 0.0)) / 1e6
    attributed_s = sum(float(v) for v in phases_s.values()
                       if isinstance(v, (int, float)))
    attributed_pct = (100.0 * attributed_s / total_s) if total_s else 0.0

    # the aggregate tail (every request at/above the p99): mean phase
    # fractions — is the specimen typical of its tail or an outlier?
    tail = [e for e in pool if float(e.get("dur", 0.0)) >= p99_us]
    tail_fractions: Dict[str, float] = {}
    counted = 0
    for e in tail:
        ph = e["args"].get("phases_s")
        dur_s = float(e.get("dur", 0.0)) / 1e6
        if not isinstance(ph, dict) or dur_s <= 0:
            continue
        counted += 1
        for k, v in ph.items():
            if isinstance(v, (int, float)):
                tail_fractions[k] = tail_fractions.get(k, 0.0) \
                    + float(v) / dur_s
    if counted:
        tail_fractions = {k: round(100.0 * v / counted, 1)
                          for k, v in tail_fractions.items()}

    return {
        "requests": len(pool),
        "failed_excluded": len(reqs) - len(pool),
        "p50_ms": round(p50_us / 1e3, 3),
        "p99_ms": round(p99_us / 1e3, 3),
        "p99_request_id": worst["args"].get("request_id"),
        "p99_batches": worst["args"].get("batches"),
        "p99_phases_ms": {k: round(float(v) * 1e3, 3)
                          for k, v in phases_s.items()
                          if isinstance(v, (int, float))},
        "attributed_pct": round(attributed_pct, 1),
        "tail_phase_pct": tail_fractions,
    }


def summarize_tails(events: Sequence[dict]) -> str:
    """The ``--tails`` text section (unit-testable without the CLI)."""
    t = tails_summary(events)
    if t is None:
        return ("(no request spans in trace — arm SPARKDL_TPU_TRACE "
                "(or SPARKDL_TPU_REQUEST_LOG=1) and serve traffic "
                "through a ModelServer to record per-request "
                "timelines)")
    if t["requests"] == 0:
        return (f"({t['failed_excluded']} failed request(s), no "
                "successes — the latency population is successes "
                "only; see the availability objective on /statusz "
                "for the failure story)")
    lines = [
        f"requests: {t['requests']} "
        f"(+{t['failed_excluded']} failed, excluded from the latency "
        f"population)   p50 {t['p50_ms']:.3f} ms   "
        f"p99 {t['p99_ms']:.3f} ms",
        "",
        f"p99 attribution — request {t['p99_request_id']} "
        f"({t['p99_batches']} micro-batch(es)):",
    ]
    total_ms = t["p99_ms"] or 1e-9
    for phase in PHASES:
        ms = t["p99_phases_ms"].get(phase)
        if ms is None:
            continue
        lines.append(f"  {phase.ljust(11)} {ms:10.3f} ms  "
                     f"{100.0 * ms / total_ms:5.1f}%")
    for phase, ms in sorted(t["p99_phases_ms"].items()):
        if phase not in PHASES:     # forward-compat: new phases print
            lines.append(f"  {phase.ljust(11)} {ms:10.3f} ms  "
                         f"{100.0 * ms / total_ms:5.1f}%")
    lines.append(f"  attributed: {t['attributed_pct']:.1f}% of the "
                 "measured p99")
    if t["tail_phase_pct"]:
        frac = ", ".join(f"{k} {v:.1f}%" for k, v in sorted(
            t["tail_phase_pct"].items(),
            key=lambda kv: -kv[1]))
        lines.append(f"  tail mean breakdown: {frac}")
    return "\n".join(lines)


def compile_summary(events: Sequence[dict]) -> Optional[dict]:
    """Compile forensics from the ``compile``-lane spans the compile
    log records (obs/compile_log.py — one span per ACTUAL compile,
    args carrying ``fn``/``kind``/``retrace``/``unexpected``/``diff``/
    ``flops``). Returns ``None`` for a trace with no compile spans
    (disarmed compile log, or pre-compile-log trace — forward AND
    backward compatible). The dict: compile count, total/max wall ms,
    retrace and unexpected-retrace counts, a per-function breakdown,
    and the retrace diffs — what "diagnosing a compile storm"
    (docs/SERVING.md) reads first."""
    spans = [e for e in events
             if e.get("ph") == "X" and e.get("name") == "compile"
             and isinstance(e.get("args"), dict)]
    if not spans:
        return None
    by_fn: Dict[str, Dict[str, float]] = {}
    retraces = []
    for e in spans:
        a = e["args"]
        fn = str(a.get("fn", "?"))
        dur = float(e.get("dur", 0.0))
        entry = by_fn.setdefault(fn, {
            "compiles": 0, "total_ms": 0.0, "max_ms": 0.0,
            "retraces": 0, "unexpected": 0})
        entry["compiles"] += 1
        entry["total_ms"] += dur / 1e3
        entry["max_ms"] = max(entry["max_ms"], dur / 1e3)
        if a.get("retrace"):
            entry["retraces"] += 1
        if a.get("unexpected"):
            entry["unexpected"] += 1
        # attribution rows cover BOTH verdicts: an unexpected compile
        # with no prior signature (steady program, log armed
        # mid-incident — retrace=False by the diff's absence) is
        # still the violation this report exists to surface
        if a.get("retrace") or a.get("unexpected"):
            retraces.append({"fn": fn, "ms": round(dur / 1e3, 3),
                             "unexpected": bool(a.get("unexpected")),
                             "diff": a.get("diff") or None})
    for entry in by_fn.values():
        entry["total_ms"] = round(entry["total_ms"], 3)
        entry["max_ms"] = round(entry["max_ms"], 3)
    return {
        "compiles": len(spans),
        "total_ms": round(sum(float(e.get("dur", 0.0))
                              for e in spans) / 1e3, 3),
        "retraces": sum(1 for s in spans
                        if s["args"].get("retrace")),
        "unexpected_retraces": sum(1 for s in spans
                                   if s["args"].get("unexpected")),
        "by_fn": by_fn,
        "retrace_events": retraces[-8:],
    }


def summarize_compile(events: Sequence[dict]) -> str:
    """The ``--compile`` text section (unit-testable without the
    CLI)."""
    c = compile_summary(events)
    if c is None:
        return ("(no compile spans in trace — arm SPARKDL_TPU_TRACE "
                "and SPARKDL_TPU_COMPILE_LOG=1 (or "
                "compile_log().arm()) and run traffic to record "
                "compile forensics)")
    lines = [
        f"compiles: {c['compiles']}   "
        f"wall {c['total_ms']:.3f} ms total (first-call: "
        "trace+compile+first execution)   "
        f"retraces {c['retraces']} "
        f"({c['unexpected_retraces']} UNEXPECTED — compiles on a "
        "steady hot path)",
        "",
        "per function (compiles, total_ms, max_ms, retraces, "
        "unexpected)",
    ]
    for fn in sorted(c["by_fn"],
                     key=lambda k: -c["by_fn"][k]["total_ms"]):
        e = c["by_fn"][fn]
        lines.append(
            f"  {fn}: {e['compiles']} compiles, "
            f"{e['total_ms']:.3f} ms total, {e['max_ms']:.3f} ms max"
            + (f", {e['retraces']} retraces"
               if e["retraces"] else "")
            + (f" ({e['unexpected']} unexpected)"
               if e["unexpected"] else ""))
    if c["retrace_events"]:
        lines += ["", "retrace attribution (most recent; the "
                      "argument that moved)"]
        for r in c["retrace_events"]:
            tag = "UNEXPECTED " if r["unexpected"] else ""
            lines.append(f"  {tag}{r['fn']} ({r['ms']:.3f} ms): "
                         f"{r['diff'] or '(no diff recorded)'}")
    return "\n".join(lines)


def workers_summary(events: Sequence[dict],
                    bundle: Optional[dict] = None) -> Optional[dict]:
    """Per-worker lanes of a merged trace (the cross-process telemetry
    plane, obs/remote.py): every process group whose metadata name
    starts with ``worker.`` becomes one row — busy % (union of its
    span intervals over the WHOLE trace's wall, so worker lanes
    compare directly against parent lanes), span/partition counts —
    joined, when a flight ``bundle`` dict is given, with that worker's
    ``workers[]`` entry (rows decoded, degrade/fault counts, dead
    flag). Returns ``None`` for a trace with no worker process groups
    (serial or disarmed run — forward AND backward compatible).
    Forward-compat both ways: unknown worker tracks flow through as
    rows here, and traces without them summarize fine everywhere
    else."""
    worker_of_pid = {}
    for e in events:
        if (e.get("ph") == "M" and e.get("name") == "process_name"
                and "pid" in e):
            name = str(e.get("args", {}).get("name", ""))
            if name.startswith("worker."):
                worker_of_pid[e["pid"]] = name
    if not worker_of_pid:
        return None
    spans = [e for e in events
             if e.get("ph") == "X" and "ts" in e and "pid" in e]
    if spans:
        t0 = min(e["ts"] for e in spans)
        t1 = max(e["ts"] + e.get("dur", 0.0) for e in spans)
        wall_us = max(t1 - t0, 1e-9)
    else:
        wall_us = 1e-9
    by_status: Dict[int, dict] = {}
    if bundle:
        for entry in bundle.get("workers") or []:
            if isinstance(entry, dict) and "index" in entry:
                by_status[entry["index"]] = entry
    workers = []
    for pid in sorted(worker_of_pid):
        track = worker_of_pid[pid]
        mine = [e for e in spans if e["pid"] == pid]
        busy = _merged_length([(e["ts"], e["ts"] + e.get("dur", 0.0))
                               for e in mine])
        # the track name is "worker.<i> (pid NNNN)[ [DEAD]]" — the
        # slot index keys the bundle join; a rename stays a plain row
        try:
            index = int(track.split()[0].split(".", 1)[1])
        except (IndexError, ValueError):
            index = None
        status = by_status.get(index, {})
        counters = status.get("counters") or {}
        faults_state = status.get("faults") or {}
        fault_count = sum(
            s.get("injected", 0)
            for s in (faults_state.get("sites") or {}).values()
            if isinstance(s, dict))
        workers.append({
            "track": track,
            "index": index,
            "busy_pct": round(100.0 * busy / wall_us, 1),
            "busy_ms": round(busy / 1e3, 3),
            "spans": len(mine),
            "partitions": sum(1 for e in mine
                              if e.get("name") == "worker.decode"),
            "rows": counters.get("pipeline.worker_rows"),
            # bundle counters round-trip through JSON as floats
            "degrades": int(counters.get("pipeline.degrade_events", 0)
                            + len(status.get("degrades") or [])),
            "faults_injected": int(fault_count),
            "dead": bool(status.get("dead")),
            "stalled": bool(status.get("stalled")),
        })
    return {"wall_ms": round(wall_us / 1e3, 3), "workers": workers}


def summarize_workers(events: Sequence[dict],
                      bundle: Optional[dict] = None) -> str:
    """The ``--workers`` text section (unit-testable without the
    CLI)."""
    w = workers_summary(events, bundle=bundle)
    if w is None:
        return ("(no worker process tracks in trace — arm "
                "SPARKDL_TPU_TRACE and run a pipeline_mode=process "
                "stream to record cross-process worker timelines; "
                "serial and thread-mode runs have none)")
    lines = [f"pipeline workers (merged cross-process trace, "
             f"{w['wall_ms']:.3f} ms wall; busy % is of the WHOLE "
             "trace wall — directly comparable to parent lanes)",
             "",
             "worker            busy_ms   busy%  spans  parts  "
             "rows  degrades  faults"]
    for row in w["workers"]:
        flags = ""
        if row["dead"]:
            flags += "  [DEAD]"
        if row["stalled"]:
            flags += "  [STALLED]"
        rows = "?" if row["rows"] is None else f"{int(row['rows'])}"
        lines.append(
            f"{row['track'].split(' ')[0].ljust(16)}  "
            f"{row['busy_ms']:8.3f}  {row['busy_pct']:5.1f}%  "
            f"{row['spans']:5d}  {row['partitions']:5d}  "
            f"{rows.rjust(4)}  {row['degrades']:8d}  "
            f"{row['faults_injected']:6d}{flags}")
    if not any(r["rows"] is not None for r in w["workers"]):
        lines.append("")
        lines.append("(rows/degrades/faults need a flight bundle: "
                     "report --workers --bundle <bundle.json> "
                     "<trace.json>)")
    return "\n".join(lines)


def main(argv: Sequence[str]) -> int:
    args = list(argv)
    tails = "--tails" in args
    if tails:
        args.remove("--tails")
    bound = "--bound" in args
    if bound:
        args.remove("--bound")
    compile_ = "--compile" in args
    if compile_:
        args.remove("--compile")
    workers = "--workers" in args
    if workers:
        args.remove("--workers")
    bundle = None
    if "--bundle" in args:
        i = args.index("--bundle")
        if i + 1 >= len(args):
            print("error: --bundle needs a flight-bundle path")
            return 2
        bundle_path = args[i + 1]
        del args[i:i + 2]
        try:
            with open(bundle_path, encoding="utf-8") as f:
                bundle = json.load(f)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: {e}")
            return 2
    if len(args) != 2 or args[0] != "report":
        print("usage: python -m sparkdl_tpu.obs report [--tails] "
              "[--bound] [--compile] [--workers] "
              "[--bundle <flight.json>] <trace.json>")
        return 2
    try:
        events = load_events(args[1])
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}")
        return 2
    print(summarize(events))
    if tails:
        print()
        print("request tails (per-request phase attribution)")
        print(summarize_tails(events))
    if bound:
        print()
        print(summarize_bound(events))
    if compile_:
        print()
        print("compile forensics (retrace attribution)")
        print(summarize_compile(events))
    if workers:
        print()
        print("cross-process workers (per-worker lanes)")
        print(summarize_workers(events, bundle=bundle))
    return 0
