"""Critical-path summary of an exported trace.

``python -m sparkdl_tpu.obs report <trace.json>`` reads a
Chrome/Perfetto trace-event file (what ``Tracer.export`` writes — a
bare event list, or a ``{"traceEvents": [...]}`` wrapper) and prints
where the run's microseconds went without opening a UI:

* per-lane busy % — the union of each lane's span intervals over the
  run's wall span: a link-bound pipeline shows the ship lane near 100%
  while engine/device idle, a decode-bound one the reverse; server
  traces (docs/SERVING.md) land on the ``serve`` lane through the same
  machinery — no special-casing;
* top spans by total time — the aggregate cost of each span name;
* stalls — the wait-shaped spans (``device_get``,
  ``collective_lock_wait``, ``device_put``, ``pad_stage``, and the
  serve lane's ``coalesce`` window) broken out, because those are the
  seconds a perf PR can actually claw back.
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence, Tuple

#: span names that are waits, not work — the claw-back targets.
#: ``coalesce`` is the serve lane's batching window: time spent
#: holding admitted requests open for more arrivals (docs/SERVING.md)
#: — latency deliberately traded for batch fill, but still a wait.
STALL_NAMES = ("device_get", "collective_lock_wait", "device_put",
               "pad_stage", "coalesce")


def load_events(path: str) -> List[dict]:
    """Read a trace-event file: a bare JSON list or the
    ``{"traceEvents": [...]}`` wrapper both formats allow."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict):
        data = data.get("traceEvents")
    if not isinstance(data, list):
        raise ValueError(
            f"{path}: not a trace-event list (expected a JSON array "
            "or {'traceEvents': [...]})")
    return data


def _merged_length(intervals: List[Tuple[float, float]]) -> float:
    """Total length of the union of [start, end) intervals."""
    total = 0.0
    cur_lo = cur_hi = None
    for lo, hi in sorted(intervals):
        if cur_hi is None or lo > cur_hi:
            if cur_hi is not None:
                total += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        else:
            cur_hi = max(cur_hi, hi)
    if cur_hi is not None:
        total += cur_hi - cur_lo
    return total


def summarize(events: Sequence[dict]) -> str:
    """The text report (also unit-testable without the CLI).

    Forward-compat contract: lanes are DATA, not a schema — a trace
    carrying lanes this report has never heard of (newer
    instrumentation), lane metadata with zero spans (an armed run that
    never exercised a subsystem), or spans whose pid has no metadata
    at all must all summarize, never crash; unknown lanes fall back to
    the span's ``cat`` (or ``?``). Pinned by
    ``tests/test_obs.py::TestReportForwardCompat``."""
    lane_of_pid = {e["pid"]: e.get("args", {}).get("name", "?")
                   for e in events
                   if e.get("ph") == "M"
                   and e.get("name") == "process_name"
                   and "pid" in e}
    spans = [e for e in events
             if e.get("ph") == "X" and "ts" in e and "pid" in e]
    if not spans:
        return "(no spans in trace)"
    t0 = min(e["ts"] for e in spans)
    t1 = max(e["ts"] + e.get("dur", 0.0) for e in spans)
    wall_us = max(t1 - t0, 1e-9)

    by_lane: Dict[str, List[Tuple[float, float]]] = {}
    by_name: Dict[Tuple[str, str], List[float]] = {}
    for e in spans:
        lane = lane_of_pid.get(e["pid"]) or e.get("cat", "?")
        dur = e.get("dur", 0.0)
        by_lane.setdefault(lane, []).append(
            (e["ts"], e["ts"] + dur))
        by_name.setdefault((lane, e.get("name", "?")), []).append(dur)

    lines = [f"trace: {len(spans)} spans over {wall_us / 1e3:.3f} ms "
             f"across lanes {', '.join(sorted(by_lane))}",
             "",
             "lane        busy_ms   busy%   spans"]
    for lane in sorted(by_lane):
        busy = _merged_length(by_lane[lane])
        lines.append(f"{lane.ljust(10)}  {busy / 1e3:8.3f}  "
                     f"{100.0 * busy / wall_us:5.1f}%  "
                     f"{len(by_lane[lane]):5d}")

    agg = sorted(((sum(durs), len(durs), max(durs), lane, name)
                  for (lane, name), durs in by_name.items()),
                 reverse=True)
    lines += ["", "top spans by total time (lane/name, calls, "
                  "total_ms, max_ms)"]
    for total, calls, mx, lane, name in agg[:12]:
        lines.append(f"  {lane}/{name}: {calls} calls, "
                     f"{total / 1e3:.3f} ms total, {mx / 1e3:.3f} ms max")

    stalls = [(total, calls, lane, name)
              for total, calls, _mx, lane, name in agg
              if any(name == s or name.startswith(s) for s in STALL_NAMES)]
    lines += ["", "stalls (wait-shaped spans — the claw-back targets)"]
    if stalls:
        for total, calls, lane, name in stalls:
            lines.append(f"  {lane}/{name}: {total / 1e3:.3f} ms over "
                         f"{calls} calls ({100.0 * total / wall_us:.1f}% "
                         "of wall)")
    else:
        lines.append("  (none recorded)")
    return "\n".join(lines)


def main(argv: Sequence[str]) -> int:
    if len(argv) != 2 or argv[0] != "report":
        print("usage: python -m sparkdl_tpu.obs report <trace.json>")
        return 2
    try:
        events = load_events(argv[1])
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}")
        return 2
    print(summarize(events))
    return 0
