"""Stall watchdog: heartbeat-fed no-progress detection for the hot
loops.

The collective-launch deadlock PR 2 fixed presented as a silent hang —
no error, no timeout, no forensics — and the serving layer added more
loops that can wedge the same way (a dispatcher blocked in a collective
program, a drain that never completes). This module is the detector:
the loops that matter mark themselves ACTIVE (``watch(source)``) and
beat cheaply while making progress (``pulse(source)``); a monitor
thread flags any active source whose last beat is older than the
threshold, logs loudly, increments ``watchdog.stalls`` in the metrics
registry, and triggers a flight-recorder dump
(:mod:`sparkdl_tpu.obs.flight`) so the hang arrives with a postmortem
attached instead of a blank screen.

Fed by: the serve dispatcher loop (one source per model session),
``dispatch_chunks`` (the ship-side dispatch/drain state machine),
the estimator step loops, and ``collective_launch`` lock holds
(``collective.hold`` is active for exactly the time the process-wide
launch lock is held — a hold past the threshold IS the deadlock
signature).

Arming follows the sanitizer's probe-and-degrade precedent:
``SPARKDL_TPU_WATCHDOG=1`` in the environment (threshold via
``SPARKDL_TPU_WATCHDOG_THRESHOLD_S``, default 30s), or
``watchdog().arm(threshold_s=...)`` programmatically (the override
wins). Disarmed, ``watch()`` returns one shared no-op context and
``pulse()`` returns after a single armed-check — the same shared-no-op
regime as the tracer, pinned alongside its <10µs bound
(``tests/test_flight.py``).

An idle process is NOT a stall: only sources inside a ``watch()``
block are monitored, and every watched loop opens the block *after*
its idle wait (the serve dispatcher watches from "batch collected" to
"batch resolved", not while blocked waiting for work). Recovery is
automatic — a stalled source that beats (or exits its watch block)
clears its verdict and counts ``watchdog.recoveries``.

All clocks are ``time.perf_counter()`` — the tracer's clock (and
sparkdl-lint H5 enforces that no ``time.time()`` sneaks into obs/serve
timing math).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional

from sparkdl_tpu.obs.registry import default_registry

logger = logging.getLogger(__name__)

_TRUE = ("1", "true", "yes", "on")

#: no-progress threshold (seconds) when SPARKDL_TPU_WATCHDOG_THRESHOLD_S
#: is unset — generous enough that a slow compile is not a "stall"
DEFAULT_THRESHOLD_S = 30.0


def _env_armed() -> bool:
    return os.environ.get("SPARKDL_TPU_WATCHDOG", "").lower() in _TRUE


# (raw env string, parsed value): threshold_s is read on every monitor
# tick and every /healthz scrape — a config typo must warn ONCE per
# value, not spam the log for the process lifetime
_env_threshold_cache: Optional[tuple] = None


def _env_threshold() -> float:
    global _env_threshold_cache
    raw = os.environ.get("SPARKDL_TPU_WATCHDOG_THRESHOLD_S", "")
    cached = _env_threshold_cache
    if cached is not None and cached[0] == raw:
        return cached[1]
    try:
        v = float(raw) if raw else DEFAULT_THRESHOLD_S
        if v <= 0:
            raise ValueError(v)
    except ValueError:
        # a config typo must degrade to the default, not crash the loop
        # that was trying to protect itself
        logger.warning(
            "SPARKDL_TPU_WATCHDOG_THRESHOLD_S=%r is not a positive "
            "number; using the default %.1fs", raw, DEFAULT_THRESHOLD_S)
        v = DEFAULT_THRESHOLD_S
    _env_threshold_cache = (raw, v)
    return v


class _NoopWatch:
    """The disarmed fast path: one shared instance, nothing tracked."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NOOP_WATCH = _NoopWatch()


class _Watch:
    """An armed activity window: the source is monitored between enter
    and exit, and exit ALWAYS deregisters (even if the watchdog was
    disarmed mid-block) so no source leaks into a false stall later."""

    __slots__ = ("_wd", "_source")

    def __init__(self, wd: "StallWatchdog", source: str):
        self._wd = wd
        self._source = source

    def __enter__(self):
        self._wd.begin(self._source, _force=True)
        return self

    def __exit__(self, exc_type, exc, tb):
        self._wd.end(self._source, _force=True)
        return False


class StallWatchdog:
    """Heartbeat table + monitor thread (module docstring). One
    process-wide instance (:func:`watchdog`) is what the instrumented
    loops feed; standalone instances exist for tests."""

    # sparkdl-lint H3 contract: sources register from every hot-loop
    # thread at once — structural mutations of the table and the
    # stall bookkeeping hold self._lock (pulse writes only a float
    # slot in an existing entry, GIL-atomic by design: the beat must
    # stay cheap enough for per-chunk call sites)
    _lock_guards = ("stalls_fired",)

    def __init__(self, threshold_s: Optional[float] = None):
        # None → follow the env; a number → programmatic override
        self._threshold_override = threshold_s
        self._armed_override: Optional[bool] = None
        self._lock = threading.Lock()
        # source → [active_count, last_beat, stalled]
        self._sources: Dict[str, list] = {}
        self.stalls_fired = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- arming --------------------------------------------------------------

    @property
    def armed(self) -> bool:
        ov = self._armed_override
        if ov is not None:
            return ov
        return _env_armed()

    @property
    def threshold_s(self) -> float:
        if self._threshold_override is not None:
            return self._threshold_override
        return _env_threshold()

    def arm(self, threshold_s: Optional[float] = None) -> None:
        """Monitor regardless of SPARKDL_TPU_WATCHDOG; an explicit
        ``threshold_s`` overrides the env threshold too."""
        if threshold_s is not None:
            if threshold_s <= 0:
                raise ValueError(
                    f"threshold_s must be positive, got {threshold_s}")
            self._threshold_override = threshold_s
        self._armed_override = True
        self._ensure_thread()

    def disarm(self) -> None:
        """Stop monitoring regardless of the env; the monitor thread
        exits and active-source bookkeeping drains as watch blocks
        close."""
        self._armed_override = False
        self._stop_thread()

    def arm_from_env(self) -> None:
        """Drop the programmatic overrides; follow the env again."""
        self._armed_override = None
        self._threshold_override = None
        if self.armed:
            self._ensure_thread()

    # -- the heartbeat surface (hot path) ------------------------------------

    def watch(self, source: str):
        """Context manager marking ``source`` ACTIVE for its duration;
        a shared no-op when disarmed. Open it around the *working*
        phase of a loop (after the idle wait), then :meth:`pulse`
        inside it on every unit of progress."""
        if not self.armed:
            return _NOOP_WATCH
        return _Watch(self, source)

    def begin(self, source: str, _force: bool = False) -> None:
        """Non-context entry half of :meth:`watch` (for __enter__/
        __exit__-shaped call sites like the collective launch lock)."""
        if not _force and not self.armed:
            return
        now = time.perf_counter()
        with self._lock:
            entry = self._sources.get(source)
            if entry is None:
                self._sources[source] = [1, now, False]
            else:
                entry[0] += 1
                entry[1] = now
        self._ensure_thread()

    def end(self, source: str, _force: bool = False) -> None:
        """Deactivate one :meth:`begin`. Cheap when nothing is tracked;
        never checks ``armed`` beyond that, so a disarm between begin
        and end cannot leak an eternally-active source."""
        # sparkdl-lint: allow[H17] -- lock-free emptiness fast path (a GIL-atomic len); the authoritative lookup re-runs under the lock below
        if not self._sources:
            return
        with self._lock:
            entry = self._sources.get(source)
            if entry is None:
                return
            entry[0] -= 1
            if entry[0] <= 0:
                was_stalled = entry[2]
                del self._sources[source]
                if was_stalled:
                    default_registry().counter(
                        "watchdog.recoveries").add()

    def pulse(self, source: str) -> None:
        """Record progress for ``source`` — one float write into the
        entry's beat slot (GIL-atomic; no lock on the hot path). A
        pulse outside any watch block is ignored."""
        # sparkdl-lint: allow[H17] -- the documented hot-path contract: one GIL-atomic dict lookup + float slot write per unit of work, no lock by design (a stale miss costs one beat, never corruption)
        entry = self._sources.get(source)
        if entry is not None:
            entry[1] = time.perf_counter()

    # -- the verdict ---------------------------------------------------------

    def check_once(self, now: Optional[float] = None) -> List[str]:
        """One monitor pass: flag newly-stalled sources (side effects:
        loud log, ``watchdog.stalls``, flight dump), un-flag recovered
        ones. Returns the sources CURRENTLY considered stalled."""
        if now is None:
            now = time.perf_counter()
        threshold = self.threshold_s
        fired: List[str] = []
        recovered: List[str] = []
        stalled: List[str] = []
        with self._lock:
            for source, entry in self._sources.items():
                if entry[0] <= 0:
                    continue
                age = now - entry[1]
                if age > threshold:
                    if not entry[2]:
                        entry[2] = True
                        fired.append(source)
                    stalled.append(source)
                elif entry[2]:
                    entry[2] = False
                    recovered.append(source)
        reg = default_registry()
        for source in fired:
            with self._lock:
                self.stalls_fired += 1
            reg.counter("watchdog.stalls").add()
            logger.error(
                "watchdog: source %r made no progress for > %.3fs — "
                "possible stall/deadlock; dumping the flight recorder",
                source, threshold)
            self._dump_flight(source, threshold)
        for source in recovered:
            reg.counter("watchdog.recoveries").add()
            logger.warning("watchdog: source %r resumed progress",
                           source)
        return stalled

    def _dump_flight(self, source: str, threshold: float) -> None:
        try:
            from sparkdl_tpu.obs import flight
            rec = flight.recorder()
            if rec.armed:
                rec.dump(reason=f"watchdog stall: {source!r} made no "
                                f"progress for > {threshold:.3f}s")
        # sparkdl-lint: allow[H12] -- the stall IS accounted (watchdog.stalls counter + ERROR log fired before this call); the dump is best-effort forensics on top
        except Exception:
            # the watchdog must survive a failed postmortem — the
            # stall log + counter above already happened
            logger.exception("watchdog: flight-recorder dump failed")

    def healthy(self) -> bool:
        """False while any active source is flagged stalled — the
        ``/healthz`` verdict."""
        with self._lock:
            return not any(e[2] for e in self._sources.values())

    def verdict(self) -> dict:
        """The scrape-able state: active source ages, current stalls,
        lifetime fire count (``/healthz`` + ``/statusz`` + flight
        bundles)."""
        now = time.perf_counter()
        with self._lock:
            active = {s: round(now - e[1], 3)
                      for s, e in self._sources.items() if e[0] > 0}
            stalled = sorted(s for s, e in self._sources.items()
                             if e[2])
            fired = self.stalls_fired
        return {"armed": self.armed,
                "threshold_s": self.threshold_s,
                "active_sources": active,
                "stalled_sources": stalled,
                "stalls_fired": fired,
                "healthy": not stalled}

    # -- the monitor thread --------------------------------------------------

    def _interval(self) -> float:
        # fast enough to fire "within threshold" of the stall, slow
        # enough to cost nothing: a quarter-threshold tick, clamped
        return min(max(self.threshold_s / 4.0, 0.01), 1.0)

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._monitor, name="sparkdl-watchdog",
                daemon=True)
            self._thread.start()

    def _stop_thread(self) -> None:
        with self._lock:
            thread = self._thread
            self._thread = None
            self._stop.set()
        if thread is not None and thread.is_alive():
            thread.join(timeout=1.0)

    def _monitor(self) -> None:
        # sparkdl-lint: allow[H17] -- binds this monitor's OWN stop Event once at thread start by design: _ensure_thread swaps a fresh Event in (under the lock) before spawning, so a later swap must not retarget a retiring monitor
        stop = self._stop
        while not stop.wait(self._interval()):
            if not self.armed:
                return
            try:
                self.check_once()
            except Exception:
                # a watchdog that cannot complete its monitor pass is
                # silently not protecting anything — count it where a
                # scrape can alert on it (H12 accounting)
                default_registry().counter(
                    "watchdog.monitor_errors").add()
                logger.exception("watchdog: monitor pass failed")

    # -- pickle discipline (StageMetrics precedent) --------------------------

    def __getstate__(self):
        # the monitor thread, lock, and active-source table are
        # process-local; arming config travels
        state = self.__dict__.copy()
        del state["_lock"]
        del state["_thread"]
        del state["_stop"]
        del state["_sources"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()
        self._thread = None
        self._stop = threading.Event()
        self._sources = {}


_WATCHDOG = StallWatchdog()


def watchdog() -> StallWatchdog:
    """THE process-wide watchdog the instrumented loops feed."""
    return _WATCHDOG


def watch(source: str):
    """Module-level shorthand for ``watchdog().watch(...)`` — the form
    the hot loops use. Disarmed it returns one shared no-op object."""
    w = _WATCHDOG
    if not w.armed:
        return _NOOP_WATCH
    return _Watch(w, source)


def pulse(source: str) -> None:
    """Module-level heartbeat: one armed-check then a float write."""
    w = _WATCHDOG
    if not w.armed:
        return
    w.pulse(source)


def begin(source: str) -> None:
    """Mark ``source`` active (non-context call sites: the collective
    launch lock's __enter__)."""
    w = _WATCHDOG
    if not w.armed:
        return
    w.begin(source)


def end(source: str) -> None:
    """Deactivate one :func:`begin`; safe (and cheap) when disarmed or
    never begun."""
    _WATCHDOG.end(source)
