"""Flight recorder: push-button postmortems for a live or wedged
process.

PR 3's tracer answers "where did the microseconds go" only when a
human arms it and exports a file; the failure mode that motivated the
collective-launch fix (PR 2) presents as a silent hang with zero
forensics. The flight recorder closes that gap: armed, it continuously
retains the last-N spans (the tracer's existing bounded ring — arming
the recorder arms the tracer) while the always-on metrics registry
keeps the rolling counter state, and on demand it writes ONE
self-contained JSON bundle:

* the retained span timeline (Perfetto trace events, drop note
  included) and the full registry snapshot;
* per-session serve queue state (depth, warmup, runner
  strategy/config) for every live :class:`ModelServer`;
* the watchdog verdict (:mod:`sparkdl_tpu.obs.watchdog`);
* device/platform info and — where the backend supports it —
  per-device ``memory_stats()`` HBM accounting, degrading gracefully
  on CPU (the sanitizer's probe-and-degrade precedent).

Dump triggers: explicit :meth:`FlightRecorder.dump`, ``SIGUSR2``
(installed when armed — ``kill -USR2 <pid>`` on a wedged process gets
you the bundle without restarting it), an unhandled serve dispatch
failure (:func:`record_failure`, called by the dispatcher's exception
path), and a watchdog stall verdict.

Arming: ``SPARKDL_TPU_FLIGHT=1`` in the environment or
``recorder().arm()`` (the override wins); ``SPARKDL_TPU_FLIGHT_DIR``
names the bundle directory (default: the system temp dir).
:func:`autoarm` applies the env switch's side effects (signal handler
+ span retention) and is called from ``ModelServer.__init__`` and
``bench.py`` so the common entry points honor the env without any
code change. Disarmed there is no signal handler, no tracer arming,
and no per-event cost — only on-demand ``dump()`` still works (it
writes whatever is retained).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

from sparkdl_tpu.obs.registry import default_registry
from sparkdl_tpu.obs.trace import span, tracer
from sparkdl_tpu.obs.watchdog import watchdog

logger = logging.getLogger(__name__)

_TRUE = ("1", "true", "yes", "on")

#: bundle format tag — bump when the layout changes incompatibly
BUNDLE_SCHEMA = "sparkdl-flight/1"


def _env_armed() -> bool:
    return os.environ.get("SPARKDL_TPU_FLIGHT", "").lower() in _TRUE


def _bundle_dir() -> str:
    d = os.environ.get("SPARKDL_TPU_FLIGHT_DIR", "")
    if d:
        return d
    import tempfile
    return tempfile.gettempdir()


# -- degradable environment probes ------------------------------------------

_platform_cache: Optional[Dict[str, Any]] = None


def platform_info() -> Dict[str, Any]:
    """Backend/device identity for the bundle, probed once and cached;
    a missing or broken backend degrades to an ``error`` entry instead
    of failing the dump (the dump is most valuable exactly when the
    process is unwell)."""
    global _platform_cache
    if _platform_cache is not None:
        return _platform_cache
    info: Dict[str, Any] = {"python": sys.version.split()[0]}
    try:
        import jax
        devices = jax.devices()
        info.update({
            "backend": devices[0].platform if devices else None,
            "device_count": len(devices),
            "devices": [str(d) for d in devices[:8]],
            "jax": getattr(jax, "__version__", None),
        })
    except Exception as e:
        info["error"] = f"{type(e).__name__}: {e}"
    _platform_cache = info
    return info


def memory_stats() -> Dict[str, Any]:
    """Per-device ``memory_stats()`` (HBM accounting on TPU backends),
    ``None`` per device where unsupported — CPU devices typically
    return nothing, and the bundle says so rather than omitting the
    section."""
    out: Dict[str, Any] = {}
    try:
        import jax
        for d in jax.devices():
            stats_fn = getattr(d, "memory_stats", None)
            try:
                out[str(d)] = stats_fn() if stats_fn is not None else None
            except Exception as e:
                out[str(d)] = {"error": f"{type(e).__name__}: {e}"}
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
    return out


# -- the serve-state hookup -------------------------------------------------

# live ModelServers announce themselves so bundles can carry their
# queue state; weak references — the recorder must never keep a closed
# server alive
_SERVERS: "weakref.WeakSet" = weakref.WeakSet()


def register_server(server) -> None:
    """Called by ``ModelServer.__init__``; the bundle's ``serve``
    section is built from every still-alive registrant's
    ``telemetry_status()``."""
    _SERVERS.add(server)


def live_servers() -> List[Any]:
    return list(_SERVERS)


def _serve_status() -> List[dict]:
    out = []
    for server in live_servers():
        try:
            out.append(server.telemetry_status())
        except Exception as e:
            out.append({"error": f"{type(e).__name__}: {e}"})
    return out


def _slo_state() -> dict:
    """The SLO tracker's verdicts — was the process inside its error
    budgets when the bundle was cut; degrades like every probe."""
    try:
        from sparkdl_tpu.obs.slo import slo_tracker
        return slo_tracker().status()
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def _request_state() -> dict:
    """The request log's state plus the most recent per-request
    records (id, status, latency, phase breakdown) — the bundle's
    "which requests were in flight and where were they stuck"
    section. Bounded: last 32 records, the ring itself is already
    capped."""
    try:
        from sparkdl_tpu.obs.request_log import request_log
        rlog = request_log()
        recent = [{
            "request_id": r.request_id, "model": r.model,
            "rows": r.rows, "batches": r.batches, "status": r.status,
            "total_s": round(r.total_s, 6),
            "phases": {k: round(v, 6) for k, v in r.phases.items()},
        } for r in rlog.records()[-32:]]
        return {**rlog.status(), "recent": recent}
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def resilience_state() -> dict:
    """The resilience layer's drill/recovery state — injection config
    + per-site counts (resilience/faults.py), every live session's
    circuit verdict, and the retry/shed totals — ONE shape shared by
    the flight bundle, ``/statusz``, and bench's ``resilience`` block
    (docs/RESILIENCE.md), so a bench row, a curl, and a postmortem
    never disagree; degrades like every probe."""
    try:
        from sparkdl_tpu.resilience import faults
        out: Dict[str, Any] = {"faults": faults.state()}
        snap = default_registry().snapshot()
        out["totals"] = {
            k: snap[k] for k in (
                "faults.injected", "serve.retries", "serve.shed",
                "serve.shed_rows", "serve.circuit_rejections",
                "engine.retries", "resilience.retries",
                "resilience.budget_denied") if k in snap}
        circuits: Dict[str, Any] = {}
        for server in live_servers():
            try:
                for name, sess in getattr(server, "_sessions",
                                          {}).items():
                    circuits[name] = sess.circuit.status()
            except Exception as e:
                circuits["error"] = f"{type(e).__name__}: {e}"
        out["circuits"] = circuits
        return out
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def ledger_state() -> dict:
    """The utilization ledger's live-roofline state — a fresh window
    when one is due, the current ceilings, and the bounded history
    ring (obs/ledger.py) — ONE shape shared by the flight bundle and
    ``/statusz`` so a curl and a postmortem never disagree; degrades
    like every probe."""
    try:
        from sparkdl_tpu.obs.ledger import ledger
        led = ledger()
        led.tick_due()
        return {**led.status(), "history": led.history()}
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def compile_state() -> dict:
    """The compile log's forensics — per-function compile counts,
    retrace/unexpected verdicts, the last event (obs/compile_log.py)
    — ONE shape shared by the flight bundle, ``/statusz``, and
    bench's ``compile`` block; degrades like every probe. Recent
    events ride along (bounded: last 16) so a retrace-triggered dump
    carries the diff that caused it."""
    try:
        from sparkdl_tpu.obs.compile_log import compile_log
        log = compile_log()
        recent = [{
            "name": e.name, "kind": e.kind,
            "wall_s": round(e.wall_s, 4), "retrace": e.retrace,
            "unexpected": e.unexpected, "diff": e.diff,
            "flops": (e.cost or {}).get("flops"),
        } for e in log.events()[-16:]]
        return {**log.state(), "recent": recent}
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def pipeline_state() -> dict:
    """The parallel host pipeline's live state — resolved
    mode/workers/read-ahead plus the ``pipeline.*`` counters
    (data/pipeline.py) — ONE shape shared by the flight bundle,
    ``/statusz``, and bench's ``pipeline_overlap`` block; degrades
    like every probe."""
    try:
        from sparkdl_tpu.data.pipeline import state
        return state()
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def inputsvc_state() -> dict:
    """The disaggregated input service's live state — the last
    stream's resolved/live fleet plus the ``inputsvc.*`` counters
    (decode RPCs, failovers, snapshot hits/corruptions;
    sparkdl_tpu/inputsvc, docs/DATA_SERVICE.md) — ONE shape shared by
    the flight bundle, ``/statusz``, and bench's ``input_service``
    block; degrades like every probe."""
    try:
        from sparkdl_tpu.inputsvc.client import state
        return state()
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def fleet_state() -> dict:
    """The fleet control plane's live state — every
    :class:`~sparkdl_tpu.fleet.registry.ModelRegistry` in this process
    (deployed models/versions, swap tallies, router replica map,
    warm-start cache hits/corruptions; sparkdl_tpu/fleet,
    docs/SERVING.md "Fleet control plane") — ONE shape shared by the
    flight bundle, ``/statusz``, and bench's ``fleet`` block. A
    process that never imported the fleet package renders
    ``registries: []``; degrades like every probe."""
    try:
        import sys
        mod = sys.modules.get("sparkdl_tpu.fleet.registry")
        if mod is None:     # fleet never imported: nothing to report
            return {"registries": []}
        return {"registries": [r.state()
                               for r in mod.live_registries()]}
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def workers_state() -> list:
    """The per-worker telemetry plane's ``workers[]`` section — agent
    state, last spans, counter snapshot, fault config for every
    pipeline worker process that has shipped a frame
    (obs/remote.py) — ONE shape shared by the flight bundle and
    ``/statusz`` so a curl and a postmortem never disagree (a
    worker-death bundle NAMES the dead worker here); degrades like
    every probe."""
    try:
        from sparkdl_tpu.obs import remote
        return remote.aggregator().workers_status()
    except Exception as e:
        return [{"error": f"{type(e).__name__}: {e}"}]


def _autotune_state() -> dict:
    """The autotune controller's knob/decision state — the bundle's
    "what was the loop doing" section; degrades like every other probe
    (lazy import: obs must stay import-light)."""
    try:
        from sparkdl_tpu.autotune.core import controller
        return controller().state()
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


class FlightRecorder:
    """Retention + bundle writer (module docstring). One process-wide
    instance (:func:`recorder`); standalone instances exist for
    tests."""

    # sparkdl-lint H3 contract: dumps can fire concurrently (watchdog
    # thread, SIGUSR2 helper thread, the dispatcher's failure path) —
    # the dump bookkeeping holds self._lock
    _lock_guards = ("dumps", "last_dump_path")

    def __init__(self):
        self._armed_override: Optional[bool] = None
        self._lock = threading.Lock()
        self._seq = 0
        self.dumps = 0
        self.last_dump_path: Optional[str] = None
        self._signal_installed = False
        self._signal_degraded = False
        self._epoch = time.perf_counter()

    # -- arming --------------------------------------------------------------

    @property
    def armed(self) -> bool:
        ov = self._armed_override
        if ov is not None:
            return ov
        return _env_armed()

    def arm(self) -> None:
        """Arm retention + triggers: the tracer starts retaining spans
        (unless a programmatic disarm pinned it off) and SIGUSR2 gains
        a dump handler (probe-and-degrade: non-main-thread or
        signal-less platforms warn once and skip)."""
        self._armed_override = True
        trc = tracer()
        if not trc.armed:
            trc.arm()
        self._install_signal()

    def disarm(self) -> None:
        self._armed_override = False

    def _install_signal(self) -> None:
        if self._signal_installed or self._signal_degraded:
            return
        try:
            import signal

            def _on_sigusr2(signum, frame):
                # the dump runs on a helper thread: bundle building
                # takes registry/tracer locks, and a signal frame that
                # interrupted a lock holder must not self-deadlock
                threading.Thread(
                    target=self.dump, kwargs={"reason": "SIGUSR2"},
                    name="sparkdl-flight-sigusr2", daemon=True).start()

            signal.signal(signal.SIGUSR2, _on_sigusr2)
            self._signal_installed = True
        except (AttributeError, ValueError, OSError) as e:
            # AttributeError: no SIGUSR2 on this platform;
            # ValueError: not the main thread — degrade once, loudly
            self._signal_degraded = True
            logger.warning(
                "flight recorder: SIGUSR2 trigger unavailable (%s); "
                "dump() and the watchdog trigger still work", e)
            default_registry().counter("flight.degrade_events").add()

    # -- the bundle ----------------------------------------------------------

    def _next_path(self) -> str:
        with self._lock:
            self._seq += 1
            seq = self._seq
        return os.path.join(
            _bundle_dir(), f"sparkdl_flight_{os.getpid()}_{seq:03d}.json")

    def bundle(self, reason: str = "",
               extra: Optional[dict] = None) -> dict:
        """The forensics dict (what :meth:`dump` writes): every section
        degrades independently — a dump must never fail because one
        probe did."""
        trc = tracer()
        events = trc.trace_events()
        # refresh the hbm.* gauges so the registry snapshot below
        # carries the current high-watermarked HBM accounting, not a
        # stale window's (obs/compile_log.py; degrades internally)
        try:
            from sparkdl_tpu.obs.compile_log import publish_hbm
            publish_hbm()
        except Exception as e:
            default_registry().counter("flight.degrade_events").add()
            logger.debug("flight: hbm refresh failed (%s)", e)
        return {
            "schema": BUNDLE_SCHEMA,
            "reason": reason,
            "pid": os.getpid(),
            # wall-clock stamp so bundles order across processes; all
            # span/latency math stays on perf_counter (H5)
            "written_unix": time.time(),  # sparkdl-lint: allow[H5] -- forensics bundle timestamp, not span/latency math
            "uptime_s": round(time.perf_counter() - self._epoch, 3),
            "platform": platform_info(),
            "memory_stats": memory_stats(),
            "registry": default_registry().snapshot(),
            "watchdog": watchdog().verdict(),
            "spans": events,
            "span_count": sum(1 for e in events if e.get("ph") == "X"),
            "spans_dropped": trc.dropped,
            "serve": _serve_status(),
            "autotune": _autotune_state(),
            "compile": compile_state(),
            "ledger": ledger_state(),
            "pipeline": pipeline_state(),
            "inputsvc": inputsvc_state(),
            "fleet": fleet_state(),
            "workers": workers_state(),
            "slo": _slo_state(),
            "requests": _request_state(),
            "resilience": resilience_state(),
            "extra": extra or {},
        }

    def dump(self, path: Optional[str] = None, reason: str = "",
             extra: Optional[dict] = None) -> str:
        """Write one self-contained bundle; returns its path. Works
        armed or not (on-demand forensics are free to ask for), and is
        spanned on the ``obs`` lane so the postmortem's own cost shows
        up in the timeline it captured."""
        if path is None:
            path = self._next_path()
        with span("flight.dump", lane="obs", reason=reason[:80]):
            data = self.bundle(reason=reason, extra=extra)
            with open(path, "w", encoding="utf-8") as f:
                json.dump(data, f, default=str)
        with self._lock:
            self.dumps += 1
            self.last_dump_path = path
        default_registry().counter("flight.dumps").add()
        logger.warning(
            "flight recorder: bundle written to %s (%s; %d spans, "
            "%d registry keys)", path, reason or "explicit dump",
            data["span_count"], len(data["registry"]))
        return path

    def record_failure(self, exc: BaseException, where: str
                       ) -> Optional[str]:
        """The unhandled-failure trigger (serve dispatcher exception
        path): always counts ``flight.failures``; dumps only when
        armed — a disarmed process must not start writing files as a
        side effect of an error it already reports."""
        default_registry().counter("flight.failures").add()
        if not self.armed:
            return None
        try:
            return self.dump(
                reason=f"unhandled failure in {where}: "
                       f"{type(exc).__name__}: {exc}")
        except Exception:
            logger.exception(
                "flight recorder: failure dump failed (original "
                "failure in %s: %s)", where, exc)
            return None

    def status(self) -> dict:
        """The scrape-able state (``/statusz``)."""
        with self._lock:
            dumps = self.dumps
            last = self.last_dump_path
        return {"armed": self.armed, "dumps": dumps,
                "last_dump_path": last,
                "sigusr2": self._signal_installed}

    # -- pickle discipline (StageMetrics precedent) --------------------------

    def __getstate__(self):
        # the lock is process-local and the signal handler/dump history
        # belong to the process that wrote them; armed-ness travels
        state = self.__dict__.copy()
        del state["_lock"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()
        self._signal_installed = False
        self._epoch = time.perf_counter()


_RECORDER = FlightRecorder()


def recorder() -> FlightRecorder:
    """THE process-wide flight recorder (dump triggers all feed it)."""
    return _RECORDER


def autoarm() -> bool:
    """Apply ``SPARKDL_TPU_FLIGHT=1``'s side effects (signal handler +
    span retention) if the env asks and nothing pinned the recorder
    off. Idempotent and cheap; called from the common entry points
    (``ModelServer.__init__``, ``bench.py``)."""
    rec = _RECORDER
    if rec._armed_override is None and _env_armed():
        rec.arm()
        return True
    return rec.armed


def record_failure(exc: BaseException, where: str) -> Optional[str]:
    """Module-level shorthand for ``recorder().record_failure(...)``."""
    return _RECORDER.record_failure(exc, where)
