"""Process-wide metrics registry: named counters and gauges with ONE
``snapshot() -> dict`` for bench/CI.

The counters the pipeline already kept were siloed per object
(``RunnerMetrics`` on each runner, ``StageMetrics`` on each engine) and
the events that matter most to the link-bound north star — collective
launch-lock waits, prefetch queue depth, strategy degrades, sanitizer
arms — were counted nowhere. The registry is the single sink:

* hot-path events record directly into :func:`default_registry`
  (``collective.lock_wait_seconds``, ``ship.inflight`` /
  ``ship.inflight_peak``, ``ship.degrade_events``,
  ``sanitize.armed_runs`` / ``sanitize.degrade_events``);
* the existing per-object metrics publish INTO a registry on demand
  (``RunnerMetrics.publish`` → ``ship.*`` gauges,
  ``StageMetrics.publish`` → ``engine.stage.*`` gauges), which is how
  ``throughput_report`` and bench's ``"obs"`` block render — one
  snapshot, no second bookkeeping path.

Naming convention: dotted ``<lane>.<what>`` keys, lanes matching the
tracer's (``engine`` / ``ship`` / ``device`` / ``estimator`` plus
``collective`` / ``sanitize`` / ``obs``).

Counters are monotonic accumulators (``add``); gauges are
last-write-wins levels (``set``, plus ``set_max`` for high-water
marks); reservoirs are bounded sliding windows of observations with
quantile readout (``observe`` / ``quantile``) — the latency-shaped
metric the serve lane needs (p50/p99) that neither a counter nor a
gauge can express. All three are thread-safe and all follow the
``StageMetrics`` pickle precedent: the lock drops on the wire and is
recreated on arrival, values travel.
"""

from __future__ import annotations

import collections
import math
import threading
from typing import Dict, Union


class Counter:
    """Monotonic named accumulator."""

    # sparkdl-lint H3 contract: one counter is hit from every worker
    # thread — writes to value hold self._lock
    _lock_guards = ("value",)

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def add(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    # locks don't pickle; values travel (StageMetrics precedent)
    def __getstate__(self):
        state = self.__dict__.copy()
        del state["_lock"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()


class Gauge:
    """Last-write-wins named level (queue depth, cumulative totals
    published from per-object metrics)."""

    _lock_guards = ("value",)

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def set_max(self, value: float) -> None:
        """High-water-mark update: keep the larger of current/new."""
        with self._lock:
            self.value = max(self.value, float(value))

    def __getstate__(self):
        state = self.__dict__.copy()
        del state["_lock"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()


def nearest_rank(sorted_vals, q: float) -> float:
    """THE nearest-rank quantile over an ascending-sorted sequence —
    the one convention every latency readout in this repo shares
    (Reservoir quantiles, ``report --tails``, bench's ``"tails"``
    block), so the scraped p99, the attributed p99, and the gated p99
    cannot drift onto different math. Raises on an empty sequence —
    callers own their "no observations" semantics."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    if not sorted_vals:
        raise ValueError("nearest_rank needs at least one value")
    last = len(sorted_vals) - 1
    return sorted_vals[
        min(last, max(0, math.ceil(q * len(sorted_vals)) - 1))]


#: default Reservoir window (observations) — enough for a stable p99
#: under sustained load without unbounded growth
DEFAULT_RESERVOIR_CAPACITY = 4096

#: retained worst-case exemplars per reservoir — a scraped p99 needs a
#: handful of traceable specimens, not a second latency log
EXEMPLAR_CAPACITY = 8


class Reservoir:
    """Bounded sliding window of observations with nearest-rank
    quantile readout (request latencies, batch fill samples). Keeps the
    most recent ``capacity`` observations; ``count`` stays the lifetime
    total so a snapshot distinguishes "few samples" from "few
    retained".

    **Exemplars**: ``observe(value, exemplar={...})`` additionally
    offers a small payload (a request_id + phase breakdown) for
    worst-case retention — the :data:`EXEMPLAR_CAPACITY` largest
    recent values keep theirs, so a scraped p99 resolves to an actual
    request/trace instead of an anonymous number. Retention is a hard
    bound: candidates not retained (and retained ones displaced or
    aged out of the observation window) count in
    ``exemplars_dropped`` — the cardinality guard made visible, never
    an unbounded side-log."""

    # sparkdl-lint H3 contract: observations arrive from every caller
    # thread at once — writes to these hold self._lock
    _lock_guards = ("count", "exemplars_dropped")

    def __init__(self, name: str,
                 capacity: int = DEFAULT_RESERVOIR_CAPACITY):
        if capacity <= 0:
            raise ValueError(
                f"capacity must be positive, got {capacity}")
        self.name = name
        self.capacity = capacity
        self.count = 0
        self.exemplars_dropped = 0
        self._window: collections.deque = collections.deque(
            maxlen=capacity)
        # (value, lifetime seq, payload) — small (EXEMPLAR_CAPACITY),
        # scanned linearly per exemplar-carrying observe
        self._exemplars: list = []
        self._lock = threading.Lock()

    def observe(self, value: float, exemplar=None) -> None:
        with self._lock:
            self._window.append(float(value))
            self.count += 1
            if exemplar is not None:
                self._offer_exemplar(float(value), exemplar)

    def _offer_exemplar(self, value: float, payload) -> None:
        # holding self._lock. Age out exemplars whose observation left
        # the sliding window — a worst case from an hour ago must not
        # shadow the current tail
        horizon = self.count - self.capacity
        fresh = [e for e in self._exemplars if e[1] > horizon]
        # sparkdl-lint: allow[H3] -- observe() holds self._lock around every _offer_exemplar call (private helper, lock documented on the first line above)
        self.exemplars_dropped += len(self._exemplars) - len(fresh)
        self._exemplars = fresh
        if len(self._exemplars) < EXEMPLAR_CAPACITY:
            self._exemplars.append((value, self.count, payload))
            return
        worst_idx = min(range(len(self._exemplars)),
                        key=lambda i: self._exemplars[i][0])
        if value > self._exemplars[worst_idx][0]:
            self._exemplars[worst_idx] = (value, self.count, payload)
        # either the displaced retained exemplar or the rejected
        # candidate — one payload was discarded by the bound
        # sparkdl-lint: allow[H3] -- observe() holds self._lock around every _offer_exemplar call
        self.exemplars_dropped += 1

    def exemplars(self) -> list:
        """The retained worst-case exemplars, largest value first:
        ``[{**payload, "value": v}, ...]`` (``value`` is reserved —
        the observed number always wins a payload collision). The
        window horizon applies HERE too: plain ``observe()`` calls
        advance the window without touching the exemplar list, and an
        hour-old specimen must not be reported as the current tail
        once its observation has left the window."""
        with self._lock:
            horizon = self.count - self.capacity
            fresh = [e for e in self._exemplars if e[1] > horizon]
            self.exemplars_dropped += len(self._exemplars) - len(fresh)
            self._exemplars = fresh
            items = sorted(fresh, reverse=True,
                           key=lambda e: (e[0], e[1]))
        out = []
        for value, _seq, payload in items:
            d = dict(payload)
            d["value"] = value
            out.append(d)
        return out

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile over the retained window; 0.0 when no
        observations have been recorded (a snapshot must never raise)."""
        return self.quantiles((q,))[0]

    def quantiles(self, qs) -> tuple:
        """Several nearest-rank quantiles from ONE sorted snapshot of
        the window — readout paths that want p50 AND p99 (every
        publish) must not pay two O(n log n) sorts."""
        for q in qs:
            if not 0.0 <= q <= 1.0:
                raise ValueError(
                    f"quantile must be in [0, 1], got {q}")
        with self._lock:
            vals = sorted(self._window)
        if not vals:
            return tuple(0.0 for _ in qs)
        return tuple(nearest_rank(vals, q) for q in qs)

    # locks don't pickle; the retained window and lifetime count travel
    # (StageMetrics precedent)
    def __getstate__(self):
        state = self.__dict__.copy()
        del state["_lock"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()


class MetricsRegistry:
    """Thread-safe name → Counter/Gauge/Reservoir table with one flat
    ``snapshot()``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Union[Counter, Gauge, Reservoir]] = {}

    def counter(self, name: str) -> Counter:
        """The counter registered under ``name`` (created on first
        use). A name is one kind forever — re-requesting it as a gauge
        raises instead of silently forking the metric."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Counter(name)
            elif not isinstance(m, Counter):
                raise TypeError(
                    f"metric {name!r} is a {type(m).__name__}, "
                    "requested as Counter")
            return m

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Gauge(name)
            elif not isinstance(m, Gauge):
                raise TypeError(
                    f"metric {name!r} is a {type(m).__name__}, "
                    "requested as Gauge")
            return m

    def reservoir(self, name: str,
                  capacity: int = DEFAULT_RESERVOIR_CAPACITY
                  ) -> Reservoir:
        """The reservoir registered under ``name`` (created on first
        use; ``capacity`` applies only at creation). Same
        one-kind-forever contract as counter/gauge."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Reservoir(name, capacity)
            elif not isinstance(m, Reservoir):
                raise TypeError(
                    f"metric {name!r} is a {type(m).__name__}, "
                    "requested as Reservoir")
            return m

    def metrics(self) -> list:
        """The registered metric objects, sorted by name — the
        kind-preserving readout (``snapshot()`` flattens kinds away;
        the Prometheus renderer in :mod:`sparkdl_tpu.obs.export` needs
        them to emit correct ``# TYPE`` lines)."""
        with self._lock:
            return [self._metrics[name]
                    for name in sorted(self._metrics)]

    def snapshot(self) -> Dict[str, float]:
        """One flat {name: value} dict, sorted by name — the bench/CI
        contract (and what ``throughput_report`` renders from).
        Reservoirs flatten to ``<name>.count`` / ``.p50`` / ``.p99``
        derived keys so the snapshot stays one level deep."""
        with self._lock:
            metrics = [self._metrics[name]
                       for name in sorted(self._metrics)]
        out: Dict[str, float] = {}
        for m in metrics:
            if isinstance(m, Reservoir):
                # quantiles() takes the reservoir's own lock — computed
                # OUTSIDE the registry lock so a concurrent observe()
                # never waits on a snapshot render
                p50, p99 = m.quantiles((0.5, 0.99))
                out[f"{m.name}.count"] = float(m.count)
                out[f"{m.name}.p50"] = p50
                out[f"{m.name}.p99"] = p99
            else:
                out[m.name] = m.value
        return out

    def clear(self) -> None:
        """Drop every metric (test isolation)."""
        with self._lock:
            self._metrics.clear()

    # locks don't pickle; the metric objects carry their own
    # drop-and-recreate hooks, so values travel
    def __getstate__(self):
        state = self.__dict__.copy()
        del state["_lock"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()


_REGISTRY = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """THE process-wide registry the instrumented hot paths record
    into."""
    return _REGISTRY
