"""Process-wide metrics registry: named counters and gauges with ONE
``snapshot() -> dict`` for bench/CI.

The counters the pipeline already kept were siloed per object
(``RunnerMetrics`` on each runner, ``StageMetrics`` on each engine) and
the events that matter most to the link-bound north star — collective
launch-lock waits, prefetch queue depth, strategy degrades, sanitizer
arms — were counted nowhere. The registry is the single sink:

* hot-path events record directly into :func:`default_registry`
  (``collective.lock_wait_seconds``, ``ship.inflight`` /
  ``ship.inflight_peak``, ``ship.degrade_events``,
  ``sanitize.armed_runs`` / ``sanitize.degrade_events``);
* the existing per-object metrics publish INTO a registry on demand
  (``RunnerMetrics.publish`` → ``ship.*`` gauges,
  ``StageMetrics.publish`` → ``engine.stage.*`` gauges), which is how
  ``throughput_report`` and bench's ``"obs"`` block render — one
  snapshot, no second bookkeeping path.

Naming convention: dotted ``<lane>.<what>`` keys, lanes matching the
tracer's (``engine`` / ``ship`` / ``device`` / ``estimator`` plus
``collective`` / ``sanitize`` / ``obs``).

Counters are monotonic accumulators (``add``); gauges are
last-write-wins levels (``set``, plus ``set_max`` for high-water
marks). Both are thread-safe and both follow the ``StageMetrics``
pickle precedent: the lock drops on the wire and is recreated on
arrival, values travel.
"""

from __future__ import annotations

import threading
from typing import Dict, Union


class Counter:
    """Monotonic named accumulator."""

    # sparkdl-lint H3 contract: one counter is hit from every worker
    # thread — writes to value hold self._lock
    _lock_guards = ("value",)

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def add(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    # locks don't pickle; values travel (StageMetrics precedent)
    def __getstate__(self):
        state = self.__dict__.copy()
        del state["_lock"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()


class Gauge:
    """Last-write-wins named level (queue depth, cumulative totals
    published from per-object metrics)."""

    _lock_guards = ("value",)

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def set_max(self, value: float) -> None:
        """High-water-mark update: keep the larger of current/new."""
        with self._lock:
            self.value = max(self.value, float(value))

    def __getstate__(self):
        state = self.__dict__.copy()
        del state["_lock"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()


class MetricsRegistry:
    """Thread-safe name → Counter/Gauge table with one flat
    ``snapshot()``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Union[Counter, Gauge]] = {}

    def counter(self, name: str) -> Counter:
        """The counter registered under ``name`` (created on first
        use). A name is one kind forever — re-requesting it as a gauge
        raises instead of silently forking the metric."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Counter(name)
            elif not isinstance(m, Counter):
                raise TypeError(
                    f"metric {name!r} is a {type(m).__name__}, "
                    "requested as Counter")
            return m

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Gauge(name)
            elif not isinstance(m, Gauge):
                raise TypeError(
                    f"metric {name!r} is a {type(m).__name__}, "
                    "requested as Gauge")
            return m

    def snapshot(self) -> Dict[str, float]:
        """One flat {name: value} dict, sorted by name — the bench/CI
        contract (and what ``throughput_report`` renders from)."""
        with self._lock:
            return {name: self._metrics[name].value
                    for name in sorted(self._metrics)}

    def clear(self) -> None:
        """Drop every metric (test isolation)."""
        with self._lock:
            self._metrics.clear()

    # locks don't pickle; the metric objects carry their own
    # drop-and-recreate hooks, so values travel
    def __getstate__(self):
        state = self.__dict__.copy()
        del state["_lock"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()


_REGISTRY = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """THE process-wide registry the instrumented hot paths record
    into."""
    return _REGISTRY
