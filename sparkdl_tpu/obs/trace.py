"""Span tracer: one shared clock across engine → ship → device.

The pipeline's signals were fragmented — ``StageMetrics`` timed engine
stages, ``RunnerMetrics`` counted ship bytes, ``utils/profiling.trace``
wrapped ``jax.profiler``, and none of them shared a clock — so "the
link moved between measurements" stayed an anecdote (BENCH r05
race_note) instead of a diagnosable timeline. This module is the shared
clock: every layer records ``span(name, lane=...)`` intervals into ONE
process-wide bounded ring buffer, stamped with ``time.perf_counter()``
from a single epoch, exportable as Chrome/Perfetto trace-event JSON
(open ``Tracer.export``'s output in ``ui.perfetto.dev``).

Arming: ``SPARKDL_TPU_TRACE=1`` in the environment, or
``tracer().arm()`` programmatically (the override wins over the env).
Disarmed, ``span()`` returns one shared no-op context manager — no
allocation, no lock, no ring-buffer growth — so instrumentation can sit
permanently on the hot path (the overhead contract is pinned by
``tests/test_obs.py::test_disarmed_span_overhead``).

Lanes are the pipeline's layers, not threads: ``engine`` (decode /
stage execution / fragment cutting), ``ship`` (staging, dispatch,
device_put, the collective launch lock), ``device`` (the explicit
device_get drain — the only host-observable device-side edge),
``estimator`` (epoch/step loops). The export maps each lane to a
Perfetto process group and each OS thread to a track inside it.

Spans never run at jit trace time: the clock reads happen in host code
around the jitted call, and sparkdl-lint's H2 rule flags any
``span(...)`` placed inside a jit-traced function (it would read the
compile-time wall clock once and freeze it into the program).

Ring-buffer discipline: the buffer is bounded (``capacity`` ctor arg,
default ``SPARKDL_TPU_TRACE_BUFFER`` or 65536 spans); when full the
OLDEST spans evict and :attr:`Tracer.dropped` counts them — the export
carries a visible drop note, never a silent truncation.

Pickle discipline (the ``StageMetrics`` precedent): ``__getstate__``
drops the lock and the ring buffer — a tracer captured in a stage
closure ships armed-ness and capacity, and spans recorded on the
remote side stay remote (driver-side timelines are a LocalEngine
feature, like driver-side metrics).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

_TRUE = ("1", "true", "yes", "on")

#: ring-buffer capacity (spans) when SPARKDL_TPU_TRACE_BUFFER is unset
DEFAULT_CAPACITY = 65536

SpanRecord = collections.namedtuple(
    "SpanRecord",
    ["name", "lane", "thread_id", "thread_name", "start", "end",
     "attrs"])


class _NoopSpan:
    """The disarmed fast path: one shared instance, nothing allocated,
    nothing recorded."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NOOP = _NoopSpan()


class _Span:
    """An armed span: records (start, end, thread, attrs) on exit —
    including exceptional exit, tagged with the exception type, so a
    failed stage still shows up on the timeline."""

    __slots__ = ("_tracer", "_name", "_lane", "_attrs", "_start")

    def __init__(self, tracer: "Tracer", name: str, lane: str,
                 attrs: Dict[str, Any]):
        self._tracer = tracer
        self._name = name
        self._lane = lane
        self._attrs = attrs
        self._start = 0.0

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        end = time.perf_counter()
        attrs = self._attrs
        if exc_type is not None:
            attrs = dict(attrs, error=exc_type.__name__)
        self._tracer._record(self._name, self._lane, self._start, end,
                             attrs)
        return False


def _env_armed() -> bool:
    return os.environ.get("SPARKDL_TPU_TRACE", "").lower() in _TRUE


class Tracer:
    """Process-wide, thread-safe span recorder with a bounded ring
    buffer and Chrome/Perfetto trace-event export."""

    # sparkdl-lint H3 contract: spans arrive from every engine worker
    # thread at once — all buffer/counter writes hold self._lock
    _lock_guards = ("_appended",)

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            # the module-level singleton parses this at import time —
            # a config typo must degrade to the default, not make
            # `import sparkdl_tpu` unimportable for disarmed runs
            raw = os.environ.get("SPARKDL_TPU_TRACE_BUFFER", "")
            try:
                capacity = int(raw) if raw else DEFAULT_CAPACITY
                if capacity <= 0:
                    raise ValueError(capacity)
            except ValueError:
                import logging
                logging.getLogger(__name__).warning(
                    "SPARKDL_TPU_TRACE_BUFFER=%r is not a positive "
                    "int; using the default %d", raw, DEFAULT_CAPACITY)
                capacity = DEFAULT_CAPACITY
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = capacity
        # None → follow the env; True/False → programmatic override
        self._override: Optional[bool] = None
        self._lock = threading.Lock()
        self._buf: collections.deque = collections.deque(maxlen=capacity)
        self._appended = 0
        # the shared clock origin: every span's export timestamp is
        # microseconds since this instant
        self._epoch = time.perf_counter()

    # -- arming --------------------------------------------------------------

    @property
    def armed(self) -> bool:
        ov = self._override
        if ov is not None:
            return ov
        return _env_armed()

    def arm(self) -> None:
        """Record spans regardless of SPARKDL_TPU_TRACE."""
        self._override = True

    def disarm(self) -> None:
        """Stop recording regardless of SPARKDL_TPU_TRACE."""
        self._override = False

    def arm_from_env(self) -> None:
        """Drop any programmatic override; follow the env again."""
        self._override = None

    # -- recording -----------------------------------------------------------

    def span(self, name: str, lane: str = "host", **attrs):
        """Context manager timing the enclosed block into the ring
        buffer; a shared no-op when disarmed."""
        if not self.armed:
            return _NOOP
        return _Span(self, name, lane, attrs)

    def _record(self, name: str, lane: str, start: float, end: float,
                attrs: Dict[str, Any]) -> None:
        t = threading.current_thread()
        rec = SpanRecord(name, lane, t.ident, t.name, start, end, attrs)
        with self._lock:
            self._buf.append(rec)  # deque(maxlen) evicts the oldest
            self._appended += 1

    # -- inspection ----------------------------------------------------------

    def spans(self) -> List[SpanRecord]:
        """The retained spans, oldest first."""
        with self._lock:
            return list(self._buf)

    @property
    def dropped(self) -> int:
        """Spans evicted by the ring buffer since the last clear() —
        the no-silent-truncation counter."""
        with self._lock:
            return self._appended - len(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._appended = 0

    # -- export --------------------------------------------------------------

    def trace_events(self) -> List[dict]:
        """The retained spans as a Chrome trace-event list: one
        Perfetto process group per lane, one track per OS thread,
        complete ("X") events in microseconds since the tracer epoch,
        plus a visible drop-note instant when the ring buffer evicted
        anything.

        **Flow events (span links)**: a span recorded with the
        reserved attrs ``flow_id`` (one id) or ``flow_ids`` (several)
        plus ``flow_ph`` (``"s"`` start / ``"t"`` step / ``"f"`` end)
        additionally emits Chrome flow events bound to its slice
        (same ts/pid/tid; steps and ends bind to the enclosing slice
        via ``bp: "e"``). The serve layer keys these by request_id, so
        a request split across N micro-batches renders in Perfetto as
        ONE connected flow: enqueue → each dispatch → resolution. The
        reserved attrs are consumed here — they do not appear in the
        exported slice args (``request_id`` is set separately where a
        visible arg is wanted)."""
        recs = self.spans()
        dropped = self.dropped
        lanes = sorted({r.lane for r in recs})
        pid_of = {lane: i + 1 for i, lane in enumerate(lanes)}
        events: List[dict] = []
        for lane in lanes:
            events.append({"name": "process_name", "ph": "M",
                           "pid": pid_of[lane], "tid": 0,
                           "args": {"name": lane}})
        named_threads = set()
        for r in recs:
            pid = pid_of[r.lane]
            key = (pid, r.thread_id)
            if key not in named_threads:
                named_threads.add(key)
                events.append({"name": "thread_name", "ph": "M",
                               "pid": pid, "tid": r.thread_id,
                               "args": {"name": r.thread_name}})
            args = dict(r.attrs)
            flow_ph = args.pop("flow_ph", None)
            flow_ids = args.pop("flow_ids", None)
            flow_id = args.pop("flow_id", None)
            ts = round((r.start - self._epoch) * 1e6, 3)
            dur = round(max(r.end - r.start, 0.0) * 1e6, 3)
            events.append({
                "name": r.name, "cat": r.lane, "ph": "X",
                "ts": ts,
                "dur": dur,
                "pid": pid, "tid": r.thread_id,
                "args": args,
            })
            if flow_ph in ("s", "t", "f"):
                ids = (list(flow_ids) if flow_ids
                       else [flow_id] if flow_id is not None else [])
                # flow events of one id must be in timestamp order:
                # starts/steps stamp at their slice's start, the END
                # stamps at its slice's end — the request span opens
                # at submit time, so an end at its start would precede
                # the enqueue start and break the chain
                fts = ts + dur if flow_ph == "f" else ts
                for fid in ids:
                    flow = {"name": "request", "cat": "request_flow",
                            "ph": flow_ph, "id": str(fid), "ts": fts,
                            "pid": pid, "tid": r.thread_id}
                    if flow_ph != "s":
                        # bind to the enclosing slice, not the next
                        # one to start (Chrome trace-format contract)
                        flow["bp"] = "e"
                    events.append(flow)
        if dropped:
            events.append({
                "name": f"ring buffer dropped {dropped} oldest spans "
                        f"(capacity {self.capacity}; raise "
                        "SPARKDL_TPU_TRACE_BUFFER)",
                "ph": "i", "s": "g", "ts": 0.0, "pid": 0, "tid": 0,
                "args": {"dropped": dropped}})
        if self is _TRACER:
            # ONE merged timeline is the whole point: the process-wide
            # export additionally carries the spans pipeline worker
            # processes shipped through the cross-process telemetry
            # plane, clock-aligned onto THIS tracer's epoch, each
            # worker on its own process track (obs/remote.py; lanes
            # claim small pids, workers claim WORKER_PID_BASE+i, so
            # the two families cannot collide)
            try:
                from sparkdl_tpu.obs import remote
                events.extend(
                    remote.aggregator().trace_events(self._epoch))
            # sparkdl-lint: allow[H12] -- the parent-side trace must export even if the remote merge breaks; aggregator ingest errors are already counted (worker.ingest_errors)
            except Exception:
                import logging
                logging.getLogger(__name__).exception(
                    "trace export: worker-span merge failed; exporting "
                    "parent spans only")
        return events

    def export(self, path: str) -> int:
        """Write the trace-event JSON list to ``path`` (loadable in
        ui.perfetto.dev / chrome://tracing); returns the span count."""
        events = self.trace_events()
        with open(path, "w", encoding="utf-8") as f:
            json.dump(events, f, default=str)
        return sum(1 for e in events if e.get("ph") == "X")

    # -- pickle discipline (StageMetrics precedent) --------------------------

    def __getstate__(self):
        state = self.__dict__.copy()
        del state["_lock"]
        del state["_buf"]          # remote-side spans stay remote
        del state["_appended"]
        del state["_epoch"]        # perf_counter origins are per-process
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()
        self._buf = collections.deque(maxlen=self.capacity)
        self._appended = 0
        self._epoch = time.perf_counter()


_TRACER = Tracer()


def tracer() -> Tracer:
    """THE process-wide tracer every instrumented layer records into
    (one shared clock is the whole point)."""
    return _TRACER


def span(name: str, lane: str = "host", **attrs):
    """Module-level shorthand for ``tracer().span(...)`` — the form the
    instrumented hot paths use. Disarmed it returns one shared no-op
    object: no allocation, no lock."""
    t = _TRACER
    if not t.armed:
        return _NOOP
    return _Span(t, name, lane, attrs)


def timed_device_get(value):
    """THE instrumented drain: every runner strategy funnels its
    device→host result syncs through this one call (``SlabSink.write``
    delegates here), so the stall the overlap strategies exist to hide
    shows up as a ``device_get`` span on the ``device`` lane. Returns
    ``(host_value, seconds)`` — ONE pair of clock reads feeds both the
    span and the caller's accounting (``transfer_wait_seconds``), so
    the printed and traced numbers cannot drift. The explicit transfer
    stays legal under ``SPARKDL_TPU_SANITIZE=1``'s transfer guard (the
    guard bans implicit transfers only) and is H1-allowlisted as the
    drain path's single blessed sync."""
    import jax

    t = _TRACER
    t0 = time.perf_counter()
    host = jax.device_get(value)
    end = time.perf_counter()
    if t.armed:
        t._record("device_get", "device", t0, end, {})
    return host, end - t0
