"""Cross-process telemetry plane: worker-side agents, a parent-side
aggregator, and a clock-aligned trace merge.

PR 14's pipeline worker processes were observability blind spots: only
busy-seconds folded back to the parent, while worker spans, registry
counters, watchdog verdicts, ``warn_once`` degrade events, and
fault-injection state all died with the worker. That is untenable for
ROADMAP item 1 (pod-scale execution needs the same stall/bound/failure
story a local run has) and item 3 (the tf.data-service shape —
PAPERS.md, arxiv 2101.12127 — disaggregates input processing onto
remote worker fleets, which only works with per-worker telemetry
flowing to one aggregation point). This module is that plane:

* :class:`TelemetryAgent` — ONE per worker process, armed from the
  parent's shipped config (``telemetry_config()``) at first armed
  task. It arms the worker's own tracer/watchdog/fault harness, then
  ``cut_frame()`` packages everything recorded since the last cut —
  span records, registry counter DELTAS and changed gauges, the
  watchdog verdict, captured ``warn_once`` degrade events, and
  ``faults.state()`` — as one plain-picklable frame riding the
  existing result-pipe/shm hand-off (``data/pipeline.py`` appends it
  to the task result tuple). The frame is the generalizable transport
  seam: ROADMAP item 3's socket workers ship the same dict over a
  socket instead of a pipe, and the aggregator cannot tell the
  difference.
* :class:`TelemetryAggregator` — ONE per parent process
  (:func:`aggregator`). ``ingest(frame)`` (a) stores worker spans in a
  bounded per-worker ring for the clock-aligned trace merge, (b) folds
  worker counters into the registry under the bounded
  ``worker.<i>.*`` namespace (``<i>`` is the worker SLOT index,
  bounded by the pool size — never a request id; rule H6) plus
  ``worker.all.*`` rollup totals, (c) dedupes degrade warnings across
  processes (ONE parent log line per reason, per-worker counts
  preserved), and (d) folds worker watchdog verdicts into the health
  surface — a worker-reported stall reaches ``/healthz`` 503 detail
  and triggers a flight dump.

**Clock alignment** (the handshake): every frame carries a
``(unix_time, perf_counter)`` pair sampled in the worker at cut time;
the aggregator samples its own pair at ingest. Since both processes
share one wall clock, a worker ``perf_counter`` value maps onto the
parent's ``perf_counter`` timeline as::

    offset = (worker_unix - worker_pc) - (parent_unix - parent_pc)
    parent_equivalent_pc = worker_pc_value + offset

so worker spans land time-aligned next to parent ship/device spans in
ONE merged Perfetto trace (``Tracer.trace_events`` pulls
:meth:`TelemetryAggregator.trace_events`), each worker on its own
process track (pid ``WORKER_PID_BASE + index``).

Arming: the plane follows the armed obs layers — the parent ships a
non-``None`` config when the tracer, the watchdog, or the fault
harness is armed. ``SPARKDL_TPU_REMOTE_TELEMETRY=1`` forces it on
(workers trace even when the parent runs dark), ``=0`` pins it off.
Disarmed, the whole plane is one ``None`` check per task on both
sides, and the fragment hand-off carries ZERO extra bytes — the
result tuples keep their exact pre-telemetry shapes.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

from sparkdl_tpu.obs.registry import Counter, Gauge, default_registry

logger = logging.getLogger(__name__)

_TRUE = ("1", "true", "yes", "on")
_FALSE = ("0", "false", "no", "off")

#: frame format tag — bump when the frame layout changes incompatibly
FRAME_SCHEMA = 1

#: env override for the whole plane: "1" forces it on even with the
#: tracer/watchdog disarmed, "0" pins it off; unset follows the armed
#: obs layers (docs/OBSERVABILITY.md "Cross-process telemetry")
ENV_REMOTE = "SPARKDL_TPU_REMOTE_TELEMETRY"

#: retained spans per worker at the parent — bounded ring, evictions
#: counted (the tracer's no-silent-truncation discipline)
WORKER_SPAN_CAPACITY = 4096

#: Perfetto pid base for worker process groups: the parent tracer's
#: lanes occupy small pids (1..N); workers land at BASE + slot index
#: so the two families can never collide
WORKER_PID_BASE = 1000

#: retained degrade events / last-spans per worker in status views
_STATUS_TAIL = 8


def telemetry_config() -> Optional[dict]:
    """The config the parent ships to worker processes — ``None`` when
    the plane is disarmed (THE fast-path check: a ``None`` config
    means the task tuple gains no frame and the worker arms nothing).
    Armed when the parent tracer, watchdog, or fault harness is armed,
    or when :data:`ENV_REMOTE` forces it; ``SPARKDL_TPU_REMOTE_TELEMETRY=0``
    pins the plane off regardless."""
    raw = os.environ.get(ENV_REMOTE, "").strip().lower()
    if raw in _FALSE and raw:
        return None
    from sparkdl_tpu.obs.trace import tracer
    from sparkdl_tpu.obs.watchdog import watchdog
    from sparkdl_tpu.resilience import faults

    trc, wd = tracer(), watchdog()
    forced = raw in _TRUE
    if not (forced or trc.armed or wd.armed or faults.armed()):
        return None
    return {
        "v": FRAME_SCHEMA,
        "trace": bool(trc.armed or forced),
        "watchdog": wd.armed,
        "threshold_s": wd.threshold_s,
        "faults": faults.spec() or None,
    }


class TelemetryAgent:
    """The worker-process side: arms the worker's obs layers from the
    parent's config and cuts plain-picklable frames (module
    docstring). One per worker process (:func:`worker_agent`);
    standalone instances exist for tests."""

    # sparkdl-lint H3 contract: a pool worker is single-threaded today,
    # but the socket-worker reuse (ROADMAP item 3) is not — buffer
    # writes hold self._lock
    _lock_guards = ("_degrades", "_counter_base", "_gauge_base",
                    "frames")

    def __init__(self, config: dict):
        self.config = dict(config)
        self._lock = threading.Lock()
        self._degrades: List[Tuple[str, str]] = []
        self._counter_base: Dict[str, float] = {}
        self._gauge_base: Dict[str, float] = {}
        self.frames = 0
        self._apply(self.config)

    def _apply(self, config: dict) -> None:
        """Arm the worker's obs layers per the parent's config, then
        zero the baselines: a fork-started worker inherits the
        parent's span ring and counter values, and shipping those back
        would double-count everything the parent already has."""
        from sparkdl_tpu.obs.trace import tracer
        from sparkdl_tpu.obs.watchdog import watchdog
        from sparkdl_tpu.resilience import faults

        trc = tracer()
        if config.get("trace"):
            trc.arm()  # sparkdl-lint: allow[H11] -- armed for the worker PROCESS's whole life by design: spans buffer until each frame cut, and the arm state dies with the process (pool shutdown)
        trc.clear()                      # drop fork-inherited spans
        if config.get("watchdog"):
            threshold = config.get("threshold_s")
            threshold = (threshold if threshold and threshold > 0
                         else None)
            watchdog().arm(threshold_s=threshold)  # sparkdl-lint: allow[H11] -- process-lifetime arm mirroring the parent's watchdog config; verdicts ship per frame and the state dies with the worker process
        spec = config.get("faults")
        if spec:
            faults.arm_spec(spec)
        self._rebase()

    def refit(self, config: dict) -> None:
        """Apply a NEW stream's config to a persistent pool worker:
        only the fault spec is live-switchable (a drill armed or
        disarmed between streams must reach workers that already
        exist); trace/watchdog arming is latched at agent creation.
        Baselines are NOT rebased — the counter deltas of whatever the
        worker did between frames still ship."""
        from sparkdl_tpu.resilience import faults

        spec = config.get("faults") or None
        if spec == (self.config.get("faults") or None):
            return
        if spec:
            faults.arm_spec(spec)
        else:
            faults.disarm()
        self.config["faults"] = spec

    def _rebase(self) -> None:
        counters, gauges = _registry_values()
        with self._lock:
            self._counter_base = counters
            self._gauge_base = gauges

    def capture_degrade(self, reason: str, message: str) -> bool:
        """Buffer one ``warn_once`` degrade event for the next frame;
        returns True (captured — the caller suppresses its local log
        so the PARENT emits the one deduped line)."""
        with self._lock:
            self._degrades.append((str(reason), str(message)))
        return True

    def cut_frame(self) -> dict:
        """Everything recorded since the last cut, as one
        plain-picklable dict — the transport payload the task result
        carries back (or a socket worker ships verbatim)."""
        from sparkdl_tpu.obs.trace import tracer
        from sparkdl_tpu.obs.watchdog import watchdog
        from sparkdl_tpu.resilience import faults

        trc = tracer()
        recs = trc.spans()
        dropped = trc.dropped
        trc.clear()
        spans = [(r.name, r.lane, r.thread_id, r.thread_name,
                  r.start, r.end, dict(r.attrs)) for r in recs]
        counters, gauges = _registry_values()
        with self._lock:
            # `worker.*` is the PARENT-side mirror namespace — it can
            # only appear here when the agent shares a registry with
            # an aggregator (an in-process DecodeServer). Shipping it
            # would re-mirror the mirror on every ingest
            # (worker.0.worker.0.…, unbounded key growth), so a
            # frame never carries it
            counter_deltas = {
                k: v - self._counter_base.get(k, 0.0)
                for k, v in counters.items()
                if v != self._counter_base.get(k, 0.0)
                and not k.startswith("worker.")}
            changed_gauges = {
                k: v for k, v in gauges.items()
                if v != self._gauge_base.get(k)
                and not k.startswith("worker.")}
            self._counter_base = counters
            self._gauge_base = gauges
            degrades, self._degrades = self._degrades, []
            self.frames += 1
        wd = watchdog()
        return {
            "v": FRAME_SCHEMA,
            "pid": os.getpid(),
            # the clock-handshake pair: wall time is the ONLY bridge
            # between per-process perf_counter origins; all span math
            # stays on perf_counter deltas
            "clock": (time.time(),  # sparkdl-lint: allow[H5] -- cross-process clock handshake: the wall stamp is the alignment bridge, not span/latency math
                      time.perf_counter()),
            "spans": spans,
            "spans_dropped": dropped,
            "counters": counter_deltas,
            "gauges": changed_gauges,
            "watchdog": wd.verdict() if wd.armed else None,
            "degrades": degrades,
            "faults": faults.state() if faults.armed() else None,
        }

    # locks don't pickle (H3); config travels, buffers stay local
    def __getstate__(self):
        state = self.__dict__.copy()
        del state["_lock"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()


def _registry_values() -> Tuple[Dict[str, float], Dict[str, float]]:
    """(counters, gauges) value maps from the process registry —
    kind-split because only counters difference meaningfully
    (reservoirs stay worker-local: quantiles don't delta)."""
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    for m in default_registry().metrics():
        if isinstance(m, Counter):
            counters[m.name] = m.value
        elif isinstance(m, Gauge):
            gauges[m.name] = m.value
    return counters, gauges


#: the one worker-process agent; ``None`` = disarmed (THE fast-path
#: check for capture_degrade and the task's frame append)
_AGENT: Optional[TelemetryAgent] = None


def worker_agent(config: dict) -> TelemetryAgent:
    """This process's agent, created (and armed) on first call — the
    pool task's entry point. Later calls return the existing agent
    (pool workers persist across streams), re-applying only the fault
    spec when a new stream's config changed it (:meth:`refit`)."""
    global _AGENT
    agent = _AGENT
    if agent is None:
        agent = _AGENT = TelemetryAgent(config)
    else:
        agent.refit(config)
    return agent


def capture_degrade(reason: str, message: str) -> bool:
    """The ``warn_once`` hook (runtime/runner.py, data/pipeline.py):
    with an armed worker agent the degrade event ships to the parent
    (which logs it ONCE across all workers) and this returns True so
    the caller suppresses its local log. Disarmed — every parent
    process, every disarmed worker — one global ``None`` check,
    returns False, the caller logs exactly as before."""
    agent = _AGENT
    if agent is None:
        return False
    return agent.capture_degrade(reason, message)


class TelemetryAggregator:
    """The parent side: worker-frame ingest, counter folding, degrade
    dedup, health verdicts, and the clock-aligned trace merge (module
    docstring). One per process (:func:`aggregator`); standalone
    instances exist for tests."""

    # sparkdl-lint H3 contract: frames arrive on the pipeline consumer
    # thread while /statusz, /healthz, flight dumps, and trace exports
    # read concurrently — ALL worker-table state holds self._lock
    _lock_guards = ("_workers", "_warned", "frames")

    def __init__(self):
        self._lock = threading.Lock()
        # pid -> slot dict; insertion order IS the worker index
        # (bounded by the pool size — the H6 cardinality argument)
        self._workers: "OrderedDict[int, Dict[str, Any]]" = OrderedDict()
        self._warned: set = set()
        self.frames = 0

    # -- ingest ---------------------------------------------------------------

    def ingest(self, frame: Optional[dict]) -> None:
        """Fold one worker frame in. Never raises — a malformed frame
        must not fail the fragment it rode with (counted in
        ``worker.ingest_errors``, the H12 accounting)."""
        if not isinstance(frame, dict):
            return
        try:
            self._ingest(frame)
        except Exception:
            default_registry().counter("worker.ingest_errors").add()
            logger.exception("remote telemetry: worker frame ingest "
                             "failed")

    def _slot_locked(self, pid: int) -> Dict[str, Any]:
        slot = self._workers.get(pid)  # sparkdl-lint: allow[H17] -- _locked-suffix helper: the sole caller (_ingest) holds self._lock around the call; a truncated (--changed-only) callgraph cannot see that proof
        if slot is None:
            slot = self._workers[pid] = {  # sparkdl-lint: allow[H17] -- same _locked contract: caller holds self._lock
                "index": len(self._workers),  # sparkdl-lint: allow[H17] -- same _locked contract: caller holds self._lock
                "pid": pid,
                "frames": 0,
                "clock": None,          # (worker_unix, worker_pc)
                "parent_clock": None,   # (parent_unix, parent_pc)
                "spans": deque(maxlen=WORKER_SPAN_CAPACITY),
                "spans_dropped": 0,
                "span_evictions": 0,
                "counters": {},
                "watchdog": None,
                "stalls_seen": 0,
                "stalled": False,
                "faults": None,
                "degrades": deque(maxlen=_STATUS_TAIL),
                "last_seen_unix": None,
                "dead": False,
                "retired": False,
                "death_reason": None,
            }
        return slot

    def _ingest(self, frame: dict) -> None:
        reg = default_registry()
        pid = int(frame.get("pid", 0))
        parent_pair = (
            time.time(),  # sparkdl-lint: allow[H5] -- the parent half of the clock handshake (wall bridge), not span/latency math
            time.perf_counter())
        counters = frame.get("counters") or {}
        gauges = frame.get("gauges") or {}
        spans = frame.get("spans") or []
        degrades = frame.get("degrades") or []
        verdict = frame.get("watchdog")
        new_stalls = 0
        fresh_warns: List[Tuple[int, str, str]] = []
        with self._lock:
            slot = self._slot_locked(pid)
            idx = slot["index"]
            slot["frames"] += 1
            # a frame is proof of life: a reused slot (pool rebuild
            # landing on the same pid) sheds any stale verdict
            slot["dead"] = False
            slot["retired"] = False
            slot["death_reason"] = None
            clock = frame.get("clock")
            if (isinstance(clock, (tuple, list)) and len(clock) == 2):
                slot["clock"] = (float(clock[0]), float(clock[1]))
                slot["parent_clock"] = parent_pair
            slot["last_seen_unix"] = parent_pair[0]
            before = len(slot["spans"])
            for rec in spans:
                slot["spans"].append(tuple(rec))
            overflow = before + len(spans) - len(slot["spans"])
            slot["span_evictions"] += max(0, overflow)
            slot["spans_dropped"] += int(frame.get("spans_dropped", 0)
                                         or 0)
            for key, delta in counters.items():
                slot["counters"][key] = \
                    slot["counters"].get(key, 0.0) + float(delta)
            if verdict is not None:
                slot["watchdog"] = verdict
                fired = int(verdict.get("stalls_fired", 0) or 0)
                new_stalls = max(0, fired - slot["stalls_seen"])
                slot["stalls_seen"] = max(slot["stalls_seen"], fired)
                slot["stalled"] = bool(verdict.get("stalled_sources"))
            if frame.get("faults") is not None:
                slot["faults"] = frame["faults"]
            for reason, message in degrades:
                if reason not in self._warned:
                    self._warned.add(reason)
                    fresh_warns.append((idx, pid, message))
            self.frames += 1
        # registry folding + logging OUTSIDE the lock (counter adds
        # take their own locks; a flight dump re-enters workers_status)
        reg.counter("worker.frames").add()
        for key, delta in counters.items():
            # bounded key family: <idx> is the worker slot index
            # (pool-size bounded), <key> the worker's own documented
            # registry key — rules H6/H9, docs/OBSERVABILITY.md
            reg.counter(f"worker.{idx}.{key}").add(float(delta))
            reg.counter(f"worker.all.{key}").add(float(delta))
        for key, value in gauges.items():
            reg.gauge(f"worker.{idx}.{key}").set(float(value))
        for _ in range(len(degrades)):
            reg.counter(f"worker.{idx}.degrade_events").add()
            reg.counter("worker.all.degrade_events").add()
        for widx, wpid, message in fresh_warns:
            logger.warning("worker %d (pid %d): %s", widx, wpid,
                           message)
        if new_stalls:
            reg.counter("worker.stalls").add(new_stalls)
            logger.error(
                "remote telemetry: worker %d (pid %d) reported %d "
                "watchdog stall(s) from its own monitor — sources: %s",
                idx, pid, new_stalls,
                (verdict or {}).get("stalled_sources"))
            self._dump_flight(
                f"worker stall: worker {idx} (pid {pid}) reported "
                f"{new_stalls} stall(s) from its own watchdog")

    def _dump_flight(self, reason: str) -> None:
        try:
            from sparkdl_tpu.obs import flight
            rec = flight.recorder()
            if rec.armed:
                rec.dump(reason=reason)
        # sparkdl-lint: allow[H12] -- the stall/death IS accounted (worker.stalls / pipeline.worker_deaths counters + ERROR log fired before this call); the dump is best-effort forensics on top
        except Exception:
            logger.exception("remote telemetry: flight dump failed")

    # -- worker death ---------------------------------------------------------

    def note_pool_broken(self, reason: str) -> List[int]:
        """Called when the process pool breaks (a worker process
        died): probe every known worker pid, mark the gone ones dead,
        count ``pipeline.worker_deaths``, and (armed) dump a flight
        bundle whose ``workers[]`` section names the corpse. Returns
        the newly-dead worker indexes."""
        dead: List[Tuple[int, int]] = []
        with self._lock:
            # retired slots are workers a CLEAN pool shutdown already
            # reaped (note_pool_retired) — their exit is not a death
            probe = [(slot["index"], pid, slot)
                     for pid, slot in self._workers.items()
                     if not slot["dead"] and not slot["retired"]]
        for idx, pid, slot in probe:
            alive = True
            try:
                os.kill(pid, 0)
            except OSError:
                alive = False
            if alive:
                continue
            with self._lock:
                if slot["dead"]:
                    continue
                slot["dead"] = True
                slot["death_reason"] = reason
            dead.append((idx, pid))
        reg = default_registry()
        for idx, pid in dead:
            reg.counter("pipeline.worker_deaths").add()
            logger.error(
                "remote telemetry: worker %d (pid %d) is DEAD — %s",
                idx, pid, reason)
        if dead:
            names = ", ".join(f"worker {i} (pid {p})" for i, p in dead)
            self._dump_flight(f"pipeline worker death: {names} — "
                              f"{reason}")
        return [idx for idx, _pid in dead]

    def note_pool_retired(self, pids: Optional[List[int]] = None
                          ) -> None:
        """Called on a CLEAN pool shutdown/resize: mark the named
        worker pids (or, with ``None``, every live slot) retired so a
        LATER pool break doesn't probe their reaped pids and
        misattribute the clean exits as deaths. No counter, no dump —
        retirement is the normal lifecycle."""
        with self._lock:
            wanted = None if pids is None else set(pids)
            for pid, slot in self._workers.items():
                if slot["dead"] or slot["retired"]:
                    continue
                if wanted is None or pid in wanted:
                    slot["retired"] = True

    # -- the merged trace -----------------------------------------------------

    def trace_events(self, epoch: float) -> List[dict]:
        """The retained worker spans as Chrome trace events on the
        PARENT timeline: one process group per worker (pid
        ``WORKER_PID_BASE + index``), timestamps converted through the
        per-worker clock handshake so they land microsecond-aligned
        next to parent spans exported against ``epoch``
        (``Tracer.trace_events`` calls this for the merge)."""
        with self._lock:
            snap = [(s["index"], pid, s["clock"], s["parent_clock"],
                     list(s["spans"]), s["spans_dropped"]
                     + s["span_evictions"], s["dead"])
                    for pid, s in self._workers.items()]
        events: List[dict] = []
        for idx, pid, clock, parent_clock, spans, dropped, dead in snap:
            if clock is None or parent_clock is None:
                continue
            wpid = WORKER_PID_BASE + idx
            offset = ((clock[0] - clock[1])
                      - (parent_clock[0] - parent_clock[1]))
            name = f"worker.{idx} (pid {pid})"
            if dead:
                name += " [DEAD]"
            events.append({"name": "process_name", "ph": "M",
                           "pid": wpid, "tid": 0,
                           "args": {"name": name}})
            named_threads = set()
            for rec in spans:
                sname, lane, tid, tname, start, end, attrs = rec
                tid = int(tid or 0)
                if tid not in named_threads:
                    named_threads.add(tid)
                    events.append({"name": "thread_name", "ph": "M",
                                   "pid": wpid, "tid": tid,
                                   "args": {"name": tname}})
                ts = round((start + offset - epoch) * 1e6, 3)
                dur = round(max(end - start, 0.0) * 1e6, 3)
                events.append({
                    "name": sname, "cat": lane, "ph": "X",
                    "ts": ts, "dur": dur,
                    "pid": wpid, "tid": tid,
                    "args": dict(attrs, worker=idx),
                })
            if dropped:
                events.append({
                    "name": f"worker.{idx} dropped {dropped} spans "
                            "(worker ring + parent retention bounds)",
                    "ph": "i", "s": "g", "ts": 0.0, "pid": wpid,
                    "tid": 0, "args": {"dropped": dropped,
                                       "worker": idx}})
        return events

    # -- health + status ------------------------------------------------------

    def health(self) -> Dict[str, Any]:
        """The rolled-up worker health verdict for ``/healthz``: which
        workers' OWN watchdogs report a live stall, and which are
        dead."""
        with self._lock:
            stalled = [f"worker.{s['index']}"
                       for s in self._workers.values() if s["stalled"]]
            dead = [f"worker.{s['index']}"
                    for s in self._workers.values() if s["dead"]]
            return {"workers": len(self._workers),
                    "stalled": sorted(stalled), "dead": sorted(dead)}

    def workers_status(self) -> List[dict]:
        """The per-worker ``workers[]`` section — ONE shape shared by
        the flight bundle, ``/statusz``, and ``report --workers``:
        agent state, last spans, counter snapshot, fault config."""
        with self._lock:
            snap = [dict(slot, spans=list(slot["spans"]),
                         degrades=list(slot["degrades"]))
                    for slot in self._workers.values()]
        out = []
        for s in sorted(snap, key=lambda d: d["index"]):
            last_spans = [
                {"name": rec[0], "lane": rec[1],
                 "dur_ms": round(max(rec[5] - rec[4], 0.0) * 1e3, 3)}
                for rec in s["spans"][-_STATUS_TAIL:]]
            out.append({
                "index": s["index"],
                "pid": s["pid"],
                "frames": s["frames"],
                "last_seen_unix": s["last_seen_unix"],
                "dead": s["dead"],
                "retired": s["retired"],
                "death_reason": s["death_reason"],
                "stalled": s["stalled"],
                "spans_retained": len(s["spans"]),
                "spans_dropped": s["spans_dropped"]
                + s["span_evictions"],
                "watchdog": s["watchdog"],
                "faults": s["faults"],
                "degrades": [{"reason": r, "message": m}
                             for r, m in s["degrades"]],
                "counters": {k: v
                             for k, v in sorted(s["counters"].items())},
                "last_spans": last_spans,
            })
        return out

    def clear(self) -> None:
        """Drop every worker slot and the degrade-dedup set (test
        isolation; registry mirrors are the registry's to clear)."""
        with self._lock:
            self._workers.clear()
            self._warned.clear()
            self.frames = 0

    # locks don't pickle (H3); the worker table is process-local
    def __getstate__(self):
        state = self.__dict__.copy()
        del state["_lock"]
        del state["_workers"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()
        self._workers = OrderedDict()


_AGGREGATOR = TelemetryAggregator()


def aggregator() -> TelemetryAggregator:
    """THE parent-process aggregator every transport feeds (the
    pipeline's frame demux today, socket workers tomorrow)."""
    return _AGGREGATOR
