"""Scrapeable health surface: Prometheus text + ``/healthz`` +
``/statusz`` from a localhost stdlib HTTP server.

The registry (PR 3) and the serve metrics (PR 4) made the pipeline's
numbers *recordable*; this module makes them *operable*: a CI soak, a
curl, or a Prometheus scraper can watch a live process without any
in-process hook. Three endpoints, one tiny threading HTTP server
(stdlib only — no new dependency, bound to localhost by default):

* ``/metricsz`` — ``MetricsRegistry`` rendered as Prometheus text
  exposition format (``# TYPE`` per metric; counters stay counters,
  gauges gauges, reservoirs flatten to ``_p50``/``_p99`` gauges plus a
  ``_count`` counter — same flattening as ``snapshot()``).
* ``/healthz`` — liveness (the server answering IS the liveness bit)
  plus the stall watchdog's verdict: 200 while healthy, 503 with the
  stalled sources named once the watchdog flags a wedge.
* ``/statusz`` — operator JSON: uptime, platform, watchdog verdict,
  flight-recorder state, and per-model serve state (warmup, queue
  depth, fill ratio) for every attached/registered ``ModelServer``.

Attach it to a server (``ModelServer.serve_telemetry(port=...)``) or
run it standalone around batch runs (:func:`start_telemetry`) — the
registry is process-wide either way, so a standalone endpoint still
sees every ship/collective/sanitize counter. ``port=0`` (the default)
lets the OS pick; read ``TelemetryServer.port`` after ``start()``.

Clocks are ``perf_counter`` deltas only (uptime) — sparkdl-lint H5.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from sparkdl_tpu.obs import flight as _flight
from sparkdl_tpu.obs.registry import (
    Counter,
    Gauge,
    MetricsRegistry,
    Reservoir,
    default_registry,
)
from sparkdl_tpu.obs.watchdog import watchdog

logger = logging.getLogger(__name__)

#: every exported sample is prefixed so a shared Prometheus namespace
#: can tell this process's pipeline metrics from anyone else's
PROM_PREFIX = "sparkdl_"

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(name: str) -> str:
    """A registry key as a legal Prometheus metric name: dots (and any
    other illegal byte) become underscores, and the ``sparkdl_`` prefix
    guarantees a legal leading character."""
    return PROM_PREFIX + _PROM_BAD.sub("_", name)


#: ``# HELP`` text by registry-key prefix (longest prefix wins; the
#: registry's dotted ``<lane>.<what>`` convention makes the lane the
#: help unit — per-key prose lives in docs/OBSERVABILITY.md's table,
#: which sparkdl-lint H9 keeps in sync with the code)
HELP_BY_PREFIX = (
    ("ledger.util.", "live-roofline utilization fraction for this "
                     "pipeline lane, per ledger window (obs/ledger.py)"),
    ("ledger.", "windowed utilization-ledger accounting — the live "
                "bottleneck verdict and its bookkeeping (obs/ledger.py)"),
    ("ship.ring_", "device-resident infeed ring: slot hits/misses, "
                   "donation stream-throughs, degrade events "
                   "(runtime/runner.py InfeedRing)"),
    ("ship.", "host->device ship path: dispatch queue, staging copies, "
              "transfer waits (runtime/runner.py)"),
    ("engine.stage.", "per-stage engine counters published from "
                      "StageMetrics (utils/profiling.py)"),
    ("engine.", "host execution engine: stage busy time and retries "
                "(data/engine.py)"),
    ("pipeline.", "parallel host pipeline: pooled decode workers, "
                  "ordered re-merge, shared-memory hand-off "
                  "(data/pipeline.py)"),
    ("device.", "device-side accounting observed from the host "
                "(runtime/runner.py)"),
    ("serve.", "online serving front-end: admission, micro-batching, "
               "latency (sparkdl_tpu/serve)"),
    ("collective.", "mesh-program collective launch discipline "
                    "(parallel/mesh.py)"),
    ("sanitize.", "runtime transfer-guard sanitizer "
                  "(runtime/sanitize.py)"),
    ("autotune.", "closed-loop infeed autotuner (sparkdl_tpu/autotune)"),
    ("watchdog.", "stall watchdog verdicts (obs/watchdog.py)"),
    ("flight.", "flight-recorder forensics bundles (obs/flight.py)"),
    ("slo.", "rolling-window SLO burn-rate/budget verdicts "
             "(obs/slo.py)"),
    ("compile.", "compile forensics: jit compiles, retrace "
                 "attribution, the steady-state zero-retrace "
                 "guarantee (obs/compile_log.py)"),
    ("hbm.", "per-device memory_stats() HBM accounting with "
             "high-watermark tracking (obs/compile_log.py)"),
    ("obs.", "the observability layer's own accounting "
             "(sparkdl_tpu/obs)"),
    ("worker.", "cross-process telemetry shipped by pipeline worker "
                "processes: per-worker (worker.<i>.*) and rollup "
                "(worker.all.*) mirrors of worker-side counters, plus "
                "the aggregator's own accounting (obs/remote.py)"),
    ("faults.", "armed fault-injection drill counters "
                "(resilience/faults.py)"),
    ("resilience.", "shared retry-policy/budget accounting "
                    "(resilience/policy.py)"),
    ("telemetry.", "telemetry-endpoint handler failures "
                   "(obs/export.py)"),
)

_HELP_FALLBACK = ("sparkdl_tpu pipeline metric (registry key table: "
                  "docs/OBSERVABILITY.md)")


def prom_help(name: str) -> str:
    """The ``# HELP`` text for a registry key: longest matching lane
    prefix, with a generic fallback — every exported sample gets a
    HELP line (the Prometheus exposition contract ci.sh validates
    line-by-line), never a bare TYPE."""
    for prefix, text in HELP_BY_PREFIX:
        if name.startswith(prefix):
            return f"{text} [key: {name}]"
    return f"{_HELP_FALLBACK} [key: {name}]"


def _fmt(value: float) -> str:
    # Prometheus floats: repr round-trips, integers stay readable
    f = float(value)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(registry: Optional[MetricsRegistry] = None) -> str:
    """The registry in Prometheus text exposition format (version
    0.0.4): one ``# HELP`` + ``# TYPE`` pair per metric, kinds
    preserved. This is THE scrape payload — ``tools/ci.sh``'s
    telemetry gate parses it line-by-line (every TYPE must follow its
    HELP) so a rendering regression fails the build, not the
    operator's dashboard."""
    registry = registry if registry is not None else default_registry()
    lines = []

    def emit(base: str, kind: str, value: float, key: str) -> None:
        lines.append(f"# HELP {base} {prom_help(key)}")
        lines.append(f"# TYPE {base} {kind}")
        lines.append(f"{base} {_fmt(value)}")

    for m in registry.metrics():
        base = prom_name(m.name)
        if isinstance(m, Counter):
            emit(base, "counter", m.value, m.name)
        elif isinstance(m, Gauge):
            emit(base, "gauge", m.value, m.name)
        elif isinstance(m, Reservoir):
            p50, p99 = m.quantiles((0.5, 0.99))
            emit(f"{base}_count", "counter", m.count, m.name)
            emit(f"{base}_p50", "gauge", p50, m.name)
            emit(f"{base}_p99", "gauge", p99, m.name)
    return "\n".join(lines) + "\n"


class TelemetryServer:
    """Localhost HTTP surface over the process-wide registry, watchdog,
    and flight recorder (module docstring).

    ``model_server`` (optional) scopes ``/statusz``'s serve section to
    one :class:`~sparkdl_tpu.serve.server.ModelServer`; without it the
    section covers every live server the flight recorder knows about.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 port: int = 0, host: str = "127.0.0.1",
                 model_server=None, watchdog_instance=None):
        self._registry = (registry if registry is not None
                          else default_registry())
        self._requested = (host, port)
        self._model_server = model_server
        self._watchdog = (watchdog_instance if watchdog_instance
                          is not None else watchdog())
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._epoch = time.perf_counter()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "TelemetryServer":
        if self._httpd is not None:
            return self
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            server_version = "sparkdl-telemetry/1"

            def do_GET(self):  # noqa: N802 (stdlib contract)
                outer._route(self)

            def log_message(self, fmt, *args):
                logger.debug("telemetry: %s", fmt % args)

        self._httpd = ThreadingHTTPServer(self._requested, _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="sparkdl-telemetry", daemon=True)
        self._thread.start()
        logger.info("telemetry endpoint listening on http://%s:%d "
                    "(/metricsz /healthz /statusz)", *self.address)
        return self

    @property
    def address(self):
        if self._httpd is None:
            return self._requested
        return self._httpd.server_address[:2]

    @property
    def port(self) -> int:
        return self.address[1]

    def url(self, path: str = "") -> str:
        host, port = self.address
        return f"http://{host}:{port}{path}"

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        thread, self._thread = self._thread, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=2.0)

    def __enter__(self) -> "TelemetryServer":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close()
        return False

    # -- routing -------------------------------------------------------------

    def _route(self, handler: BaseHTTPRequestHandler) -> None:
        path = handler.path.split("?", 1)[0]
        try:
            if path in ("/metricsz", "/metrics"):
                # refresh the SLO gauges at scrape time: the serve
                # loop's publish is rate-limited (obs/slo.py
                # publish_due — status() scans the whole outcome
                # window, not a per-micro-batch cost), and the scrape
                # is exactly the rare reader that should pay for
                # freshness. Degrades silently: a broken tracker must
                # not 500 every other metric.
                try:
                    from sparkdl_tpu.obs.slo import slo_tracker
                    slo_tracker().publish(self._registry)
                except Exception as e:
                    self._registry.counter("telemetry.errors").add()
                    logger.debug("telemetry: slo refresh failed: %s",
                                 e)
                # the utilization ledger's reader-driven window: a
                # scrape closes a window when one is due, so
                # ledger.util.* is fresh without any in-process
                # arming; degrades like the SLO refresh (a broken
                # probe must not 500 every other metric)
                try:
                    from sparkdl_tpu.obs.ledger import ledger
                    ledger().tick_due()
                except Exception as e:
                    self._registry.counter("telemetry.errors").add()
                    logger.debug("telemetry: ledger tick failed: %s",
                                 e)
                # HBM accounting at scrape time: a scrape is exactly
                # the reader that should pay for gauge freshness (the
                # SLO-refresh precedent); degrades internally
                try:
                    from sparkdl_tpu.obs.compile_log import publish_hbm
                    publish_hbm(self._registry)
                except Exception as e:
                    self._registry.counter("telemetry.errors").add()
                    logger.debug("telemetry: hbm refresh failed: %s",
                                 e)
                body = render_prometheus(self._registry).encode()
                self._reply(handler, 200, body,
                            "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                verdict = self._watchdog.verdict()
                # a pipeline worker's OWN watchdog verdict reaches the
                # liveness bit too (obs/remote.py): a wedged worker
                # process is a stalled pipeline even when every
                # parent-side loop still beats
                try:
                    from sparkdl_tpu.obs import remote
                    worker_health = remote.aggregator().health()
                except Exception as e:
                    logger.debug("telemetry: worker health probe "
                                 "failed: %s", e)
                    worker_health = {"workers": 0, "stalled": [],
                                     "dead": []}
                code = (200 if verdict["healthy"]
                        and not worker_health["stalled"] else 503)
                # the compile-forensics detail (obs/compile_log.py):
                # unexpected retraces are a perf-guarantee violation,
                # not a liveness failure — the status code stays the
                # stall verdicts'; the detail flips so a probe (and
                # ci.sh's gate) sees the warm-start contract break
                try:
                    from sparkdl_tpu.obs.compile_log import compile_log
                    retraces = compile_log().unexpected_retraces
                except Exception:
                    retraces = None
                body = json.dumps({
                    "status": "ok" if code == 200 else "stalled",
                    "stalled_sources": verdict["stalled_sources"],
                    "worker_stalled": worker_health["stalled"],
                    "worker_dead": worker_health["dead"],
                    "watchdog_armed": verdict["armed"],
                    "unexpected_retraces": retraces,
                    "compile_steady": (retraces == 0
                                       if retraces is not None
                                       else None),
                }).encode()
                self._reply(handler, code, body, "application/json")
            elif path == "/statusz":
                body = json.dumps(self._statusz(),
                                  default=str).encode()
                self._reply(handler, 200, body, "application/json")
            else:
                self._reply(handler, 404,
                            b'{"error": "unknown path; try /metricsz, '
                            b'/healthz, /statusz"}',
                            "application/json")
        except Exception:
            # the health surface must never take the process down (and
            # a broken probe should read as a 500, not a hang) — but a
            # failing surface must COUNT its failures where the next
            # successful scrape sees them (H12)
            self._registry.counter("telemetry.errors").add()
            logger.exception("telemetry: %s handler failed", path)
            try:
                self._reply(handler, 500, b'{"error": "internal"}',
                            "application/json")
            # sparkdl-lint: allow[H12] -- root failure counted in telemetry.errors above; the reply failing means the peer hung up, and there is no socket left to account anything to
            except Exception as e:
                logger.debug("telemetry: error reply failed: %s", e)

    @staticmethod
    def _reply(handler, code: int, body: bytes, ctype: str) -> None:
        handler.send_response(code)
        handler.send_header("Content-Type", ctype)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    def _statusz(self) -> dict:
        if self._model_server is not None:
            servers = [self._model_server.telemetry_status()]
        else:
            # the flight recorder's per-server degrade shaping, reused:
            # /statusz and flight bundles must not drift apart
            servers = _flight._serve_status()
        from sparkdl_tpu.obs.request_log import request_log
        from sparkdl_tpu.obs.slo import slo_tracker
        return {
            "pid": os.getpid(),
            "uptime_s": round(time.perf_counter() - self._epoch, 3),
            "platform": _flight.platform_info(),
            "watchdog": self._watchdog.verdict(),
            "flight": _flight.recorder().status(),
            # error budgets + burn rate (obs/slo.py) and the bounded
            # per-request log's state (obs/request_log.py) — the same
            # shapes the flight bundle carries, so a curl and a
            # postmortem never disagree
            "slo": slo_tracker().status(),
            "request_log": request_log().status(),
            # the resilience layer's drill/recovery state: fault-
            # injection config + per-site counts, live circuit
            # verdicts, retry/shed totals (docs/RESILIENCE.md) — same
            # shape as the flight bundle's section, so a curl and a
            # postmortem never disagree
            "resilience": _flight.resilience_state(),
            # the live roofline: current window, ceilings, and the
            # bounded history ring (obs/ledger.py) — literally the
            # same renderer the flight bundle uses
            "ledger": _flight.ledger_state(),
            # the parallel host pipeline's live worker/read-ahead/mode
            # picture (data/pipeline.py) — same shape as the flight
            # bundle's section, so a curl and a postmortem never
            # disagree
            "pipeline": _flight.pipeline_state(),
            # the disaggregated input service's fleet/snapshot picture
            # (sparkdl_tpu/inputsvc, docs/DATA_SERVICE.md) — same
            # shape as the flight bundle's section
            "inputsvc": _flight.inputsvc_state(),
            # the fleet control plane's deployments/swap/warm-start
            # picture (sparkdl_tpu/fleet, docs/SERVING.md "Fleet
            # control plane") — same shape as the flight bundle's
            # section, so a curl and a postmortem never disagree
            "fleet": _flight.fleet_state(),
            # the cross-process telemetry plane's per-worker view
            # (obs/remote.py) — same shape as the flight bundle's
            # workers[] section, so a curl and a postmortem never
            # disagree
            "workers": _flight.workers_state(),
            # compile forensics (obs/compile_log.py): per-function
            # compile counts, retrace attribution, the steady-state
            # zero-retrace verdict — same shape as the flight
            # bundle's section ("diagnosing a compile storm",
            # docs/SERVING.md)
            "compile": _flight.compile_state(),
            "servers": servers,
            "metrics_count": len(self._registry.snapshot()),
        }


def start_telemetry(port: int = 0, host: str = "127.0.0.1",
                    registry: Optional[MetricsRegistry] = None
                    ) -> TelemetryServer:
    """Standalone endpoint around batch runs: start scraping the
    process-wide registry/watchdog/flight state with one call (close
    the returned server when done, or let the daemon thread die with
    the process)."""
    return TelemetryServer(registry=registry, port=port,
                           host=host).start()
