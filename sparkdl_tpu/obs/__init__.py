"""Unified pipeline observability: spans, a metrics registry,
Perfetto-exportable timelines, and an operable health surface across
engine → ship → device.

Eleven pieces (docs/OBSERVABILITY.md):

* :mod:`sparkdl_tpu.obs.compile_log` — compile forensics: every
  package jit compile routes through ONE CompileLog (callable name,
  abstract arg signature, wall time, ``cost_analysis``/
  ``memory_analysis`` FLOPs+bytes), recompiles of known functions
  carry a signature diff naming the offending argument, and
  ``warmup``/``prewarm`` mark programs *steady* — after which any
  compile counts ``compile.unexpected_retraces`` and fires a flight
  dump (the runtime-enforced zero-retrace guarantee); per-device
  ``memory_stats()`` publishes as periodic ``hbm.*`` gauges with
  high-watermark tracking;

* :mod:`sparkdl_tpu.obs.ledger` — the windowed utilization ledger:
  per-window rates over the hot paths' feed counters, divided by
  probed per-host ceilings into ``ledger.util.*`` fractions and ONE
  continuous ``ledger.bound_by`` roofline verdict (the same
  ``attribute()`` bench.py's offline ``pipeline_bound_by`` uses);

* :mod:`sparkdl_tpu.obs.trace` — ``span(name, lane=...)`` recording
  into one process-wide bounded ring buffer on a single clock, armed by
  ``SPARKDL_TPU_TRACE=1`` (near-zero overhead disarmed), exported as
  Chrome/Perfetto trace-event JSON;
* :mod:`sparkdl_tpu.obs.registry` — named counters/gauges/reservoirs
  with ONE ``snapshot() -> dict`` (bench's ``"obs"`` block,
  throughput_report);
* :mod:`sparkdl_tpu.obs.report` — ``python -m sparkdl_tpu.obs report
  <trace.json>``: per-lane busy %, top spans, stall breakdown;
* :mod:`sparkdl_tpu.obs.watchdog` — heartbeat-fed stall detection for
  the hot loops (``SPARKDL_TPU_WATCHDOG=1``): no-progress beyond the
  threshold logs loudly, counts ``watchdog.stalls``, and dumps the
  flight recorder;
* :mod:`sparkdl_tpu.obs.flight` — the flight recorder
  (``SPARKDL_TPU_FLIGHT=1``): retains recent spans + the rolling
  registry, writes a self-contained forensics bundle on ``dump()``,
  SIGUSR2, serve dispatch failure, or a watchdog stall;
* :mod:`sparkdl_tpu.obs.export` — Prometheus text rendering plus a
  localhost ``/metricsz`` / ``/healthz`` / ``/statusz`` HTTP surface
  (stdlib only), attachable to a ``ModelServer`` or standalone;
* :mod:`sparkdl_tpu.obs.request_log` — per-request timelines: every
  serve submit mints a ``request_id``, armed requests record a phase
  breakdown (queue / coalesce / staging / device / reassembly) into a
  bounded ring, render as linked Perfetto flows, and feed the latency
  reservoir's worst-case exemplars (``report --tails`` attributes the
  p99 from an exported trace);
* :mod:`sparkdl_tpu.obs.slo` — rolling-window SLO evaluation (latency
  + availability objectives): error-budget remaining and burn rate,
  published as ``sparkdl_slo_*`` on ``/metricsz``;
* :mod:`sparkdl_tpu.obs.remote` — the cross-process telemetry plane:
  pipeline worker processes arm a :class:`TelemetryAgent` that ships
  spans, counter deltas, watchdog verdicts, degrade events, and fault
  state back over the result hand-off; the parent
  :class:`TelemetryAggregator` merges worker spans into ONE
  clock-aligned Perfetto trace, folds counters into ``worker.<i>.*``
  (+ ``worker.all.*`` rollups), and extends ``/healthz``, flight
  bundles (``workers[]``), and ``report --workers`` across process
  boundaries.

Import-light on purpose: nothing here pulls jax (the report CLI and
the telemetry endpoint work on any machine); :func:`timed_device_get`
and the flight recorder's platform probes import it lazily.
"""

from sparkdl_tpu.obs.compile_log import (
    CompileLog,
    compile_log,
    publish_hbm,
)
from sparkdl_tpu.obs.export import (
    TelemetryServer,
    render_prometheus,
    start_telemetry,
)
from sparkdl_tpu.obs.flight import FlightRecorder
from sparkdl_tpu.obs.flight import recorder as flight_recorder
from sparkdl_tpu.obs.ledger import (
    UtilizationLedger,
    ledger,
    ledger_poll,
    probe_ceilings,
)
from sparkdl_tpu.obs.ledger import attribute as ledger_attribute
from sparkdl_tpu.obs.registry import (
    Counter,
    Gauge,
    MetricsRegistry,
    Reservoir,
    default_registry,
)
from sparkdl_tpu.obs.request_log import (
    RequestLog,
    RequestRecord,
    RequestTimeline,
    request_log,
)
from sparkdl_tpu.obs.remote import (
    TelemetryAgent,
    TelemetryAggregator,
    telemetry_config,
)
from sparkdl_tpu.obs.remote import aggregator as telemetry_aggregator
from sparkdl_tpu.obs.slo import SLObjective, SLOTracker, slo_tracker
from sparkdl_tpu.obs.trace import (
    SpanRecord,
    Tracer,
    span,
    timed_device_get,
    tracer,
)
from sparkdl_tpu.obs.watchdog import StallWatchdog
from sparkdl_tpu.obs.watchdog import watchdog as stall_watchdog

__all__ = [
    "CompileLog",
    "Counter",
    "FlightRecorder",
    "Gauge",
    "MetricsRegistry",
    "RequestLog",
    "RequestRecord",
    "RequestTimeline",
    "Reservoir",
    "SLObjective",
    "SLOTracker",
    "SpanRecord",
    "StallWatchdog",
    "TelemetryAgent",
    "TelemetryAggregator",
    "TelemetryServer",
    "Tracer",
    "UtilizationLedger",
    "compile_log",
    "default_registry",
    "flight_recorder",
    "ledger",
    "ledger_attribute",
    "ledger_poll",
    "probe_ceilings",
    "publish_hbm",
    "render_prometheus",
    "request_log",
    "slo_tracker",
    "span",
    "stall_watchdog",
    "start_telemetry",
    "telemetry_aggregator",
    "telemetry_config",
    "timed_device_get",
    "tracer",
]
