"""Unified pipeline observability: spans, a metrics registry, and
Perfetto-exportable timelines across engine → ship → device.

Three pieces (docs/OBSERVABILITY.md):

* :mod:`sparkdl_tpu.obs.trace` — ``span(name, lane=...)`` recording
  into one process-wide bounded ring buffer on a single clock, armed by
  ``SPARKDL_TPU_TRACE=1`` (near-zero overhead disarmed), exported as
  Chrome/Perfetto trace-event JSON;
* :mod:`sparkdl_tpu.obs.registry` — named counters/gauges with ONE
  ``snapshot() -> dict`` (bench's ``"obs"`` block, throughput_report);
* :mod:`sparkdl_tpu.obs.report` — ``python -m sparkdl_tpu.obs report
  <trace.json>``: per-lane busy %, top spans, stall breakdown.

Import-light on purpose: nothing here pulls jax (the report CLI works
on any machine); :func:`timed_device_get` imports it lazily at the
drain.
"""

from sparkdl_tpu.obs.registry import (
    Counter,
    Gauge,
    MetricsRegistry,
    Reservoir,
    default_registry,
)
from sparkdl_tpu.obs.trace import (
    SpanRecord,
    Tracer,
    span,
    timed_device_get,
    tracer,
)

__all__ = [
    "Counter",
    "Gauge",
    "MetricsRegistry",
    "Reservoir",
    "SpanRecord",
    "Tracer",
    "default_registry",
    "span",
    "timed_device_get",
    "tracer",
]
