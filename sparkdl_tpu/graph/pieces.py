"""Prebuilt converter pieces gluing the image schema to model tensors.

Re-design of the reference's ``python/sparkdl/graph/pieces.py``
(``buildSpImageConverter``: struct fields → decode_raw → reshape → cast;
``buildFlattener``: reshape(x, [-1])). Here the host runner already
assembles image structs into contiguous uint8 NHWC batches (see
``runtime/runner.py``), so the converter's device-side job is the cast /
scale / channel-reorder — deliberately done ON DEVICE so the host ships
uint8 (4× less host→device bandwidth, see BASELINE.md) and XLA fuses the
cast into the model's first conv.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from sparkdl_tpu.graph.function import ModelFunction


def buildSpImageConverter(height: int, width: int, nChannels: int = 3,
                          channel_order: str = "RGB",
                          scale: float = 1.0,
                          offset: float = 0.0) -> ModelFunction:
    """uint8 [N,H,W,C] image batch → float32 ``x*scale + offset`` with
    optional BGR reorder (the reference supported OpenCV-style BGR
    structs; our structs are RGB so BGR is the conversion case)."""
    if channel_order not in ("RGB", "BGR"):
        raise ValueError(f"channel_order must be RGB or BGR, "
                         f"got {channel_order!r}")

    def convert(x):
        x = x.astype(jnp.float32)
        if channel_order == "BGR":
            x = x[..., ::-1]
        if scale != 1.0:
            x = x * scale
        if offset != 0.0:
            x = x + offset
        return x

    return ModelFunction.fromSingle(
        convert, None,
        input_shape=(height, width, nChannels), input_dtype=jnp.uint8,
        input_name="image", output_name="converted",
        name="spImageConverter")


def buildFlattener(input_shape: Tuple[int, ...] = (),
                   input_name: str = "input") -> ModelFunction:
    """[N, ...] → float32 [N, prod(...)] (reference ``buildFlattener``)."""

    def flatten(x):
        return x.reshape(x.shape[0], -1).astype(jnp.float32)

    return ModelFunction.fromSingle(
        flatten, None, input_shape=tuple(input_shape),
        input_name=input_name, output_name="flattened", name="flattener")
