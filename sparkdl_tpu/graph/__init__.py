"""Graph toolkit (reference L4: ``python/sparkdl/graph/``).

The reference's unit of deployable compute was a frozen TF GraphDef
(``GraphFunction``) built inside an ``IsolatedSession`` and broadcast to
executors. Here the unit is a :class:`ModelFunction`: a pure jittable
function + params pytree + named IO signature, serializable to StableHLO
via ``jax.export`` — the north-star's "serializes StableHLO instead of TF
GraphDefs". Composition replaces graph surgery; XLA fusion replaces
manual graph stitching.
"""

from sparkdl_tpu.graph.function import ModelFunction  # noqa: F401
from sparkdl_tpu.graph.ingest import ModelIngest, TFInputGraph  # noqa: F401
from sparkdl_tpu.graph.pieces import (  # noqa: F401
    buildFlattener,
    buildSpImageConverter,
)
from sparkdl_tpu.graph import utils  # noqa: F401  (the reference's tfx)

__all__ = [
    "ModelFunction",
    "ModelIngest",
    "TFInputGraph",
    "buildSpImageConverter",
    "buildFlattener",
    "utils",
]
