"""ModelFunction: the deployable unit of compute.

TPU-native re-design of the reference's
``python/sparkdl/graph/builder.py::GraphFunction`` (frozen GraphDef +
input/output names) and ``IsolatedSession`` (hermetic graph build +
``asGraphFunction`` freeze). A ModelFunction is:

* ``apply_fn(params, inputs: dict[str, Array]) -> dict[str, Array]`` — a
  pure function; ``params`` is a pytree (the reference froze variables
  into graph constants; here they stay an explicit pytree, and "freezing"
  is ``export()`` which bakes them into serialized StableHLO).
* named input/output signatures (per-row shapes, batch dim implicit) —
  the counterpart of the reference's tensor-name mappings.
* ``fromList`` composition replacing GraphFunction.fromList's GraphDef
  import/re-export surgery: plain function composition, fused by XLA
  into one program at jit time.

No session isolation is needed: JAX is functional, so the reference's
``IsolatedSession``/``KSessionWrap`` global-state hygiene (builder.py,
keras_utils.py) has no analogue — that entire failure class is gone.

A ModelFunction may instead wrap an opaque **host** callable (backend
"host") for ingested TF-era graphs that execute via the TF CPU runtime —
the same place the reference executed them (executor CPUs via JNI
libtensorflow); see ``graph/ingest.py`` for the boundary.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sparkdl_tpu.obs.compile_log import compile_log

# name -> (per-row shape tuple, dtype)
Signature = Dict[str, Tuple[Tuple[int, ...], Any]]


def _as_dict(x, names: Sequence[str]) -> Dict[str, Any]:
    if isinstance(x, dict):
        return x
    if len(names) != 1:
        raise ValueError(
            f"got a single array for multi-input function {list(names)}")
    return {names[0]: x}


class ModelFunction:
    """A named-IO pure function + params, composable and exportable."""

    def __init__(self,
                 apply_fn: Callable[[Any, Dict[str, jax.Array]],
                                    Dict[str, jax.Array]],
                 params: Any = None,
                 input_signature: Optional[Signature] = None,
                 output_names: Optional[Sequence[str]] = None,
                 backend: str = "jax",
                 name: str = "model_fn"):
        self.apply_fn = apply_fn
        self.params = params
        self.input_signature: Signature = dict(input_signature or {})
        self._output_names = list(output_names) if output_names else None
        self.backend = backend
        self.name = name
        self._jit_cache: Dict[Any, Callable] = {}
        # device copies of params keyed by placement; each entry keeps
        # the host object it was built from so reassigning .params
        # invalidates it
        self._params_cache: Dict[Any, Tuple[Any, Any]] = {}
        # the put callable behind each placement key, recorded so the
        # fleet hot-swap (stage_params) can re-place NEW params onto
        # exactly the placements this process serves — including a
        # device-pinned put the registry seeded for a packed replica
        self._puts: Dict[Any, Callable] = {}
        # known output signature (set by deserialize, which reads it
        # from the exported avals); when present, output_signature()
        # returns it instead of eval_shape-probing — a fixed-batch
        # exported program rejects any other batch size
        self._output_signature: Optional[Signature] = None
        # the ONLY batch size a fixed-batch exported program accepts
        # (set by deserialize; propagated by wrappers). eval_shape
        # probes must use it — batch-1 probes crash such programs.
        self._fixed_batch: Optional[int] = None

    # -- construction -------------------------------------------------------

    @staticmethod
    def fromSingle(fn: Callable, params: Any = None,
                   input_shape: Tuple[int, ...] = (),
                   input_dtype=jnp.float32,
                   input_name: str = "input",
                   output_name: str = "output",
                   name: str = "model_fn") -> "ModelFunction":
        """Wrap a single-tensor function ``fn(params, x) -> y`` (or
        ``fn(x) -> y`` when params is None)."""

        def apply_fn(params_, inputs):
            x = inputs[input_name]
            y = fn(params_, x) if params_ is not None else fn(x)
            if isinstance(y, dict):
                return y
            return {output_name: y}

        return ModelFunction(
            apply_fn, params,
            input_signature={input_name: (tuple(input_shape), input_dtype)},
            output_names=[output_name], name=name)

    @staticmethod
    def fromList(functions: Sequence["ModelFunction"],
                 name: str = "composed") -> "ModelFunction":
        """Chain single-output→single-input functions into one
        (reference ``GraphFunction.fromList``). The composite is one
        jittable function; XLA fuses the stages."""
        functions = list(functions)
        if not functions:
            raise ValueError("fromList needs at least one function")
        for f in functions:
            if f.backend != "jax":
                raise ValueError(
                    f"fromList requires jax-backend functions, got "
                    f"'{f.backend}' for {f.name}")
        head = functions[0]

        def apply_fn(params_list, inputs):
            cur = inputs
            out: Dict[str, jax.Array] = {}
            for i, f in enumerate(functions):
                out = f.apply_fn(params_list[i], cur)
                if i + 1 < len(functions):
                    out_names = list(out)
                    if len(out_names) != 1:
                        raise ValueError(
                            f"stage {f.name} has {len(out_names)} outputs; "
                            "fromList chains single-output stages")
                    nxt_in = functions[i + 1].input_names
                    if len(nxt_in) != 1:
                        raise ValueError(
                            f"stage {functions[i+1].name} has "
                            f"{len(nxt_in)} inputs; fromList chains "
                            "single-input stages")
                    cur = {nxt_in[0]: out[out_names[0]]}
            return out

        return ModelFunction(
            apply_fn,
            params=[f.params for f in functions],
            input_signature=dict(head.input_signature),
            output_names=functions[-1]._output_names,
            name=name)

    # -- introspection ------------------------------------------------------

    @property
    def input_names(self) -> List[str]:
        return list(self.input_signature)

    @property
    def output_names(self) -> List[str]:
        if self._output_names is None:
            self._output_names = list(self.output_signature())
        return list(self._output_names)

    def output_signature(self, batch_size: int = 1) -> Signature:
        """Infer named output shapes via ``jax.eval_shape`` (per-row
        shapes, batch stripped); deserialized models return the
        signature recorded in the export instead of probing, and
        wrappers around a fixed-batch deserialized program probe with
        ITS batch size (any other size is rejected by the export)."""
        if self._output_signature is not None:
            return dict(self._output_signature)
        if self.backend != "jax":
            raise ValueError("output_signature requires a jax backend")
        if self._fixed_batch is not None:
            batch_size = self._fixed_batch
        inputs = {
            n: jax.ShapeDtypeStruct((batch_size,) + tuple(shape), dtype)
            for n, (shape, dtype) in self.input_signature.items()
        }
        out = jax.eval_shape(self.apply_fn, self.params, inputs)
        return {n: (tuple(s.shape[1:]), s.dtype) for n, s in out.items()}

    def rename_io(self, input_map: Optional[Dict[str, str]] = None,
                  output_map: Optional[Dict[str, str]] = None
                  ) -> "ModelFunction":
        """New ModelFunction with renamed inputs/outputs (the counterpart
        of the reference's signature-name↔tensor-name translation,
        ``graph/input.py::translateInputMapping``)."""
        input_map = input_map or {}
        output_map = output_map or {}
        inv_in = {new: old for old, new in input_map.items()}
        base = self

        def apply_fn(params_, inputs):
            renamed = {inv_in.get(n, n): v for n, v in inputs.items()}
            out = base.apply_fn(params_, renamed)
            return {output_map.get(n, n): v for n, v in out.items()}

        sig = {input_map.get(n, n): v
               for n, v in self.input_signature.items()}
        out_names = ([output_map.get(n, n) for n in self._output_names]
                     if self._output_names else None)
        out = ModelFunction(apply_fn, self.params, sig, out_names,
                            backend=self.backend,
                            name=f"{self.name}.renamed")
        out._fixed_batch = self._fixed_batch
        if self._output_signature is not None:
            out._output_signature = {
                output_map.get(n, n): v
                for n, v in self._output_signature.items()}
        return out

    # -- execution ----------------------------------------------------------

    def _cached_device_params(self, key, put: Callable):
        self._puts[key] = put
        entry = self._params_cache.get(key)
        if entry is None or entry[0] is not self.params:
            # params changed: purge EVERY stale placement, not just this
            # key — dead replicated copies would otherwise hold HBM on
            # all devices for the ModelFunction's lifetime
            self._params_cache = {
                k: v for k, v in self._params_cache.items()
                if v[0] is self.params}
            # a cache miss is a weight transfer the compile forensics
            # want on the books (obs/compile_log.py): each placement
            # holds param-sized HBM for the ModelFunction's lifetime,
            # and a steady process re-placing weights is the same
            # class of hot-path surprise as a retrace
            log = compile_log()
            if log.armed:
                t0 = time.perf_counter()
                placed = put(self.params)
                wall = time.perf_counter() - t0
                leaves = jax.tree_util.tree_leaves(self.params)
                log.record_transfer(
                    name=f"{self.name}.device_params", kind="device_put",
                    wall_s=wall,
                    detail={"placement": (key if isinstance(key, str)
                                          else key[0]),
                            "leaves": len(leaves),
                            "bytes": sum(int(getattr(v, "nbytes", 0))
                                         for v in leaves)})
                entry = (self.params, placed)
            else:
                entry = (self.params, put(self.params))
            self._params_cache[key] = entry
        return entry[1]

    def device_params(self):
        """``params`` resident on the default device, transferred once
        and cached — passing the host pytree to every jitted call would
        re-transfer each weight leaf per call. Cache is keyed on the
        params object's identity, so reassigning ``self.params``
        invalidates it."""
        if self.backend != "jax" or self.params is None:
            return self.params
        return self._cached_device_params("default", jax.device_put)

    def replicated_params(self, mesh):
        """``params`` replicated to every device of ``mesh``, cached per
        mesh (the sharded-inference analogue of :meth:`device_params`)."""
        if self.backend != "jax" or self.params is None:
            return self.params
        from sparkdl_tpu.parallel.mesh import replicated
        sharding = replicated(mesh)
        return self._cached_device_params(
            ("replicated", mesh), lambda p: jax.device_put(p, sharding))

    def sharded_jitted(self, mesh) -> Callable:
        """Jit compiled against ``mesh``: params replicated, every named
        input/output batch-sharded over the ``data`` axis — the same
        axis name ShardedBatchRunner sizes its global batches by
        (cached per mesh, like :meth:`jitted`)."""
        if self.backend != "jax":
            raise ValueError(f"cannot jit backend '{self.backend}'")
        key = ("sharded", mesh)
        if key not in self._jit_cache:
            from sparkdl_tpu.parallel.mesh import data_sharding, replicated
            rep = replicated(mesh)
            dat = data_sharding(mesh)
            fn = jax.jit(
                self.apply_fn,
                in_shardings=(rep, {k: dat for k in self.input_names}),
                out_shardings=dat)
            # route compiles through the process-wide CompileLog
            # (obs/compile_log.py): retrace attribution + cost/memory
            # accounting; one armed-check + passthrough disarmed
            self._jit_cache[key] = compile_log().instrument(
                fn, name=f"{self.name}.sharded_jitted",
                kind="sharded_jit",
                config={"mesh": tuple(mesh.shape.items()),
                        "in_shardings": "replicated+data",
                        "out_shardings": "data"},
                arg_names=("params", "inputs"))
        return self._jit_cache[key]

    def jitted(self, donate_inputs: bool = False) -> Callable:
        """Jit-compiled ``(params, inputs) -> outputs`` (cached)."""
        if self.backend != "jax":
            raise ValueError(f"cannot jit backend '{self.backend}'")
        key = ("jit", donate_inputs)
        if key not in self._jit_cache:
            fn = jax.jit(
                self.apply_fn,
                donate_argnums=(1,) if donate_inputs else ())
            # route compiles through the process-wide CompileLog
            # (obs/compile_log.py) — the serve layer's zero-retrace
            # guarantee is enforced against exactly this wrapper. The
            # donated variant is a DISTINCT program with its own
            # signature history: sharing the undonated name would make
            # its first (legitimate) compile read as a phantom retrace.
            log_name = (f"{self.name}.jitted[donated]"
                        if donate_inputs else f"{self.name}.jitted")
            self._jit_cache[key] = compile_log().instrument(
                fn, name=log_name, kind="jit",
                config={"donate_inputs": donate_inputs},
                arg_names=("params", "inputs"))
        return self._jit_cache[key]

    # -- hot swap (the fleet registry's two-phase weight flip) --------------

    def stage_params(self, new_params) -> Dict[Any, Any]:
        """Place ``new_params`` on device for every placement this
        function currently serves, WITHOUT making them live — the
        hot-swap's staging half (sparkdl_tpu/fleet/registry.py). The
        slow transfers happen here, off the dispatch path; the commit
        (:meth:`commit_params`) is then a pointer flip under the serve
        session's swap gate. Returns the staged placements to hand to
        :meth:`commit_params` — or to drop, which un-stages them (the
        rollback path frees the device copies by releasing the only
        reference)."""
        if self.backend != "jax":
            raise ValueError(
                f"cannot stage params for backend {self.backend!r}")
        puts = dict(self._puts) or {"default": jax.device_put}
        staged: Dict[Any, Any] = {}
        log = compile_log()
        for key, put in puts.items():
            t0 = time.perf_counter()
            staged[key] = put(new_params)
            if log.armed:
                leaves = jax.tree_util.tree_leaves(new_params)
                log.record_transfer(
                    name=f"{self.name}.stage_params", kind="device_put",
                    wall_s=time.perf_counter() - t0,
                    detail={"placement": (key if isinstance(key, str)
                                          else key[0]),
                            "leaves": len(leaves),
                            "bytes": sum(int(getattr(v, "nbytes", 0))
                                         for v in leaves)})
        return staged

    def commit_params(self, new_params, staged: Dict[Any, Any]) -> None:
        """Atomically flip to pre-staged params: ``.params`` and every
        device placement change by assignment only — no transfer, no
        retrace (the jit cache is untouched; only argument VALUES
        change, and the compiled shapes were validated by the caller).
        The caller holds the serve session's swap gate so the flip
        lands BETWEEN dispatches, never inside one."""
        self.params = new_params
        self._params_cache = {k: (new_params, v)
                              for k, v in staged.items()}

    def install_aot(self, compiled: Callable, *, wall_s: float = 0.0,
                    blob_bytes: Optional[int] = None) -> Callable:
        """Install a pre-compiled executable behind :meth:`jitted` —
        the executable-import half of the persisted warm-start seam
        (fleet/warmstart.py). The wrapper is the CompileLog's
        :class:`_AotProgram`: dispatches route through it like any
        instrumented program, but nothing it does can ever record a
        compile, because this process only LOADED the program. Covers
        the undonated program only (the serve dispatch path); the
        donated ring variant still jits lazily on first engagement."""
        if self.backend != "jax":
            raise ValueError(
                f"cannot install an executable for backend "
                f"{self.backend!r}")
        wrapper = compile_log().instrument_aot(
            compiled, name=f"{self.name}.jitted", kind="aot",
            wall_s=wall_s,
            detail={"bytes": blob_bytes} if blob_bytes else None)
        self._jit_cache[("jit", False)] = wrapper
        return wrapper

    def __call__(self, inputs, params: Any = "__own__"):
        if self.backend == "host":
            p = self.params if params == "__own__" else params
            d = _as_dict(inputs, self.input_names)
            return self.apply_fn(p, {k: np.asarray(v) for k, v in d.items()})
        p = self.device_params() if params == "__own__" else params
        single = not isinstance(inputs, dict)
        d = _as_dict(inputs, self.input_names)
        d = {k: jnp.asarray(v) for k, v in d.items()}
        # sparkdl-lint: allow[H15] -- jnp.asarray is zero-copy when the caller already hands device (or committed host) arrays, so `d` may ALIAS caller-owned buffers; donating would invalidate the caller's arrays on a second use — batch-path donation lives in jitted(donate_inputs=True), opted into by owners of their buffers
        out = self.jitted()(p, d)
        if single and len(out) == 1:
            return next(iter(out.values()))
        return out

    # -- serialization (the "freeze" step) ----------------------------------

    def export(self, batch_size: Optional[int] = None) -> bytes:
        """Serialize to StableHLO bytes with params baked in — the
        TPU-era analogue of ``strip_and_freeze_until`` + GraphDef
        serialization (reference ``graph/utils.py``). ``batch_size=None``
        exports a symbolic batch dimension."""
        if self.backend != "jax":
            raise ValueError(f"cannot export backend '{self.backend}'")
        from jax import export as jax_export

        params = self.params
        base = self.apply_fn

        def frozen(inputs):
            return base(params, inputs)

        if batch_size is None:
            (bdim,) = jax_export.symbolic_shape("batch")
            mk = lambda shape: (bdim,) + tuple(shape)  # noqa: E731
        else:
            mk = lambda shape: (batch_size,) + tuple(shape)  # noqa: E731
        args = {
            n: jax.ShapeDtypeStruct(mk(shape), dtype)
            for n, (shape, dtype) in self.input_signature.items()
        }
        exported = jax_export.export(jax.jit(frozen))(args)
        return bytes(exported.serialize())

    @staticmethod
    def deserialize(blob: bytes, name: str = "stablehlo") -> "ModelFunction":
        """Load serialized StableHLO back into a callable ModelFunction.
        The result is jittable and composable (it re-traces through the
        exported computation)."""
        from jax import export as jax_export
        t0 = time.perf_counter()
        try:
            exported = jax_export.deserialize(blob)
        except Exception as e:
            # jax surfaces raw flatbuffer unpack errors here ("requires
            # a buffer of at least 544501618 bytes") — name the actual
            # problem
            raise ValueError(
                f"not a serialized StableHLO export ({len(blob)} "
                "bytes; produce one with ModelFunction.export / "
                f"ModelIngest.fromExport): {type(e).__name__}: "
                f"{str(e)[:120]}") from e
        # a StableHLO load is a compile-adjacent event the forensics
        # want on the books (obs/compile_log.py): deserialization wall
        # time + blob size, keyed by the deployed name — an AOT
        # warm-start story is judged by where these land relative to
        # the first request
        log = compile_log()
        if log.armed:
            log.record_transfer(
                name=f"{name}.deserialize", kind="deserialize",
                wall_s=time.perf_counter() - t0,
                detail={"bytes": len(blob)})
        in_tree = exported.in_tree
        # input signature from the exported avals: one dict arg
        avals = exported.in_avals
        flat_names = jax.tree.unflatten(in_tree, list(range(len(avals))))
        # flat_names is ((dict_arg,), {}) structure mirror with leaf indices
        (dict_arg,), _ = flat_names
        sig = {}
        for key, idx in dict_arg.items():
            aval = avals[idx]
            sig[key] = (tuple(int(d) for d in aval.shape[1:]), aval.dtype)

        def apply_fn(params_, inputs):
            return exported.call(inputs)

        # Output names AND signature come from the exported avals
        # directly — the lazy eval_shape probe would call the program
        # with batch 1, which a fixed-batch export rejects.
        out_avals = exported.out_avals
        out_tree_names = jax.tree.unflatten(
            exported.out_tree, list(range(len(out_avals))))
        output_names = None
        out_sig = None
        if isinstance(out_tree_names, dict):
            output_names = list(out_tree_names)
            out_sig = {
                key: (tuple(int(d) for d in out_avals[idx].shape[1:]),
                      out_avals[idx].dtype)
                for key, idx in out_tree_names.items()}

        mf = ModelFunction(apply_fn, None, sig, output_names, name=name)
        mf._output_signature = out_sig
        try:
            mf._fixed_batch = int(avals[0].shape[0])
        except Exception:
            # symbolic batch dims (jax shape-poly raises its own
            # InconclusiveDimensionOperation on int()) → no constraint
            mf._fixed_batch = None
        return mf

    # -- shipping -----------------------------------------------------------

    def __getstate__(self):
        """Stage closures holding a ModelFunction ship to Spark
        executors (spark_binding; cloudpickle handles apply_fn and the
        host params pytree). Compiled programs and device-resident
        params are process-local — drop them on the wire; the executor
        re-jits and re-places lazily, exactly like a fresh process.
        Host-backend functions (ingested TF graphs) hold live TF objects
        and cannot ship — re-ingest from the artifact on the executor."""
        if self.backend == "host":
            raise TypeError(
                f"host-backend ModelFunction {self.name!r} cannot be "
                "serialized for shipping (it wraps live TF runtime "
                "state); re-create it on the worker from its source "
                "artifact (SavedModel/checkpoint path), or export a "
                "jax-backend model to StableHLO instead")
        state = self.__dict__.copy()
        state["_jit_cache"] = {}
        state["_params_cache"] = {}
        # put callables may close over meshes / pinned devices —
        # process-local, like the placements they produce
        state["_puts"] = {}
        return state

    def __repr__(self) -> str:
        outs = self._output_names or "?"
        return (f"ModelFunction({self.name}, backend={self.backend}, "
                f"inputs={self.input_names}, outputs={outs})")
