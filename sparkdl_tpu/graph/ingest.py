"""ModelIngest: uniform model ingestion from every supported source.

Re-design of the reference's single most important L4 component,
``python/sparkdl/graph/input.py::TFInputGraph`` — which ingested a TF
model from 6 sources (graph / graphdef / saved_model ±signature /
checkpoint ±signature) into one frozen, serialized form plus tensor-name
mappings. The TPU-era source matrix:

==============================  ============================================
reference source                TPU-native source
==============================  ============================================
tf.Graph in a session           ``fromGraph`` (host-executed, frozen graph);
                                jax users: ``fromFunction`` (fn + params)
frozen GraphDef bytes           ``fromGraphDef`` (host-executed);
                                TPU broadcast form: ``fromExport``
                                (serialized StableHLO bytes)
Keras .h5 model file            ``fromKerasFile`` / ``fromKerasModel``
                                (Keras 3, JAX backend → jittable)
SavedModel + signature          ``fromSavedModelWithSignature``
SavedModel (default sig)        ``fromSavedModel``
tf.train checkpoint (±sig)      ``fromCheckpoint`` / weight-pytree pairing
==============================  ============================================

Honest execution boundary (SURVEY §7 "hard parts"): arbitrary TF-era
graphs (SavedModel/checkpoint meta-graphs) cannot be re-targeted to TPU
without a TF→StableHLO bridge, so they run on the **host CPU via the TF
runtime** — which is exactly where the reference executed them (executor
CPUs via TensorFrames/JNI libtensorflow). They are first-class citizens
of the pipeline (host-backend ModelFunctions); for TPU execution, bring
the model as a jax/flax function, a Keras 3 model, or exported StableHLO,
or extract checkpoint weights into a zoo architecture.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from sparkdl_tpu.graph.function import ModelFunction, Signature

_TF_ATTR_SUFFIX = "/.ATTRIBUTES/VARIABLE_VALUE"


def _tf():
    """Import TF lazily, pinned to host CPU (the tunneled TPU plugin has
    no TF kernels; TF is used only to read/execute TF-era artifacts)."""
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    import tensorflow as tf
    try:
        tf.config.set_visible_devices([], "TPU")
        tf.config.set_visible_devices([], "GPU")
    except Exception:
        pass
    return tf


class ModelIngest:
    """Namespace of ingestion constructors; every method returns a
    :class:`ModelFunction` ready for the transformers/runner."""

    # -- native jax sources -------------------------------------------------

    @staticmethod
    def fromFunction(fn: Callable, params: Any = None,
                     input_signature: Optional[Signature] = None,
                     input_shape: Optional[Tuple[int, ...]] = None,
                     input_dtype=np.float32,
                     name: str = "jax_fn") -> ModelFunction:
        """A jax function: either ``fn(params, inputs_dict)->outputs_dict``
        with an explicit ``input_signature``, or a single-tensor
        ``fn(params, x)``/``fn(x)`` with ``input_shape``."""
        if input_signature is not None:
            return ModelFunction(fn, params, input_signature, name=name)
        if input_shape is None:
            raise ValueError("need input_signature or input_shape")
        return ModelFunction.fromSingle(
            fn, params, input_shape=input_shape, input_dtype=input_dtype,
            name=name)

    @staticmethod
    def fromExport(blob: bytes, name: str = "stablehlo") -> ModelFunction:
        """Serialized StableHLO (from ``ModelFunction.export``) — the
        broadcast/frozen form (reference: frozen GraphDef bytes)."""
        return ModelFunction.deserialize(blob, name=name)

    # -- Keras sources ------------------------------------------------------

    @staticmethod
    def fromKerasModel(model, name: Optional[str] = None) -> ModelFunction:
        """A Keras 3 model (JAX backend): wrapped via ``stateless_call``
        so it is a pure jittable function with an explicit params pytree
        (reference: Keras model → frozen graph inside ``KSessionWrap``)."""
        import keras
        if keras.backend.backend() != "jax":
            raise RuntimeError(
                "Keras must run with the JAX backend for TPU execution; "
                "set KERAS_BACKEND=jax before importing keras")
        if len(model.inputs) != 1:
            raise ValueError(
                f"expected a single-input model, got {len(model.inputs)}")
        raw_shape = model.inputs[0].shape[1:]
        if any(d is None for d in raw_shape):
            raise ValueError(
                f"model {model.name!r} has dynamic input shape "
                f"{model.inputs[0].shape}; XLA needs static shapes — "
                "rebuild the model with concrete input dims "
                "(e.g. Input((224, 224, 3)) instead of Input((None, None, 3)))")
        in_shape = tuple(int(d) for d in raw_shape)
        in_dtype = model.inputs[0].dtype or "float32"
        out_names = [f"output_{i}" for i in range(len(model.outputs))]

        params = {
            "trainable": [v.value for v in model.trainable_variables],
            "non_trainable": [v.value for v in model.non_trainable_variables],
        }

        def apply_fn(p, inputs):
            (x,) = inputs.values()
            outs, _ = model.stateless_call(
                p["trainable"], p["non_trainable"], x, training=False)
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
            return dict(zip(out_names, outs))

        return ModelFunction(
            apply_fn, params,
            input_signature={"input": (in_shape, np.dtype(in_dtype))},
            output_names=out_names,
            name=name or f"keras:{model.name}")

    @staticmethod
    def fromKerasFile(path: str, name: Optional[str] = None) -> ModelFunction:
        """Load a user Keras model file (.h5 legacy or .keras) with the
        JAX backend (reference ``KerasImageFileTransformer.modelFile``)."""
        import keras
        model = keras.models.load_model(path, compile=False)
        return ModelIngest.fromKerasModel(
            model, name=name or f"keras:{os.path.basename(path)}")

    # -- TF-era sources (host-executed; see module docstring) ---------------

    @staticmethod
    def fromGraphDef(graph_def, feed_names: Sequence[str],
                     fetch_names: Sequence[str],
                     name: Optional[str] = None) -> ModelFunction:
        """Frozen TF GraphDef (proto or serialized bytes, the TF1-era
        artifact format) → host-backend ModelFunction executing the
        pruned graph on CPU via the TF runtime, exactly like the
        SavedModel path (reference ``TFInputGraph.fromGraphDef``).

        ``feed_names``/``fetch_names`` are tensor names (``"x:0"``; a
        bare op name means its output 0). Input/output keys on the
        resulting ModelFunction are the clean op names; when several
        tensors come off the SAME op (``"split:0"``, ``"split:1"``)
        their keys keep the output index so none collide — use
        ``rename_io`` to remap.
        """
        tf = _tf()
        if isinstance(graph_def, (bytes, bytearray)):
            proto = tf.compat.v1.GraphDef()
            proto.ParseFromString(bytes(graph_def))
            graph_def = proto

        def _tensor_name(n: str) -> str:
            return n if ":" in n else n + ":0"

        def _import():
            tf.compat.v1.import_graph_def(graph_def, name="")

        wrapped = tf.compat.v1.wrap_function(_import, [])
        feeds = [wrapped.graph.get_tensor_by_name(_tensor_name(n))
                 for n in feed_names]
        fetches = [wrapped.graph.get_tensor_by_name(_tensor_name(n))
                   for n in fetch_names]
        pruned = wrapped.prune(feeds=feeds, fetches=fetches)

        def _keys(names: Sequence[str], role: str) -> List[str]:
            """Dict keys for tensors: the bare op name, EXCEPT when
            several requested tensors share an op — then every such key
            keeps its output index (``op_1``), because colliding keys
            would silently drop all but the last tensor. If even those
            collide with another requested op's literal name (an op
            actually named ``split_0`` next to ``split:0``), fall back
            to the full unique tensor names for everything."""
            full = [_tensor_name(n) for n in names]
            if len(set(full)) != len(full):
                dup = next(t for t in full if full.count(t) > 1)
                raise ValueError(
                    f"duplicate {role} tensor {dup!r}")
            ops = [t.split(":")[0] for t in full]
            keys = [op if ops.count(op) == 1
                    else f"{op}_{t.split(':')[1]}"
                    for op, t in zip(ops, full)]
            return keys if len(set(keys)) == len(keys) else full

        in_keys = _keys(feed_names, "feed")
        out_keys = _keys(fetch_names, "fetch")
        input_signature: Signature = {}
        for key, t in zip(in_keys, feeds):
            shape = tuple(int(d) if d is not None else None
                          for d in t.shape.as_list()[1:]) \
                if t.shape.rank is not None else ()
            input_signature[key] = (shape, np.dtype(t.dtype.name))

        def apply_fn(_params, inputs: Dict[str, np.ndarray]):
            args = [tf.constant(np.asarray(inputs[k])) for k in in_keys]
            out = pruned(*args)
            if not isinstance(out, (list, tuple)):
                out = [out]
            return {k: np.asarray(v) for k, v in zip(out_keys, out)}

        mf = ModelFunction(
            apply_fn, params=None, input_signature=input_signature,
            output_names=out_keys, backend="host",
            name=name or "graphdef")
        mf._keras_loaded = pruned  # keep the ConcreteFunction alive
        return mf

    @staticmethod
    def fromGraph(graph, feed_names: Sequence[str],
                  fetch_names: Sequence[str],
                  name: Optional[str] = None) -> ModelFunction:
        """A live ``tf.Graph`` (frozen: variables already constants) →
        host-backend ModelFunction (reference ``TFInputGraph.fromGraph``,
        which froze the session's graph; freeze first if yours holds
        variables)."""
        return ModelIngest.fromGraphDef(
            graph.as_graph_def(), feed_names, fetch_names,
            name=name or "graph")

    @staticmethod
    def fromSavedModel(saved_model_dir: str,
                       tagSet: Optional[str] = None,
                       signatureDefKey: Optional[str] = None,
                       name: Optional[str] = None) -> ModelFunction:
        """TF SavedModel → host-backend ModelFunction executing the chosen
        signature on CPU via the TF runtime (reference
        ``TFInputGraph.fromSavedModel``)."""
        tf = _tf()
        tags = tagSet.split(",") if tagSet else None
        loaded = tf.saved_model.load(saved_model_dir, tags=tags)
        key = signatureDefKey or "serving_default"
        if key not in loaded.signatures:
            raise KeyError(
                f"signature {key!r} not in SavedModel; available: "
                f"{list(loaded.signatures)}")
        sig_fn = loaded.signatures[key]

        _, kw_specs = sig_fn.structured_input_signature
        input_signature: Signature = {}
        for arg_name, spec in kw_specs.items():
            # dynamic (None) non-batch dims are legal in serving
            # signatures; keep them as None — the host path never needs
            # static shapes (only jax-backend functions do).
            shape = tuple(int(d) if d is not None else None
                          for d in spec.shape[1:])
            input_signature[arg_name] = (shape, np.dtype(spec.dtype.name))
        out_names = list(sig_fn.structured_outputs)

        def apply_fn(_params, inputs: Dict[str, np.ndarray]):
            feed = {k: tf.constant(np.asarray(v)) for k, v in inputs.items()}
            out = sig_fn(**feed)
            return {k: np.asarray(v) for k, v in out.items()}

        mf = ModelFunction(
            apply_fn, params=None, input_signature=input_signature,
            output_names=out_names, backend="host",
            name=name or f"saved_model:{os.path.basename(saved_model_dir)}")
        mf._keras_loaded = loaded  # keep the trackable alive
        return mf

    @staticmethod
    def fromSavedModelWithSignature(saved_model_dir: str,
                                    signatureDefKey: str,
                                    name: Optional[str] = None
                                    ) -> ModelFunction:
        """Explicit-signature variant (reference
        ``fromSavedModelWithSignature``)."""
        return ModelIngest.fromSavedModel(
            saved_model_dir, signatureDefKey=signatureDefKey, name=name)

    @staticmethod
    def loadCheckpointVariables(checkpoint_path: str) -> Dict[str, np.ndarray]:
        """Read all variables from a TF checkpoint (dir or file prefix)
        into ``{clean_name: ndarray}`` — TF2 object-graph attribute
        suffixes are stripped. This is the weight-extraction half of the
        reference's ``fromCheckpoint`` freeze."""
        tf = _tf()
        path = checkpoint_path
        if os.path.isdir(path):
            latest = tf.train.latest_checkpoint(path)
            if latest is None:
                raise FileNotFoundError(
                    f"no checkpoint found under {path}")
            path = latest
        reader = tf.train.load_checkpoint(path)
        out = {}
        for key in reader.get_variable_to_shape_map():
            if key == "_CHECKPOINTABLE_OBJECT_GRAPH":
                continue
            clean = key[:-len(_TF_ATTR_SUFFIX)] \
                if key.endswith(_TF_ATTR_SUFFIX) else key
            out[clean] = reader.get_tensor(key)
        return out

    @staticmethod
    def fromCheckpoint(checkpoint_path: str,
                       apply_fn: Callable,
                       input_signature: Signature,
                       params_builder: Optional[
                           Callable[[Dict[str, np.ndarray]], Any]] = None,
                       name: Optional[str] = None) -> ModelFunction:
        """TF checkpoint + a jax ``apply_fn`` → TPU-native ModelFunction.

        ``params_builder`` maps the checkpoint's ``{name: ndarray}`` to
        the pytree ``apply_fn`` expects (defaults to the dict itself).
        This is the TPU-honest version of the reference's
        ``fromCheckpoint`` (which imported the checkpoint's meta-graph:
        impossible to retarget to XLA; the *weights* are what survive).
        """
        variables = ModelIngest.loadCheckpointVariables(checkpoint_path)
        params = params_builder(variables) if params_builder else variables
        return ModelFunction(
            apply_fn, params, input_signature,
            name=name or f"checkpoint:{os.path.basename(checkpoint_path)}")

    @staticmethod
    def fromCheckpointWithSignature(checkpoint_path: str,
                                    apply_fn: Callable,
                                    input_signature: Signature,
                                    input_mapping: Dict[str, str],
                                    output_mapping: Dict[str, str],
                                    params_builder=None,
                                    name: Optional[str] = None
                                    ) -> ModelFunction:
        """Checkpoint variant with signature-name translation (reference
        ``fromCheckpointWithSignature`` + ``translateInput/OutputMapping``)."""
        mf = ModelIngest.fromCheckpoint(
            checkpoint_path, apply_fn, input_signature,
            params_builder=params_builder, name=name)
        return mf.rename_io(input_mapping, output_mapping)


# Reference-era alias: sparkdl users know this class as TFInputGraph.
TFInputGraph = ModelIngest
