"""IO-name hygiene and freeze helpers for ModelFunctions.

Re-design of the reference's ``python/sparkdl/graph/utils.py`` (imported
there as ``tfx``: ``op_name``/``tensor_name`` canonicalization,
``get_op``/``get_tensor``/``get_shape`` lookups, ``validated_graph``/
``validated_input``/``validated_output`` checks,
``strip_and_freeze_until`` graph surgery). TF-graph name strings
("op:0") don't exist in the TPU design — a ModelFunction's named IO
plays that role — so the module maps onto validation and freeze over
those names.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from sparkdl_tpu.graph.function import ModelFunction


def validated_model(mf) -> ModelFunction:
    """Assert the object is a usable ModelFunction (reference
    ``validated_graph``)."""
    if not isinstance(mf, ModelFunction):
        raise TypeError(
            f"expected a ModelFunction, got {type(mf).__name__}")
    if not mf.input_signature:
        raise ValueError(f"model {mf.name!r} declares no inputs")
    return mf


def validated_input(mf: ModelFunction, name: str) -> str:
    """Assert ``name`` is one of the model's inputs (reference
    ``validated_input``)."""
    validated_model(mf)
    if name not in mf.input_signature:
        raise ValueError(
            f"input {name!r} not in model {mf.name!r}; inputs: "
            f"{mf.input_names}")
    return name


def validated_output(mf: ModelFunction, name: str) -> str:
    """Assert ``name`` is one of the model's outputs (reference
    ``validated_output``)."""
    validated_model(mf)
    if name not in mf.output_names:
        raise ValueError(
            f"output {name!r} not in model {mf.name!r}; outputs: "
            f"{mf.output_names}")
    return name


def get_input_shape(mf: ModelFunction, name: str
                    ) -> Tuple[Optional[int], ...]:
    """Per-row shape of a named input (reference ``get_shape``; batch
    dim implicit)."""
    shape, _ = mf.input_signature[validated_input(mf, name)]
    return tuple(shape)


def get_output_shape(mf: ModelFunction, name: str) -> Tuple[int, ...]:
    """Per-row shape of a named output, inferred via eval_shape."""
    validated_output(mf, name)
    shape, _ = mf.output_signature()[name]
    return tuple(shape)


def input_names(mf: ModelFunction) -> List[str]:
    return validated_model(mf).input_names


def output_names(mf: ModelFunction) -> List[str]:
    return validated_model(mf).output_names


def strip_and_freeze(mf: ModelFunction,
                     batch_size: Optional[int] = None) -> bytes:
    """Params baked in, computation serialized to StableHLO bytes — the
    TPU-era ``strip_and_freeze_until`` (which folded TF variables into
    constants and pruned the graph; XLA export does both by
    construction). The bytes are the broadcast/deploy form."""
    return validated_model(mf).export(batch_size=batch_size)


def load_frozen(blob: bytes, name: str = "frozen") -> ModelFunction:
    """Inverse of :func:`strip_and_freeze` (reference: GraphDef parse +
    import)."""
    return ModelFunction.deserialize(blob, name=name)
