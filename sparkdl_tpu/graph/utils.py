"""IO-name hygiene and freeze helpers for ModelFunctions.

Re-design of the reference's ``python/sparkdl/graph/utils.py`` (imported
there as ``tfx``: ``op_name``/``tensor_name`` canonicalization,
``get_op``/``get_tensor``/``get_shape`` lookups, ``validated_graph``/
``validated_input``/``validated_output`` checks,
``strip_and_freeze_until`` graph surgery). TF-graph name strings
("op:0") don't exist in the TPU design — a ModelFunction's named IO
plays that role — so the module maps onto validation and freeze over
those names.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from sparkdl_tpu.graph.function import ModelFunction


def _propagate_fixed_batch(base: ModelFunction, wrapped: ModelFunction):
    """Wrappers around a fixed-batch deserialized program must keep its
    batch constraint, or their eval_shape probes crash with batch-1
    inputs the export rejects."""
    wrapped._fixed_batch = base._fixed_batch


def validated_model(mf) -> ModelFunction:
    """Assert the object is a usable ModelFunction (reference
    ``validated_graph``)."""
    if not isinstance(mf, ModelFunction):
        raise TypeError(
            f"expected a ModelFunction, got {type(mf).__name__}")
    if not mf.input_signature:
        raise ValueError(f"model {mf.name!r} declares no inputs")
    return mf


def validated_input(mf: ModelFunction, name: str) -> str:
    """Assert ``name`` is one of the model's inputs (reference
    ``validated_input``)."""
    validated_model(mf)
    if name not in mf.input_signature:
        raise ValueError(
            f"input {name!r} not in model {mf.name!r}; inputs: "
            f"{mf.input_names}")
    return name


def validated_output(mf: ModelFunction, name: str) -> str:
    """Assert ``name`` is one of the model's outputs (reference
    ``validated_output``)."""
    validated_model(mf)
    if name not in mf.output_names:
        raise ValueError(
            f"output {name!r} not in model {mf.name!r}; outputs: "
            f"{mf.output_names}")
    return name


def get_input_shape(mf: ModelFunction, name: str
                    ) -> Tuple[Optional[int], ...]:
    """Per-row shape of a named input (reference ``get_shape``; batch
    dim implicit)."""
    shape, _ = mf.input_signature[validated_input(mf, name)]
    return tuple(shape)


def get_output_shape(mf: ModelFunction, name: str) -> Tuple[int, ...]:
    """Per-row shape of a named output, inferred via eval_shape."""
    validated_output(mf, name)
    shape, _ = mf.output_signature()[name]
    return tuple(shape)


def input_names(mf: ModelFunction) -> List[str]:
    return validated_model(mf).input_names


def output_names(mf: ModelFunction) -> List[str]:
    return validated_model(mf).output_names


def select_outputs(mf: ModelFunction, names: List[str],
                   name: Optional[str] = None) -> ModelFunction:
    """Prune a ModelFunction to a subset of its outputs — the TPU-era
    remnant of the reference's graph pruning (``strip_and_freeze_until``
    cut the TF graph at the requested fetches; here XLA's dead-code
    elimination deletes the unused computation when the wrapped fn stops
    returning it, so slicing the output dict IS the pruning)."""
    validated_model(mf)
    names = [validated_output(mf, n) for n in names]
    if not names:
        raise ValueError("select_outputs needs at least one output")

    def apply_fn(params_, inputs):
        out = mf.apply_fn(params_, inputs)
        return {k: out[k] for k in names}

    out = ModelFunction(
        apply_fn, params=mf.params, input_signature=mf.input_signature,
        output_names=list(names), backend=mf.backend,
        name=name or f"{mf.name}[{','.join(names)}]")
    _propagate_fixed_batch(mf, out)
    if mf._output_signature is not None:
        out._output_signature = {
            k: v for k, v in mf._output_signature.items() if k in names}
    return out


def with_preprocessor(mf: ModelFunction, fn, input_signature=None,
                      name: Optional[str] = None) -> ModelFunction:
    """Prepend a pure per-input fn (``{name: array} → {name: array}``)
    to the model; both run inside ONE jitted XLA program, so elementwise
    preprocessing fuses into the model's first matmul/conv (the
    reference stitched a preprocessor GraphFunction in front via
    ``GraphFunction.fromList`` — reference ``udf/keras_image_model.py``)."""
    validated_model(mf)

    def apply_fn(params_, inputs):
        return mf.apply_fn(params_, fn(inputs))

    out = ModelFunction(
        apply_fn, params=mf.params,
        input_signature=input_signature or mf.input_signature,
        output_names=mf.output_names, backend=mf.backend,
        name=name or f"pre+{mf.name}")
    _propagate_fixed_batch(mf, out)
    if mf._output_signature is not None:
        out._output_signature = dict(mf._output_signature)
    return out


def with_postprocessor(mf: ModelFunction, fn,
                       output_names_out: Optional[List[str]] = None,
                       name: Optional[str] = None) -> ModelFunction:
    """Append a pure fn (``{name: array} → {name: array}``) after the
    model inside the same XLA program (the reference's output flattener,
    ``graph/pieces.py::buildFlattener``, was this composed at the graph
    level)."""
    validated_model(mf)

    def apply_fn(params_, inputs):
        return fn(mf.apply_fn(params_, inputs))

    out_names = output_names_out
    if out_names is None:
        if mf.backend != "jax":
            # A host model can't be shape-traced; inferring names would
            # mean running the full model on a zero batch at wrap time
            # (slow, and crashes models that reject all-zero input).
            raise ValueError(
                f"host-backend model {mf.name!r}: pass "
                "output_names_out explicitly (name inference would "
                "execute the model at wrap time)")
        import jax
        # fixed-batch deserialized programs reject any other batch size
        nb = mf._fixed_batch or 1
        probe = {
            k: jax.ShapeDtypeStruct((nb,) + tuple(
                d if d is not None else 1 for d in shape), dtype)
            for k, (shape, dtype) in mf.input_signature.items()}
        out = jax.eval_shape(lambda p, x: apply_fn(p, x),
                             mf.params, probe)
        out_names = list(out)

    out = ModelFunction(
        apply_fn, params=mf.params, input_signature=mf.input_signature,
        output_names=out_names, backend=mf.backend,
        name=name or f"{mf.name}+post")
    _propagate_fixed_batch(mf, out)
    return out


def strip_and_freeze(mf: ModelFunction,
                     batch_size: Optional[int] = None) -> bytes:
    """Params baked in, computation serialized to StableHLO bytes — the
    TPU-era ``strip_and_freeze_until`` (which folded TF variables into
    constants and pruned the graph; XLA export does both by
    construction). The bytes are the broadcast/deploy form."""
    return validated_model(mf).export(batch_size=batch_size)


def load_frozen(blob: bytes, name: str = "frozen") -> ModelFunction:
    """Inverse of :func:`strip_and_freeze` (reference: GraphDef parse +
    import)."""
    return ModelFunction.deserialize(blob, name=name)
