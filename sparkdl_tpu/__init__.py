"""sparkdl_tpu — TPU-native Deep Learning Pipelines.

A brand-new framework with the capabilities of Deep Learning Pipelines for
Spark (reference: ``phi-dbq/spark-deep-learning`` / ``sparkdl``,
``python/sparkdl/__init__.py::__all__``), re-designed TPU-first on
JAX/XLA: partitioned Arrow columns instead of Spark DataFrames, serialized
StableHLO instead of frozen TF GraphDefs, jit/pjit on TPU meshes instead of
TensorFrames' JNI-embedded TF sessions.

Public API surface mirrors the reference's eight user-facing names plus
``readImages`` (reference ``python/sparkdl/__init__.py``). Exports resolve
lazily so importing the package doesn't pull jax/keras until a symbol is
touched.
"""

__version__ = "0.4.0"

_EXPORTS = {
    "imageSchema": ("sparkdl_tpu.image.imageIO", "imageSchema"),
    "readImages": ("sparkdl_tpu.image.imageIO", "readImages"),
    "DeepImageFeaturizer": ("sparkdl_tpu.transformers.named_image",
                            "DeepImageFeaturizer"),
    "DeepImagePredictor": ("sparkdl_tpu.transformers.named_image",
                           "DeepImagePredictor"),
    "ImageTransformer": ("sparkdl_tpu.transformers.image_transform",
                         "ImageTransformer"),
    "TensorTransformer": ("sparkdl_tpu.transformers.tensor_transform",
                          "TensorTransformer"),
    # Reference-era aliases (TFImageTransformer / TFTransformer).
    "TFImageTransformer": ("sparkdl_tpu.transformers.image_transform",
                           "ImageTransformer"),
    "TFTransformer": ("sparkdl_tpu.transformers.tensor_transform",
                      "TensorTransformer"),
    "KerasImageFileTransformer": ("sparkdl_tpu.transformers.keras_image",
                                  "KerasImageFileTransformer"),
    "KerasTransformer": ("sparkdl_tpu.transformers.keras_tensor",
                         "KerasTransformer"),
    "KerasImageFileEstimator": (
        "sparkdl_tpu.estimators.keras_image_file_estimator",
        "KerasImageFileEstimator"),
    "LogisticRegression": ("sparkdl_tpu.estimators.logistic_regression",
                           "LogisticRegression"),
    "registerKerasImageUDF": ("sparkdl_tpu.udf.keras_image_model",
                              "registerKerasImageUDF"),
    # SQL-catalog seam (reference makeGraphUDF's registration half)
    "register_udf": ("sparkdl_tpu.data.spark_binding", "register_udf"),
    "DataFrame": ("sparkdl_tpu.data.frame", "DataFrame"),
    "Pipeline": ("sparkdl_tpu.params.pipeline", "Pipeline"),
    "CrossValidator": ("sparkdl_tpu.params.tuning", "CrossValidator"),
    "TrainValidationSplit": ("sparkdl_tpu.params.tuning",
                             "TrainValidationSplit"),
    "ParamGridBuilder": ("sparkdl_tpu.params.tuning", "ParamGridBuilder"),
    "ClassificationEvaluator": ("sparkdl_tpu.estimators.evaluators",
                                "ClassificationEvaluator"),
    "BinaryClassificationEvaluator": ("sparkdl_tpu.estimators.evaluators",
                                      "BinaryClassificationEvaluator"),
    "LossEvaluator": ("sparkdl_tpu.estimators.evaluators",
                      "LossEvaluator"),
    # fitted-stage persistence (pyspark ML save/load semantics)
    "load_model": ("sparkdl_tpu.params.persistence", "load_stage"),
}

__all__ = list(_EXPORTS)


def __getattr__(name):
    try:
        module_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(f"module 'sparkdl_tpu' has no attribute {name!r}")
    import importlib
    value = getattr(importlib.import_module(module_name), attr)
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
