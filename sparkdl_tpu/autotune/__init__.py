"""Closed-loop infeed autotuner (docs/PERFORMANCE.md).

The layer that makes the measured pipeline self-driving: a
measure→decide→apply controller
(:mod:`sparkdl_tpu.autotune.core`) reads the per-window rates
the pipeline already records (``RunnerMetrics``, ``ServeMetrics``,
the obs registry) and moves the shape-safe throughput knobs at
runtime through attachable targets
(:mod:`sparkdl_tpu.autotune.targets`):

* ``RunnerTarget`` — ``prefetch_depth`` (the depth-N input look-ahead
  in ``dispatch_chunks``) and ``max_inflight``: raised while
  ``transfer_wait_seconds`` dominates wall time, shed on backend
  degrade / memory-pressure signals;
* ``ServeTarget`` — the serve dispatcher's coalesce window
  (``ModelSession.max_wait_s``): shrunk when batch fill saturates,
  grown when fill is poor and p99 headroom exists;
* ``RechunkTarget`` — the device batch / engine re-chunk hint, moved
  only along a pre-warmed shape ladder (zero cold retraces);
* ``PipelineTarget`` — the parallel host pipeline's worker count and
  read-ahead window (``data/pipeline.py``): deepened (trial-gated)
  while the live roofline says the decode lane binds, shed on memory
  pressure;
* ``FleetTarget`` — a fleet-registry model's replica count
  (``sparkdl_tpu/fleet``): grown (grow-only, warm-started from the
  persisted AOT cache) only while the roofline says the serve lane
  binds AND replica queues stay deep.

Armed by ``SPARKDL_TPU_AUTOTUNE=1`` or ``controller().arm()``;
disarmed, the hot-path :func:`poll` hook is a single armed-check (the
tracer's shared-no-op regime). Decisions use hysteresis + bounded
steps and are fully observable: the ``autotune`` span lane,
``autotune.decisions/oscillations/clamps`` registry counters,
``autotune.knob.*`` gauges, and controller state in every flight
bundle.
"""

from sparkdl_tpu.autotune.core import (
    AutotuneController,
    Knob,
    Proposal,
    controller,
    poll,
)
from sparkdl_tpu.autotune.targets import (
    FleetTarget,
    PipelineTarget,
    RechunkTarget,
    RunnerTarget,
    ServeTarget,
)

__all__ = [
    "AutotuneController",
    "FleetTarget",
    "Knob",
    "PipelineTarget",
    "Proposal",
    "RechunkTarget",
    "RunnerTarget",
    "ServeTarget",
    "controller",
    "poll",
]
