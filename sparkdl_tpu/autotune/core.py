"""Closed-loop infeed autotuner: the measure→decide→apply controller.

Every throughput-critical knob in the pipeline used to be hand-frozen:
runner strategy and ``max_inflight`` defaulted from a platform guess,
input prefetch was pinned at depth 1, the engine re-chunk hint and the
serve coalesce window were static config — while the process
continuously measured exactly the signals needed to set them
(``transfer_wait_seconds``, ``ship.inflight_peak``, serve fill ratio
and p99). This module closes the loop, the tf.data lesson (Murray et
al., 2021: autotuned pipeline parallelism/prefetch beats static expert
configs across heterogeneous hosts) applied to a link whose bandwidth
swings several-x between minutes.

Shape of the loop:

* **measure** — attached targets (:mod:`sparkdl_tpu.autotune.targets`)
  diff the per-object metrics the pipeline already keeps
  (``RunnerMetrics``, ``ServeMetrics``) into per-window rates; nothing
  new is sampled on the hot path.
* **decide** — targets emit bounded single-step :class:`Proposal`\\ s
  (one rung / ±1 / one multiplicative notch) gated by hysteresis: a
  per-knob cooldown after every change, an explore→evaluate→revert
  trial for speculative moves, and a freeze after a reverted trial so
  a knob that didn't pay stops being poked. A quick direction flip is
  counted as an oscillation (``autotune.oscillations``), refused, and
  backed off — the controller must settle, not hunt.
* **apply** — knob writes are single int/float attribute stores that
  the owning hot loop re-reads at its next unit of work
  (``runner.run`` reads strategy/inflight/depth per call, the serve
  dispatcher reads ``max_wait_s`` per collect, the engine re-reads the
  re-chunk hint per block) — so applies never interrupt a dispatch,
  never hold a hot-path lock, and are watchdog-safe by construction.
  Shape-changing knobs move only along a pre-warmed ladder
  (:class:`~sparkdl_tpu.autotune.targets.RechunkTarget`), degrading
  PR 4's "every dispatch is ONE compiled shape" to "one of K
  pre-warmed shapes, zero cold retraces".

Arming follows the tracer/watchdog precedent: ``SPARKDL_TPU_AUTOTUNE=1``
in the environment or :meth:`AutotuneController.arm` (the override
wins); the step cadence is ``SPARKDL_TPU_AUTOTUNE_INTERVAL_S`` (default
2s; a typo degrades to the default with one warning). Disarmed,
:func:`poll` — the hook the runners and the serve dispatcher call after
each unit of work — returns after a single armed-check, the same
shared-no-op regime as the tracer (<10µs, pinned by
``tests/test_autotune.py``). There is no controller thread: steps run
on the hot-loop thread that happened to poll past the interval, so an
idle pipeline is never re-tuned on stale windows and the controller
adds no new thread that can wedge.

Observability: every step/apply lands on the ``autotune`` span lane,
decisions/oscillations/clamps count into the metrics registry,
per-knob values publish as ``autotune.knob.<target>.<knob>`` gauges,
and :meth:`AutotuneController.state` rides in every flight-recorder
bundle (docs/OBSERVABILITY.md, docs/PERFORMANCE.md).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, List, Optional

from sparkdl_tpu.obs.registry import default_registry
from sparkdl_tpu.obs.trace import span

logger = logging.getLogger(__name__)

_TRUE = ("1", "true", "yes", "on")

#: step cadence (seconds) when SPARKDL_TPU_AUTOTUNE_INTERVAL_S is unset
#: — long enough for a window to hold several dispatches, short enough
#: to track a link whose bandwidth moves between minutes
DEFAULT_INTERVAL_S = 2.0


def _env_armed() -> bool:
    return os.environ.get("SPARKDL_TPU_AUTOTUNE", "").lower() in _TRUE


# (raw env string, parsed value): read per armed step — a config typo
# must warn ONCE per value, not per step (the watchdog-threshold
# precedent)
_env_interval_cache: Optional[tuple] = None


def _env_interval() -> float:
    global _env_interval_cache
    raw = os.environ.get("SPARKDL_TPU_AUTOTUNE_INTERVAL_S", "")
    cached = _env_interval_cache
    if cached is not None and cached[0] == raw:
        return cached[1]
    try:
        v = float(raw) if raw else DEFAULT_INTERVAL_S
        if v < 0:
            raise ValueError(v)
    except ValueError:
        logger.warning(
            "SPARKDL_TPU_AUTOTUNE_INTERVAL_S=%r is not a non-negative "
            "number; using the default %.1fs", raw, DEFAULT_INTERVAL_S)
        v = DEFAULT_INTERVAL_S
    _env_interval_cache = (raw, v)
    return v


class Knob:
    """One tunable: bounds, a getter/setter pair, and the hysteresis
    state the controller keeps per knob (cooldown after a change,
    freeze after a reverted trial, last direction for oscillation
    detection). Mutated only on the controller's single-stepper (the
    step lock serializes steps), so it carries no lock of its own."""

    __slots__ = ("name", "_get", "_set", "lo", "hi", "cooldown",
                 "frozen_for", "last_dir", "steps_since_change")

    def __init__(self, name: str, get: Callable[[], Any],
                 set: Callable[[Any], None], lo, hi):
        if lo > hi:
            raise ValueError(f"knob {name!r}: lo {lo} > hi {hi}")
        self.name = name
        self._get = get
        self._set = set
        self.lo = lo
        self.hi = hi
        self.cooldown = 0
        self.frozen_for = 0
        self.last_dir = 0
        self.steps_since_change = 0

    @property
    def value(self):
        return self._get()

    def set(self, v) -> None:
        self._set(v)

    def clamp(self, v):
        return min(self.hi, max(self.lo, v))

    def usable(self) -> bool:
        """Whether the controller would currently accept a non-forced
        change (targets use this to skip proposing into a cooldown)."""
        return self.cooldown == 0 and self.frozen_for == 0

    def freeze(self, steps: int) -> None:
        """Stop accepting changes for ``steps`` controller steps — the
        explore-didn't-pay / oscillation backoff."""
        self.frozen_for = max(self.frozen_for, int(steps))

    def tick(self) -> None:
        self.cooldown = max(0, self.cooldown - 1)
        self.frozen_for = max(0, self.frozen_for - 1)
        self.steps_since_change += 1

    def describe(self) -> dict:
        return {"name": self.name, "value": self.value,
                "lo": self.lo, "hi": self.hi,
                "cooldown": self.cooldown,
                "frozen_for": self.frozen_for,
                "last_dir": self.last_dir}


class Proposal:
    """One bounded knob change a target wants: ``force`` marks trial
    reverts, which bypass cooldown and never count as oscillation (a
    revert is the trial machinery working, not the loop hunting)."""

    __slots__ = ("knob", "value", "reason", "force")

    def __init__(self, knob: Knob, value, reason: str,
                 force: bool = False):
        self.knob = knob
        self.value = value
        self.reason = reason
        self.force = force


class AutotuneController:
    """The process-wide measure→decide→apply loop (module docstring).
    One singleton (:func:`controller`) is what the hot-loop
    :func:`poll` hooks drive; standalone instances exist for tests."""

    # sparkdl-lint H3 contract: poll() can race from every hot-loop
    # thread and state() from a telemetry scrape — bookkeeping writes
    # hold self._lock (the step lock serializes the step body itself)
    _lock_guards = ("steps", "decisions_applied", "oscillations",
                    "clamps")

    #: steps a knob rests after any accepted change (hysteresis)
    cooldown_steps = 2
    #: a direction flip within this many steps of the last change is
    #: an oscillation — refused, counted, and frozen out
    osc_window = 3
    #: steps a knob stays frozen after a reverted trial / oscillation
    freeze_steps = 64
    #: initial steps that only build measurement windows (compile and
    #: cache warmup pollute the first rates — never act on them)
    warmup_steps = 2

    def __init__(self, interval_s: Optional[float] = None):
        # None → follow the env; a number → programmatic override
        self._interval_override = interval_s
        self._armed_override: Optional[bool] = None
        self._lock = threading.Lock()
        # serializes step bodies; poll() try-acquires so a hot loop
        # NEVER blocks on a step another thread is running
        self._step_lock = threading.Lock()
        self._targets: List[Any] = []
        self._last_step_t = float("-inf")
        self.steps = 0
        self.decisions_applied = 0
        self.oscillations = 0
        self.clamps = 0

    # -- arming --------------------------------------------------------------

    @property
    def armed(self) -> bool:
        ov = self._armed_override
        if ov is not None:
            return ov
        return _env_armed()

    @property
    def interval_s(self) -> float:
        if self._interval_override is not None:
            return self._interval_override
        return _env_interval()

    def arm(self, interval_s: Optional[float] = None) -> None:
        """Tune regardless of SPARKDL_TPU_AUTOTUNE; an explicit
        ``interval_s`` overrides the env cadence too (0 = decide on
        every poll — the deterministic bench/test mode)."""
        if interval_s is not None:
            if interval_s < 0:
                raise ValueError(
                    f"interval_s must be >= 0, got {interval_s}")
            self._interval_override = interval_s
        self._armed_override = True

    def disarm(self) -> None:
        """Stop tuning regardless of the env; attached targets keep
        their current knob values (the last applied config stands)."""
        self._armed_override = False

    def arm_from_env(self) -> None:
        """Drop the programmatic overrides; follow the env again."""
        self._armed_override = None
        self._interval_override = None

    def reset(self) -> None:
        """Detach every target, zero the bookkeeping, and follow the
        env again (bench/test epilogue — knob values already applied
        to runners/sessions are left as they are)."""
        with self._step_lock:
            with self._lock:
                self._targets.clear()
                self.steps = 0
                self.decisions_applied = 0
                self.oscillations = 0
                self.clamps = 0
            self._last_step_t = float("-inf")
        self.arm_from_env()

    # -- targets -------------------------------------------------------------

    def attach(self, target):
        """Register a target (RunnerTarget / ServeTarget /
        RechunkTarget — anything with ``name``, ``propose(warming)``,
        ``knobs()``, ``describe()``); returns it for chaining.

        If the controller is already armed and the target has an
        ``on_attach`` hook (RechunkTarget's ladder prewarm), it runs
        HERE, on the caller's setup thread — heavy one-time work
        (compiling every ladder rung) must not run inside a hot loop's
        first step, where it would eat a watchdog heartbeat budget."""
        if self.armed:
            prep = getattr(target, "on_attach", None)
            if prep is not None:
                prep()
        with self._lock:
            self._targets.append(target)
        return target

    def detach(self, target) -> None:
        with self._lock:
            if target in self._targets:
                self._targets.remove(target)

    def targets(self) -> List[Any]:
        with self._lock:
            return list(self._targets)

    # -- the loop ------------------------------------------------------------

    def maybe_step(self) -> None:
        """The :func:`poll` body: step iff the interval elapsed and no
        other thread is mid-step (try-lock — a hot loop never waits
        here)."""
        if time.perf_counter() - self._last_step_t < self.interval_s:
            return
        if not self._step_lock.acquire(blocking=False):
            return
        try:
            now = time.perf_counter()
            if now - self._last_step_t < self.interval_s:
                return
            self._step_locked(now)
        finally:
            self._step_lock.release()

    def step(self) -> None:
        """One deterministic measure→decide→apply round — what tests
        and the bench drive directly; production runs reach it through
        :func:`poll`."""
        with self._step_lock:
            self._step_locked(time.perf_counter())

    def _step_locked(self, now: float) -> None:
        self._last_step_t = now
        with self._lock:
            self.steps += 1
            step_no = self.steps
            targets = list(self._targets)
        if not targets:
            return
        warming = step_no <= self.warmup_steps
        with span("autotune.step", lane="autotune", step=step_no,
                  warming=warming):
            for target in targets:
                try:
                    proposals = target.propose(warming) or []
                except Exception:
                    logger.exception(
                        "autotune: target %r propose failed; skipping",
                        getattr(target, "name", target))
                    proposals = []
                for p in proposals:
                    self._apply(target, p)
                for knob in target.knobs():
                    knob.tick()

    def _apply(self, target, p: Proposal) -> bool:
        """Hysteresis + bounds around one knob write; returns whether
        the knob actually moved. Targets learn a refused trial by
        seeing the knob still at its old value next window."""
        knob = p.knob
        cur = knob.value
        if not p.force and not knob.usable():
            return False
        v = knob.clamp(p.value)
        clamped = v != p.value
        if v == cur:
            if clamped:
                # the proposal wanted past the bound and the bound is
                # where we already are — record the pressure
                self._count("clamps")
            return False
        direction = 1 if v > cur else -1
        if (not p.force and knob.last_dir
                and direction != knob.last_dir
                and knob.steps_since_change <= self.osc_window):
            # a quick direction flip is the loop hunting, not control:
            # refuse it, count it, and back the knob off hard
            self._count("oscillations")
            knob.freeze(self.freeze_steps)
            logger.warning(
                "autotune: refused oscillating change of %s.%s "
                "(%s -> %s within %d steps of the last move); knob "
                "frozen for %d steps", target.name, knob.name, cur, v,
                knob.steps_since_change, self.freeze_steps)
            return False
        with span("autotune.apply", lane="autotune",
                  target=target.name, knob=knob.name, frm=cur, to=v,
                  reason=str(p.reason)[:120]):
            knob.set(v)
        knob.last_dir = 0 if p.force else direction
        knob.cooldown = self.cooldown_steps
        knob.steps_since_change = 0
        if clamped:
            self._count("clamps")
        self._count("decisions")
        default_registry().gauge(
            f"autotune.knob.{target.name}.{knob.name}").set(float(v))
        logger.info("autotune: %s.%s %s -> %s (%s)", target.name,
                    knob.name, cur, v, p.reason)
        return True

    def _count(self, what: str) -> None:
        default_registry().counter(f"autotune.{what}").add()
        with self._lock:
            if what == "decisions":
                self.decisions_applied += 1
            elif what == "oscillations":
                self.oscillations += 1
            elif what == "clamps":
                self.clamps += 1

    # -- the scrape-able state (flight bundles, /statusz readers) ------------

    def state(self) -> dict:
        """Controller + per-target knob state for the flight
        recorder's bundles; every target describes independently — a
        broken target must not cost the postmortem."""
        with self._lock:
            targets = list(self._targets)
            out = {"armed": self.armed,
                   "interval_s": self.interval_s,
                   "steps": self.steps,
                   "warmup_steps": self.warmup_steps,
                   "decisions": self.decisions_applied,
                   "oscillations": self.oscillations,
                   "clamps": self.clamps}
        described = []
        for t in targets:
            try:
                described.append(t.describe())
            except Exception as e:
                described.append({"error": f"{type(e).__name__}: {e}"})
        out["targets"] = described
        return out

    # -- pickle discipline (StageMetrics precedent) --------------------------

    def __getstate__(self):
        # locks and attached targets (live runner/session handles) are
        # process-local; arming config and lifetime counters travel
        state = self.__dict__.copy()
        del state["_lock"]
        del state["_step_lock"]
        del state["_targets"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()
        self._step_lock = threading.Lock()
        self._targets = []
        self._last_step_t = float("-inf")


_CONTROLLER = AutotuneController()


def controller() -> AutotuneController:
    """THE process-wide controller the :func:`poll` hooks drive."""
    return _CONTROLLER


def poll() -> None:
    """The hot-loop hook (runner.run epilogues, the serve dispatcher):
    disarmed it returns after one armed-check — the tracer's
    shared-no-op regime, overhead pinned alongside the span bound."""
    c = _CONTROLLER
    if not c.armed:
        return
    c.maybe_step()
