"""Autotune targets: what the controller measures and which knobs it
may move.

Each target wraps one live object (a runner, a serve session) and
turns the cumulative metrics that object already keeps into per-window
rates — no new hot-path sampling. ``propose(warming)`` returns bounded
single-step :class:`~sparkdl_tpu.autotune.core.Proposal`\\ s; the
controller owns hysteresis, clamping, and oscillation refusal.

Speculative moves (deepening overlap, climbing the shape ladder) run
as **trials**: apply one step, evaluate the next traffic window's
throughput, keep the step only if it paid ``min_gain``, otherwise
revert and freeze the knob — so a knob that cannot help on this
host/link stops being poked instead of oscillating. Signal-shaped
moves (shrinking a saturated coalesce window, shedding overlap after a
backend degrade, stepping the ladder down under heavy padding) apply
directly off their signal with the controller's cooldown as the only
damping.

All knob writes are single int/float attribute stores the owning hot
loop re-reads at its next unit of work — shape-safe, lock-free,
watchdog-safe (controller module docstring).
"""

from __future__ import annotations

import itertools
import logging
from typing import List, Optional

import numpy as np

from sparkdl_tpu.autotune.core import Knob, Proposal
from sparkdl_tpu.obs.registry import default_registry

logger = logging.getLogger(__name__)

_SEQ = itertools.count(1)


class _TrialMixin:
    """The explore→evaluate→revert machinery shared by targets whose
    upward moves are speculative. A trial records (knob, old value,
    baseline throughput, proposed value); the next traffic window
    either keeps the move (gain ≥ ``min_gain``) or reverts and
    freezes. A trial whose proposal the controller refused (cooldown /
    oscillation guard) is dropped without judgment — the knob never
    moved, so there is nothing to evaluate."""

    #: relative throughput gain a trial must show to be kept
    min_gain = 0.02
    #: controller steps a knob rests after a reverted trial
    freeze_steps = 64
    #: how stale a ledger window may be and still count as a prior
    #: (in multiples of the ledger's own window length) — a verdict
    #: from minutes ago describes a different pipeline
    ledger_prior_max_windows = 10.0

    _trial: Optional[tuple] = None

    def _ledger_prior(self) -> Optional[str]:
        """The live roofline's ``bound_by`` verdict as a measured
        prior (obs/ledger.py — READ-only: targets never tick or write
        the ledger). ``None`` when no fresh window exists, so
        processes that never ran the ledger tune exactly as
        before."""
        from sparkdl_tpu.obs.ledger import ledger
        led = ledger()
        return led.last_bound(
            max_age_s=self.ledger_prior_max_windows * led.window_s)

    def _start_trial(self, knob: Knob, proposed, tput: float,
                     reason: str, out: List[Proposal]) -> None:
        self._trial = (knob, knob.value, tput, proposed)
        out.append(Proposal(knob, proposed, reason))

    def _eval_trial(self, tput: float, out: List[Proposal]) -> bool:
        """Returns True when a revert was emitted (the caller should
        not explore further this window).

        EVERY completed trial freezes its knob — kept gains persist
        but the next climb waits out the freeze epoch. Without this, a
        noisy window that happens to clear ``min_gain`` re-arms the
        trial immediately and the knob random-walks toward its bound
        instead of settling; with it, convergence is structural (each
        knob completes at most one trial per epoch) and a genuinely
        faster depth still climbs one validated step per epoch."""
        if self._trial is None:
            return False
        knob, old, base, proposed = self._trial
        self._trial = None
        if knob.value == old:
            return False        # controller refused the trial
        if tput < base * (1.0 + self.min_gain):
            knob.freeze(self.freeze_steps)
            out.append(Proposal(
                knob, old,
                f"revert {knob.name}: {tput:.1f} rows/s did not beat "
                f"{base:.1f} by {self.min_gain:.0%}; frozen "
                f"{self.freeze_steps} steps", force=True))
            return True
        knob.freeze(self.freeze_steps)      # kept — settle the epoch
        return False


class RunnerTarget(_TrialMixin):
    """Tunes a runner's overlap knobs: ``prefetch_depth`` (prefetch
    strategy) and ``max_inflight`` (any queued strategy).

    Raise path (trial-gated): while ``transfer_wait_seconds`` takes
    more than ``raise_wait_frac`` of the window's wall time, the ship
    path is stalling in drains while transfers could overlap — deepen
    the input look-ahead first (prefetch), then the result queue.
    Lower path (signal-shaped): a window that recorded
    ``ship.prefetch_degrade_events`` means the backend rejected the
    async PLACEMENT this look-ahead depends on — shed
    ``prefetch_depth`` one step toward its floor and stop trialing it
    up. The counter is placement-specific on purpose: the mixed
    ``ship.degrade_events`` total also counts missing
    ``copy_to_host_async`` (which says nothing about look-ahead) and
    would disable depth tuning on backends where placement works. It
    is process-global, which is semantically right — ``device_put``
    capability is a backend property, one backend per process.
    ``max_inflight`` is deliberately NOT shed on degrades:
    ``dispatch_chunks`` already shallows the result queue at runtime
    when host copies are missing, and a permanently-degraded backend
    (which re-probes once per run, counting an event every window)
    must not walk a healthy queue down to 1. A ``memory_pressure``
    hook (for TPU hosts that can read ``memory_stats``) is the
    legitimate reason to reclaim depth AND queue slots; depth that is
    merely unused is left alone — idle slots cost nothing on a
    healthy backend.

    Link-prior path (trial-gated, prior-vetoed): runners that expose
    the device-resident infeed ring (``infeed_ring`` /
    ``transfer_interleave``, runtime/runner.py) get two more knobs,
    deepened ONLY while the live roofline's latest window says
    ``bound_by == "link"`` (the PipelineTarget read-only-prior
    precedent) — ring slots hold HBM and interleave threads hold host
    cores, so growing either without evidence the link binds would
    spend real resources learning nothing. With no fresh ledger window
    neither knob moves. Runners without the attributes (or with the
    ring disabled) tune exactly as before."""

    #: fraction of window wall time blocked in device_get drains above
    #: which the overlap is deepened
    raise_wait_frac = 0.15

    def __init__(self, runner, name: Optional[str] = None,
                 max_inflight_cap: int = 32,
                 max_prefetch_depth: int = 8,
                 max_infeed_ring: int = 8,
                 max_interleave: int = 8,
                 memory_pressure=None):
        self.runner = runner
        self.name = name or f"runner{next(_SEQ)}"
        self.memory_pressure = memory_pressure
        self._inflight = Knob(
            "max_inflight",
            get=lambda: runner.max_inflight,
            set=lambda v: setattr(runner, "max_inflight", int(v)),
            lo=1, hi=int(max_inflight_cap))
        self._depth = Knob(
            "prefetch_depth",
            get=lambda: runner.prefetch_depth,
            set=lambda v: setattr(runner, "prefetch_depth", int(v)),
            lo=1, hi=int(max_prefetch_depth))
        # ring/interleave knobs only for runners that grew them
        # (hasattr, not isinstance: stub runners in tests and older
        # pickles simply tune without them)
        self._ring: Optional[Knob] = None
        if hasattr(runner, "infeed_ring"):
            self._ring = Knob(
                "infeed_ring",
                get=lambda: int(runner.infeed_ring),
                set=lambda v: setattr(runner, "infeed_ring", int(v)),
                lo=0, hi=int(max_infeed_ring))
        self._interleave: Optional[Knob] = None
        if hasattr(runner, "transfer_interleave"):
            self._interleave = Knob(
                "transfer_interleave",
                get=lambda: int(runner.transfer_interleave),
                set=lambda v: setattr(
                    runner, "transfer_interleave", int(v)),
                lo=0, hi=int(max_interleave))
        self._prev: Optional[tuple] = None
        self._prev_degrades: Optional[float] = None

    def knobs(self) -> List[Knob]:
        ks = [self._inflight, self._depth]
        if self._ring is not None:
            ks.append(self._ring)
        if self._interleave is not None:
            ks.append(self._interleave)
        return ks

    def _window(self) -> Optional[tuple]:
        """(rows/s, wait_frac, placement degrades) over the window
        since the last call; None when no traffic moved."""
        m = self.runner.metrics
        deg = default_registry().counter(
            "ship.prefetch_degrade_events").value
        cur = (m.rows, m.seconds, m.transfer_wait_seconds)
        prev, self._prev = self._prev, cur
        prev_deg, self._prev_degrades = self._prev_degrades, deg
        if prev is None:
            return None
        drows = cur[0] - prev[0]
        dsec = cur[1] - prev[1]
        dwait = cur[2] - prev[2]
        if drows <= 0 or dsec <= 0:
            return None
        return (drows / dsec, max(0.0, dwait / dsec),
                deg - (prev_deg or 0.0))

    def propose(self, warming: bool) -> List[Proposal]:
        w = self._window()
        out: List[Proposal] = []
        if w is None or warming:
            return out
        tput, wait_frac, degrades = w
        if self._eval_trial(tput, out):
            return out
        if self.runner.strategy == "immediate":
            return out          # no queue to tune
        if self.memory_pressure is not None and self.memory_pressure():
            # HBM pressure: reclaim overlap buffers — depth first,
            # then the result queue
            if self._depth.value > self._depth.lo:
                out.append(Proposal(self._depth, self._depth.value - 1,
                                    "memory pressure"))
            elif self._inflight.value > self._inflight.lo:
                out.append(Proposal(self._inflight,
                                    self._inflight.value - 1,
                                    "memory pressure"))
            return out
        if degrades > 0 and self._depth.value > self._depth.lo:
            # the backend refused async placement this window: stop
            # asking for look-ahead (depth only — see class docstring
            # for why max_inflight must NOT follow)
            out.append(Proposal(self._depth, self._depth.value - 1,
                                "placement degrade events in window"))
        if wait_frac >= self.raise_wait_frac:
            prior = self._ledger_prior()
            if prior == "decode":
                # the live roofline says the DECODE lane binds right
                # now: deepening ship-side overlap cannot relieve an
                # input-side wall, and the trial would burn a freeze
                # epoch learning that. The prior is consulted, never
                # written (obs/ledger.py stays read-only to targets).
                return out
            reason = (f"transfer_wait is {wait_frac:.0%} of wall; "
                      "deepen overlap")
            if prior is not None:
                reason += f" (ledger prior: bound by {prior})"
            if (self.runner.strategy == "prefetch" and degrades == 0
                    and self._depth.usable()
                    and self._depth.value < self._depth.hi):
                self._start_trial(self._depth, self._depth.value + 1,
                                  tput, reason, out)
            elif (self._inflight.usable()
                    and self._inflight.value < self._inflight.hi):
                self._start_trial(self._inflight,
                                  self._inflight.value + 1, tput,
                                  reason, out)
        if out:
            return out          # one move per window
        # link-prior path: grow the infeed ring (then the interleave
        # width) ONLY while the live roofline says the link binds —
        # see class docstring. 0→2 jumps the K≥2 floor in one step
        # (depth 1 is not a ring); past it, single validated steps.
        if (self._ring is None and self._interleave is None):
            return out
        prior = self._ledger_prior()
        if prior != "link":
            return out
        reason = "ledger prior: bound by link; keep bytes resident"
        if (self._ring is not None and self._ring.usable()
                and self._ring.value < self._ring.hi):
            nxt = 2 if self._ring.value < 2 else self._ring.value + 1
            self._start_trial(self._ring, nxt, tput, reason, out)
        elif (self._interleave is not None
                and self._interleave.usable()
                and self._interleave.value < self._interleave.hi):
            cur = self._interleave.value
            nxt = 2 if cur < 2 else cur + 1
            self._start_trial(self._interleave, nxt, tput,
                              reason + "; widen transfer streams", out)
        return out

    def describe(self) -> dict:
        return {"name": self.name, "kind": "runner",
                "strategy": getattr(self.runner, "strategy", None),
                "trial_open": self._trial is not None,
                "ledger_prior": self._ledger_prior(),
                "knobs": [k.describe() for k in self.knobs()]}


class PipelineTarget(_TrialMixin):
    """Tunes a :class:`~sparkdl_tpu.data.engine.LocalEngine`'s
    parallel host pipeline (``data/pipeline.py``):
    ``pipeline_workers`` (the decode worker pool) and
    ``pipeline_read_ahead`` (the ordered re-merge's look-ahead
    window).

    Deepening is **trial-gated and prior-vetoed in the raising
    direction**: the pool only helps while the DECODE lane binds, so a
    worker (then read-ahead) step up is proposed only when the live
    roofline's latest window says ``bound_by == "decode"``
    (obs/ledger.py — read-only, the RunnerTarget precedent) and is
    kept only if the next window's merged rows per pooled-stream-active
    second pays ``min_gain``;
    otherwise it reverts and the knob freezes for the epoch. With no
    fresh ledger window there is no evidence a deeper pool can pay —
    the target proposes nothing rather than exploring blind (workers
    are processes; idle ones are not free the way idle queue slots
    are).

    Shedding is signal-shaped: a ``memory_pressure`` hook (the
    RunnerTarget shape — e.g. a host-RSS check) reclaims read-ahead
    first (each look-ahead slot parks one decoded fragment), then
    workers. Knob writes are single int attribute stores the engine
    re-reads at its next ``execute()``/submission wave — shape-safe,
    lock-free, watchdog-safe (the repo-wide apply discipline); worker
    count 1 means serial (the pool disengages entirely)."""

    def __init__(self, engine, name: Optional[str] = None,
                 max_workers: Optional[int] = None,
                 max_read_ahead: int = 16,
                 memory_pressure=None):
        import os
        self.engine = engine
        self.name = name or f"pipeline{next(_SEQ)}"
        self.memory_pressure = memory_pressure
        cap = int(max_workers if max_workers is not None
                  else max(2, os.cpu_count() or 2))
        self._workers = Knob(
            "pipeline_workers",
            get=lambda: int(engine.pipeline_workers),
            set=lambda v: setattr(engine, "pipeline_workers", int(v)),
            lo=1, hi=cap)
        self._read_ahead = Knob(
            "pipeline_read_ahead",
            get=lambda: int(engine.pipeline_read_ahead),
            set=lambda v: setattr(engine, "pipeline_read_ahead",
                                  int(v)),
            lo=1, hi=int(max_read_ahead))
        # the disaggregated decode fleet's fan-out width
        # (sparkdl_tpu/inputsvc; docs/DATA_SERVICE.md): only an engine
        # CONFIGURED with endpoints grows this knob — the ceiling is
        # the provisioned fleet size, and the apply is the same plain
        # int attribute store the engine re-reads per execute()
        fleet = len(getattr(engine, "inputsvc_endpoints", None) or ())
        self._remote: Optional[Knob] = None
        if fleet >= 1:
            self._remote = Knob(
                "inputsvc_workers",
                get=lambda: int(engine.inputsvc_workers),
                set=lambda v: setattr(engine, "inputsvc_workers",
                                      int(v)),
                lo=1, hi=fleet)
        self._prev: Optional[tuple] = None

    def knobs(self) -> List[Knob]:
        out = [self._workers, self._read_ahead]
        if self._remote is not None:
            out.append(self._remote)
        return out

    def _window(self) -> Optional[float]:
        """Merged rows per pooled-stream-ACTIVE second over the window
        since the last call — ``pipeline.rows`` over
        ``pipeline.stream_seconds``, both fed by the ordered re-merge
        (the RunnerTarget active-seconds precedent: wall-clock idle
        between executes must not deflate a trial's evaluation and
        spuriously revert-freeze a good step). None when no pooled
        stream finished in the window."""
        reg = default_registry()
        # remote decode streams (sparkdl_tpu/inputsvc) feed the same
        # merged-rows-per-active-second signal through their own
        # counters — a purely remote stream must still evaluate an
        # inputsvc_workers trial
        rows = (reg.counter("pipeline.rows").value
                + reg.counter("inputsvc.rows").value)
        active = (reg.counter("pipeline.stream_seconds").value
                  + reg.counter("inputsvc.stream_seconds").value)
        prev, self._prev = self._prev, (rows, active)
        if prev is None:
            return None
        drows = rows - prev[0]
        dsec = active - prev[1]
        if drows <= 0 or dsec <= 0:
            return None
        return drows / dsec

    def propose(self, warming: bool) -> List[Proposal]:
        tput = self._window()
        out: List[Proposal] = []
        if tput is None or warming:
            return out
        if self._eval_trial(tput, out):
            return out
        if self.memory_pressure is not None and self.memory_pressure():
            # reclaim look-ahead fragments first, then whole workers
            if self._read_ahead.value > self._read_ahead.lo:
                out.append(Proposal(self._read_ahead,
                                    self._read_ahead.value - 1,
                                    "memory pressure"))
            elif self._workers.value > self._workers.lo:
                out.append(Proposal(self._workers,
                                    self._workers.value - 1,
                                    "memory pressure"))
            return out
        if self._ledger_prior() != "decode":
            # the decode lane is not the wall right now: a deeper host
            # pool cannot move the pipeline, and the trial would burn
            # a freeze epoch learning that
            return out
        reason = "ledger prior: decode lane binds; deepen host pipeline"
        if (self._remote is not None and self._remote.usable()
                and self._remote.value < self._remote.hi):
            # widen the PROVISIONED remote fleet before growing local
            # pool processes: remote lanes are capacity that already
            # exists (the trial still validates the step pays)
            self._start_trial(
                self._remote, self._remote.value + 1, tput,
                "ledger prior: decode lane binds; widen the remote "
                "decode fleet", out)
        elif self._workers.usable() \
                and self._workers.value < self._workers.hi:
            self._start_trial(self._workers, self._workers.value + 1,
                              tput, reason, out)
        elif self._read_ahead.usable() \
                and self._read_ahead.value < self._read_ahead.hi:
            self._start_trial(self._read_ahead,
                              self._read_ahead.value + 1, tput,
                              reason + " (read-ahead)", out)
        return out

    def describe(self) -> dict:
        return {"name": self.name, "kind": "pipeline",
                "trial_open": self._trial is not None,
                "ledger_prior": self._ledger_prior(),
                "knobs": [k.describe() for k in self.knobs()]}


class ServeTarget:
    """Tunes one serve session's dynamic micro-batching window
    (``ModelSession.max_wait_s``): shrink it when the queue saturates
    batches without waiting (the window only adds latency then), grow
    it when fill is poor and the p99 budget has headroom (waiting
    longer is exactly how coalescing buys fill). The deadband between
    ``lo_fill`` and ``hi_fill`` plus the controller cooldown is the
    hysteresis — load that sits in the band moves nothing."""

    #: window fill below which the coalesce window grows
    lo_fill = 0.6
    #: window fill above which the coalesce window shrinks
    hi_fill = 0.95
    #: multiplicative step (bounded: one notch per decision)
    grow_factor = 1.5

    def __init__(self, session, name: Optional[str] = None,
                 min_wait_s: float = 0.0,
                 max_wait_cap_s: Optional[float] = None,
                 latency_budget_s: Optional[float] = None):
        self.session = session
        self.name = name or f"serve:{session.name}"
        if max_wait_cap_s is None:
            max_wait_cap_s = max(4.0 * session.max_wait_s, 0.02)
        if latency_budget_s is None:
            latency_budget_s = session.config.default_deadline_s
        self.latency_budget_s = latency_budget_s
        self._wait = Knob(
            "max_wait_s",
            get=lambda: session.max_wait_s,
            set=lambda v: setattr(session, "max_wait_s", float(v)),
            lo=float(min_wait_s), hi=float(max_wait_cap_s))
        self._prev: Optional[tuple] = None

    def knobs(self) -> List[Knob]:
        return [self._wait]

    def propose(self, warming: bool) -> List[Proposal]:
        m = self.session.metrics
        cur_counts = (m.batches, m.batch_rows, m.batch_capacity_rows)
        prev, self._prev = self._prev, cur_counts
        if prev is None or warming:
            return []
        dbatches = cur_counts[0] - prev[0]
        dcap = cur_counts[2] - prev[2]
        if dbatches <= 0 or dcap <= 0:
            return []
        fill = (cur_counts[1] - prev[1]) / dcap
        cur = self._wait.value
        if fill >= self.hi_fill and cur > self._wait.lo:
            # saturated: arrivals outrun dispatch — the window is pure
            # added latency now
            return [Proposal(self._wait, max(self._wait.lo, cur / 2.0),
                             f"fill {fill:.0%} saturated; shrink the "
                             "coalesce window")]
        if fill < self.lo_fill and cur < self._wait.hi:
            new = min(self._wait.hi,
                      max(cur * self.grow_factor, 0.001))
            if self.latency_budget_s is not None:
                p99 = m.latency_seconds(0.99)
                if p99 + (new - cur) > 0.5 * self.latency_budget_s:
                    return []   # no p99 headroom to spend on fill
            return [Proposal(self._wait, new,
                             f"fill {fill:.0%}; grow the coalesce "
                             "window for fill")]
        return []

    def describe(self) -> dict:
        return {"name": self.name, "kind": "serve",
                "model": self.session.name,
                "latency_budget_s": self.latency_budget_s,
                "knobs": [k.describe() for k in self.knobs()]}


class RechunkTarget(_TrialMixin):
    """Moves a :class:`~sparkdl_tpu.runtime.runner.BatchRunner`'s
    device batch — and with it the engine's re-chunk hint, which
    follows ``preferred_chunk`` live through
    :class:`~sparkdl_tpu.data.frame.LiveBatchHint` — along a small
    pre-warmed shape **ladder**.

    The ladder is the retrace guarantee: :meth:`prewarm` traces and
    compiles every rung up front (one zeros run each through the jit
    cache), so PR 4's "every dispatch is ONE compiled shape" degrades
    to "one of K pre-warmed shapes, **zero cold retraces**" — the
    sparkdl-lint H2 discipline kept at runtime. Decisions only ever
    move one rung and only among warmed rungs.

    Down moves are signal-shaped: a window whose mean dispatched fill
    (rows / batches·chunk) sits under ``down_fill`` is paying the
    small-partition padding tax — a smaller rung strictly reduces pad.
    Up moves (amortizing per-dispatch latency on high-RTT links) are
    speculative and trial-gated.

    NOT for runners registered behind a ``ModelServer`` — a serve
    session fixes its chunk at registration (``session.chunk``) and
    its warmup covers exactly that one shape."""

    #: window mean batch fill below which the ladder steps down
    down_fill = 0.5
    #: window mean batch fill above which an up-trial may start
    up_fill = 0.98

    def __init__(self, runner, ladder=None, name: Optional[str] = None):
        self.runner = runner
        self.name = name or f"rechunk{next(_SEQ)}"
        base = int(runner.batch_size)
        if ladder is None:
            ladder = {max(1, base // 2), base, base * 2}
        self.ladder = sorted({int(r) for r in ladder})
        if any(r <= 0 for r in self.ladder):
            raise ValueError(f"ladder rungs must be positive, got "
                             f"{self.ladder}")
        if base not in self.ladder:
            raise ValueError(
                f"runner batch_size {base} must be one of the ladder "
                f"rungs {self.ladder} (the current shape is warmed by "
                "construction)")
        self.warmed = False
        self._rung = Knob(
            "ladder_rung",
            get=self._current_rung,
            set=self._apply_rung,
            lo=0, hi=len(self.ladder) - 1)
        self._prev: Optional[tuple] = None

    def _current_rung(self) -> int:
        try:
            return self.ladder.index(int(self.runner.batch_size))
        except ValueError:
            return -1           # moved off-ladder externally

    def _apply_rung(self, idx) -> None:
        self.runner.batch_size = self.ladder[int(idx)]

    def knobs(self) -> List[Knob]:
        return [self._rung]

    def prewarm(self) -> int:
        """Trace + compile every rung's shape into the runner's jit
        cache — DIRECTLY through ``model_fn.jitted()`` (the exact
        callable ``_run_device`` dispatches), never by cycling the
        live ``batch_size``: a concurrent ``run()`` on another thread
        must never observe a transient rung (runner.run snapshots
        batch_size per call, but the snapshot of a mid-prewarm value
        would be a cold shape). Host backends and unknown-dim
        signatures no-op, the ``warmup_runner`` discipline.
        Idempotent; returns the number of rungs actually warmed.
        Runs at ``controller().attach`` time on the setup thread (the
        ``on_attach`` hook) when the controller is already armed; the
        lazy fallback in :meth:`propose` covers targets attached
        before arming — that path pays the compile inside a controller
        step, so prefer arm-then-attach for latency-sensitive
        processes."""
        if self.warmed:
            return 0
        mf = self.runner.model_fn
        sig = mf.input_signature
        if (getattr(mf, "backend", None) != "jax"
                or any(d is None
                       for shape, _ in sig.values() for d in shape)):
            self.warmed = True
            return 0            # nothing jitted to warm
        fn = mf.jitted()
        params = mf.device_params()
        for rung in self.ladder:
            zeros = {k: np.zeros((rung,) + tuple(shape), dtype)
                     for k, (shape, dtype) in sig.items()}
            fn(params, zeros)
        self.warmed = True
        # every rung is compiled — mark the model's programs STEADY in
        # the compile log (obs/compile_log.py): the one-of-K-prewarmed
        # guarantee becomes a runtime invariant, and any OFF-ladder
        # shape from here on counts compile.unexpected_retraces with a
        # diff naming the argument that moved
        from sparkdl_tpu.obs.compile_log import compile_log
        compile_log().mark_model_steady(mf, reason="prewarm")
        logger.info("autotune: %s pre-warmed %d ladder rungs %s",
                    self.name, len(self.ladder), self.ladder)
        return len(self.ladder)

    # controller().attach runs this on the setup thread when armed —
    # the ladder compile must not land inside a hot loop's first step
    on_attach = prewarm

    def propose(self, warming: bool) -> List[Proposal]:
        m = self.runner.metrics
        if not warming and not self.warmed:
            # prewarm FIRST, then baseline the window after it — the
            # ladder's zeros runs must not read as traffic
            self.prewarm()
            self._prev = (m.rows, m.batches, m.seconds)
            return []
        cur_counts = (m.rows, m.batches, m.seconds)
        prev, self._prev = self._prev, cur_counts
        if warming or prev is None:
            return []
        drows = cur_counts[0] - prev[0]
        dbatches = cur_counts[1] - prev[1]
        dsec = cur_counts[2] - prev[2]
        if drows <= 0 or dbatches <= 0 or dsec <= 0:
            return []
        out: List[Proposal] = []
        tput = drows / dsec
        if self._eval_trial(tput, out):
            return out
        idx = self._rung.value
        if idx < 0:
            return []           # batch_size moved off-ladder externally
        fill = drows / (dbatches * self.runner.batch_size)
        if fill < self.down_fill and idx > self._rung.lo:
            out.append(Proposal(
                self._rung, idx - 1,
                f"batch fill {fill:.0%}: padding tax — step the shape "
                f"ladder down to {self.ladder[idx - 1]}"))
        elif (fill >= self.up_fill and idx < self._rung.hi
                and self._rung.usable()):
            self._start_trial(
                self._rung, idx + 1, tput,
                f"batch fill {fill:.0%}: amortize per-dispatch "
                f"latency — trial rung {self.ladder[idx + 1]}", out)
        return out

    def describe(self) -> dict:
        return {"name": self.name, "kind": "rechunk",
                "ladder": list(self.ladder),
                "batch_size": int(self.runner.batch_size),
                "prewarmed": self.warmed,
                "trial_open": self._trial is not None,
                "knobs": [k.describe() for k in self.knobs()]}


class FleetTarget(_TrialMixin):
    """Grows a logical model's replica count through the fleet
    registry when the live roofline says SERVING is the binding
    ceiling and the replicas' request queues stay deep.

    The knob is ``ModelRegistry.scale`` — grow-only (scale never tears
    down a live session mid-traffic), so there is no trial/revert
    machinery here: a replica is added only behind TWO measured gates
    and the knob's own cooldown, never speculatively:

    * the ledger's ``bound_by`` verdict must be the **serve** lane
      (``_TrialMixin._ledger_prior()``; obs/ledger.py) — compute- or
      decode-bound pipelines gain nothing from more serve sessions,
      and a process that never ran the ledger never scales (no prior,
      no growth: the expensive knob needs positive evidence);
    * the mean queue depth per replica must exceed
      ``grow_depth_batches`` dispatch batches — momentary bursts the
      coalesce window absorbs do not count.

    Growth is cheap precisely because of the warm-start cache: the new
    replica deserializes the persisted AOT executable instead of
    compiling (fleet/warmstart.py), which is why this knob is safe to
    hand to the controller at all.
    """

    #: mean per-replica queue depth (in dispatch batches) that reads
    #: as "persistently behind" — below it the fleet never grows
    grow_depth_batches = 2.0

    def __init__(self, registry, model: str,
                 name: Optional[str] = None,
                 max_replicas: int = 4):
        self.registry = registry
        self.model = model
        self.name = name or f"fleet:{model}"
        entry = registry.entry(model)     # typed KeyError surface
        self._replicas = Knob(
            "replicas",
            get=lambda: len(registry.entry(model).replicas),
            set=lambda v: registry.scale(model, int(v)),
            lo=len(entry.replicas), hi=int(max_replicas))

    def knobs(self) -> List[Knob]:
        return [self._replicas]

    def _mean_depth(self) -> Optional[float]:
        """Mean request-queue depth across the model's live replicas
        (``ModelSession.queue_depth()``), ``None`` when unreadable."""
        try:
            entry = self.registry.entry(self.model)
            server = self.registry._server
            depths = [server.session(r).queue_depth()
                      for r in entry.replicas]
        # sparkdl-lint: allow[H12] -- measurement probe: a replica mid-teardown means "no signal this window", not a controller crash
        except Exception:
            return None
        return (sum(depths) / len(depths)) if depths else None

    def propose(self, warming: bool) -> List[Proposal]:
        if warming or not self._replicas.usable():
            return []
        cur = self._replicas.value
        if cur >= self._replicas.hi:
            return []
        if self._ledger_prior() != "serve":
            return []           # the ceiling is elsewhere — hold
        depth = self._mean_depth()
        batch = self.registry.entry(self.model).batch_size
        if depth is None or depth < self.grow_depth_batches * batch:
            return []
        return [Proposal(
            self._replicas, cur + 1,
            f"serve-bound with mean queue depth {depth:.0f} rows "
            f"(≥ {self.grow_depth_batches:g} batches of {batch}) — "
            f"grow {self.model!r} to {cur + 1} replicas")]

    def describe(self) -> dict:
        return {"name": self.name, "kind": "fleet",
                "model": self.model,
                "ledger_prior": self._ledger_prior(),
                "knobs": [k.describe() for k in self.knobs()]}
