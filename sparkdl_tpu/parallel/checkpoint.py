"""Train-state checkpoint/resume via orbax.

The reference had load-only checkpointing (SURVEY §5: ``TFInputGraph``
read TF checkpoints/SavedModels, but no training state was ever saved —
a crashed estimator fit restarted from scratch). Orbax save/restore of
the full :class:`~sparkdl_tpu.parallel.train.TrainState` closes that
gap: fine-tunes resume at the last saved step.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from sparkdl_tpu.parallel.train import TrainState

_STATE_KEY = "train_state"


def _as_saveable(state: TrainState) -> dict:
    """The array-valued part of the state (apply_fn/tx are code, not
    data — reconstructed by the caller on restore)."""
    return {
        "step": state.step,
        "params": state.params,
        "opt_state": state.opt_state,
        "batch_stats": state.batch_stats,
    }


def save_checkpoint(directory: str, state: TrainState, step: int,
                    keep: int = 3) -> str:
    """Save the state under ``directory/step_{step}``; prunes to the
    newest ``keep`` checkpoints. Returns the checkpoint path."""
    directory = os.path.abspath(directory)
    with ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=keep),
    ) as mgr:
        mgr.save(step, args=ocp.args.StandardSave(_as_saveable(state)))
        mgr.wait_until_finished()
    return os.path.join(directory, str(step))


def latest_step(directory: str) -> Optional[int]:
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return None
    with ocp.CheckpointManager(directory) as mgr:
        return mgr.latest_step()


def _abstract_leaf(leaf):
    """Template leaf for StandardRestore: shape/dtype, plus the leaf's
    sharding when it is a device array — so a state laid out by
    ``shard_train_step`` restores straight into the same mesh layout
    (works multi-host, where materializing to numpy would not)."""
    if isinstance(leaf, jax.Array):
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                    sharding=leaf.sharding)
    arr = np.asarray(leaf)
    return jax.ShapeDtypeStruct(arr.shape, arr.dtype)


def restore_checkpoint(directory: str, state: TrainState,
                       step: Optional[int] = None) -> TrainState:
    """Restore into the structure of ``state`` (shapes/dtypes/shardings
    taken from it; pass a freshly-built state). ``step=None`` →
    latest."""
    directory = os.path.abspath(directory)
    template = jax.tree.map(_abstract_leaf, _as_saveable(state))
    with ocp.CheckpointManager(directory) as mgr:
        if step is None:
            step = mgr.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found under {directory}")
        restored = mgr.restore(
            step, args=ocp.args.StandardRestore(template))
    return state.replace(
        step=restored["step"],
        params=restored["params"],
        opt_state=restored["opt_state"],
        batch_stats=restored["batch_stats"])
