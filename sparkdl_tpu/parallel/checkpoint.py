"""Train-state checkpoint/resume via orbax.

The reference had load-only checkpointing (SURVEY §5: ``TFInputGraph``
read TF checkpoints/SavedModels, but no training state was ever saved —
a crashed estimator fit restarted from scratch). Orbax save/restore of
the full :class:`~sparkdl_tpu.parallel.train.TrainState` closes that
gap: fine-tunes resume at the last saved step.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from sparkdl_tpu.parallel.train import TrainState

_STATE_KEY = "train_state"


def _as_saveable(state: TrainState) -> dict:
    """The array-valued part of the state (apply_fn/tx are code, not
    data — reconstructed by the caller on restore)."""
    return {
        "step": state.step,
        "params": state.params,
        "opt_state": state.opt_state,
        "batch_stats": state.batch_stats,
    }


class PytreeCheckpointer:
    """One CheckpointManager held open across a training loop — per-step
    saves are async (overlapping the next step's compute) and the
    manager is torn down once at ``close()``/context exit."""

    def __init__(self, directory: str, keep: int = 3):
        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=keep))

    def save(self, step: int, tree: Any):
        self._mgr.save(step, args=ocp.args.StandardSave(tree))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def restore(self, template: Any, step: Optional[int] = None) -> Any:
        abstract = jax.tree.map(_abstract_leaf, template)
        if step is None:
            step = self._mgr.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found under {self.directory}")
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract))

    def close(self):
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def save_checkpoint(directory: str, state: TrainState, step: int,
                    keep: int = 3) -> str:
    """Save the state under ``directory/step_{step}``; prunes to the
    newest ``keep`` checkpoints. Returns the checkpoint path."""
    return save_pytree(directory, _as_saveable(state), step, keep)


def latest_step(directory: str) -> Optional[int]:
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return None
    with ocp.CheckpointManager(directory) as mgr:
        return mgr.latest_step()


def _abstract_leaf(leaf):
    """Template leaf for StandardRestore: shape/dtype, plus the leaf's
    sharding when it is a device array — so a state laid out by
    ``shard_train_step`` restores straight into the same mesh layout
    (works multi-host, where materializing to numpy would not)."""
    if isinstance(leaf, jax.Array):
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                    sharding=leaf.sharding)
    arr = np.asarray(leaf)
    return jax.ShapeDtypeStruct(arr.shape, arr.dtype)


def save_pytree(directory: str, tree: Any, step: int, keep: int = 3) -> str:
    """One-shot save of an arbitrary array pytree under
    ``directory/step_{step}`` (loops should hold a
    :class:`PytreeCheckpointer` instead)."""
    with PytreeCheckpointer(directory, keep=keep) as ck:
        ck.save(step, tree)
    return os.path.join(os.path.abspath(directory), str(step))


def restore_pytree(directory: str, template: Any,
                   step: Optional[int] = None) -> Any:
    """Restore a pytree saved by :func:`save_pytree` into ``template``'s
    structure/shapes. ``step=None`` → latest."""
    with PytreeCheckpointer(directory) as ck:
        return ck.restore(template, step)


def restore_checkpoint(directory: str, state: TrainState,
                       step: Optional[int] = None) -> TrainState:
    """Restore into the structure of ``state`` (shapes/dtypes/shardings
    taken from it; pass a freshly-built state). ``step=None`` →
    latest."""
    restored = restore_pytree(directory, _as_saveable(state), step)
    return state.replace(
        step=restored["step"],
        params=restored["params"],
        opt_state=restored["opt_state"],
        batch_stats=restored["batch_stats"])
