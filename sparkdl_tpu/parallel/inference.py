"""Sharded data-parallel inference over a device mesh.

Multi-chip counterpart of ``runtime/runner.py::BatchRunner`` — the
reference's core strategy scaled the TPU way (SURVEY §2.4 "data
parallelism (inference)"): the reference replicated the frozen graph to
every Spark executor and gave each a partition; here the jitted program
is compiled once against a ``Mesh``, params replicated to every chip,
and each global batch's leading dim is split over the ``data`` axis —
host→device transfer of batch *i+1* overlaps device compute of batch
*i* via JAX async dispatch, exactly like the single-chip runner.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh

from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.obs import span
from sparkdl_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    MeshSpec,
    collective_launch,
    make_mesh,
    mesh_has_collectives,
)
from sparkdl_tpu.runtime.runner import (
    ChunkPhases,
    CopyCounters,
    InfeedRing,
    PadStaging,
    RunnerMetrics,
    ShipStats,
    SlabSink,
    check_against_signature,
    check_row_counts,
    checkout_staging,
    dispatch_chunks,
    empty_jax_outputs,
    iter_padded_chunks,
    record_run_feeds,
    warmup_runner,
)
from sparkdl_tpu.runtime.sanitize import ship_guard


class ShardedBatchRunner:
    """Runs a jax-backend ModelFunction data-parallel over a mesh.

    ``batch_size`` is the PER-CHIP batch; the global device batch is
    ``batch_size * mesh.shape["data"]``.
    """

    # run() accepts the phases= accumulator (runtime/runner.py
    # ChunkPhases) — the serve layer probes this attribute
    supports_phases = True

    def __init__(self, model_fn: ModelFunction, mesh: Optional[Mesh] = None,
                 batch_size: int = 64,
                 metrics: Optional[RunnerMetrics] = None,
                 strategy: Optional[str] = None,
                 max_inflight: Optional[int] = None,
                 prefetch_depth: Optional[int] = None,
                 infeed_ring: Optional[int] = None,
                 transfer_interleave: Optional[int] = None):
        if model_fn.backend != "jax":
            raise ValueError(
                f"sharded execution requires a jax backend, got "
                f"'{model_fn.backend}' for {model_fn.name}")
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        self.model_fn = model_fn
        # default: THIS process's devices — a global mesh over
        # non-addressable devices can't consume host-local numpy batches
        self.mesh = mesh if mesh is not None else make_mesh(
            devices=jax.local_devices())
        self.batch_size = batch_size
        self.metrics = metrics or RunnerMetrics()
        # same measured strategy selection + validation as BatchRunner
        # (runner.py module docstring): host_async on tunneled devices,
        # bounded async dispatch on direct-attached ones
        from sparkdl_tpu.runtime.runner import (
            resolve_infeed_ring,
            resolve_prefetch_depth,
            resolve_strategy,
            resolve_transfer_interleave,
        )
        self.strategy, self.max_inflight = resolve_strategy(
            strategy, max_inflight)
        # depth-N input look-ahead for the "prefetch" strategy
        # (runtime/runner.py) — prefetched chunks land with the data
        # sharding, so depth costs global-batch-sized HBM per slot
        self.prefetch_depth = resolve_prefetch_depth(prefetch_depth)
        # device-resident infeed ring over the PLACED sharded slabs —
        # each retained slot already lives split across the data axis,
        # so one logical ring IS the per-device ring set; stream-through
        # chunks dispatch undonated (sharded_jitted declares no
        # donate_argnums — sharded donation is a future rung)
        self.infeed_ring = resolve_infeed_ring(infeed_ring)
        # per-device transfer interleave width for sharded placements
        # (runtime/runner.py::interleaved_device_put)
        self.transfer_interleave = resolve_transfer_interleave(
            transfer_interleave)
        self._global_batch = batch_size * self.mesh.shape[DATA_AXIS]
        # persistent pad staging (BatchRunner's checkout discipline):
        # concurrent run() calls fall back to a throwaway stager
        self._staging = PadStaging()
        self._staging_lock = threading.Lock()
        # persistent ring + try-lock (BatchRunner discipline: a
        # contended run() bypasses the ring rather than racing)
        self._ring: Optional[InfeedRing] = None
        self._ring_lock = threading.Lock()

    # Locks, warm staging buffers, and the mesh's device handles are
    # process-local; a runner captured in a stage closure ships to
    # Spark executors (spark_binding) — drop them on the wire and
    # rebuild on arrival, the same discipline as BatchRunner /
    # RunnerMetrics. The mesh's AXIS STRUCTURE (its model-axis width)
    # does ship: the receiving process re-derives devices from ITS
    # local topology but keeps the parallelism layout, so a
    # model-parallel runner stays model-parallel (a host whose device
    # count can't satisfy the layout fails loudly in MeshSpec.resolve
    # rather than silently collapsing to pure DP). preferred_chunk may
    # legitimately differ across hosts — each sizes global batches by
    # its own data-axis width.
    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_staging", None)
        state.pop("_staging_lock", None)
        state.pop("_ring", None)
        state.pop("_ring_lock", None)
        state.pop("mesh", None)
        state.pop("_global_batch", None)
        state["_mesh_model_axis"] = self.mesh.shape[MODEL_AXIS]
        return state

    def __setstate__(self, state):
        model_axis = state.pop("_mesh_model_axis", 1)
        self.__dict__.update(state)
        self.mesh = make_mesh(MeshSpec(data=-1, model=model_axis),
                              devices=jax.local_devices())
        self._global_batch = self.batch_size * self.mesh.shape[DATA_AXIS]
        self._staging = PadStaging()
        self._staging_lock = threading.Lock()
        self._ring = None
        self._ring_lock = threading.Lock()

    def _checkout_ring(self):
        """(ring, locked, stats) — BatchRunner's checkout discipline
        minus the donated program (sharded stream-through dispatches
        undonated; see ``__init__``)."""
        depth = int(self.infeed_ring)
        if depth < 2:
            return None, False, None
        if not self._ring_lock.acquire(blocking=False):
            return None, False, None
        if self._ring is None:
            self._ring = InfeedRing(depth)
        else:
            self._ring.resize(depth)
        from sparkdl_tpu.obs import default_registry
        reg = default_registry()
        reg.gauge("ship.ring_depth").set(depth)
        reg.gauge("ship.interleave_width").set(
            int(self.transfer_interleave))
        return self._ring, True, ShipStats()

    def ring_state(self) -> Optional[dict]:
        """Live infeed-ring telemetry (None when no ring engaged)."""
        ring = self._ring
        return ring.state() if ring is not None else None

    @property
    def preferred_chunk(self) -> int:
        """Row count at which run() pads nothing: the GLOBAL mesh batch
        (per-chip batch × data-axis size) — published as the device
        stage's plan batch_hint."""
        return self._global_batch

    def warmup(self) -> bool:
        """Pre-trace/compile the sharded program at the global mesh
        batch shape (one zeros run of ``preferred_chunk`` rows) so the
        first real ``run()`` pays no compile — the warmup goes through
        :meth:`run`, so a model-parallel program's first launch already
        holds the collective launch lock. See
        :func:`~sparkdl_tpu.runtime.runner.warmup_runner`."""
        return warmup_runner(self)

    def run(self, inputs: Dict[str, np.ndarray],
            phases: Optional[ChunkPhases] = None
            ) -> Dict[str, np.ndarray]:
        """inputs: {name: [N, *row_shape]} → {name: [N, *out_shape]};
        N is cut into global batches, the tail padded then truncated.
        ``phases`` (optional) accumulates placement/enqueue/drain
        timestamps for per-request attribution (runtime/runner.py)."""
        n = check_row_counts(inputs)
        if n == 0:  # before the signature check: empty flat inputs
            return empty_jax_outputs(self.model_fn)
        check_against_signature(inputs, self.model_fn)

        # compile + replicate lazily, cached on the ModelFunction so
        # multiple runners over the same model share one program and one
        # device copy of the weights
        fn = self.model_fn.sharded_jitted(self.mesh)
        params = self.model_fn.replicated_params(self.mesh)

        # Single-process jit accepts numpy args and shards them itself;
        # a multi-process runtime refuses numpy for non-trivially
        # sharded args even on an all-local mesh — place each chunk
        # explicitly there (all this mesh's devices are addressable, so
        # the device_put is purely local). The prefetch strategy always
        # places with the data sharding: an unsharded device_put would
        # commit the chunk to one device and force an on-device reshard
        # at dispatch.
        place = None
        dat = None
        place_required = jax.process_count() > 1
        if (place_required or self.strategy == "prefetch"
                or self.transfer_interleave >= 2):
            from sparkdl_tpu.parallel.mesh import data_sharding
            dat = data_sharding(self.mesh)
        if place_required:
            place = lambda c: {k: jax.device_put(v, dat)  # noqa: E731
                               for k, v in c.items()}

        t0 = time.perf_counter()
        sink = SlabSink(n)
        counters = CopyCounters()
        staging, locked = checkout_staging(self._staging,
                                           self._staging_lock)
        ring, ring_locked, stats = self._checkout_ring()
        try:
            chunks = iter_padded_chunks(inputs, n, self._global_batch,
                                        staging, counters)
            # the shared dispatch state machine (runtime/runner.py),
            # with the mesh's data sharding for prefetched chunks;
            # SPARKDL_TPU_SANITIZE=1 arms transfer_guard around it
            # (runtime/sanitize.py — explicit place/drain stay legal).
            # A model-parallel program carries collectives, so its
            # launches must not interleave with another thread's
            # (parallel/mesh.py::collective_launch); the pure-DP
            # forward has no cross-device edges and stays lock-free
            # (the policy lives in mesh_has_collectives — the serve
            # layer reads the same predicate).
            launch = collective_launch(
                self.mesh if mesh_has_collectives(self.mesh) else None)
            with span("runner.run_sharded", lane="ship", rows=n,
                      strategy=self.strategy,
                      mesh=f"{self.mesh.shape[DATA_AXIS]}x"
                           f"{self.mesh.shape[MODEL_AXIS]}"), \
                    launch, ship_guard():
                batches = dispatch_chunks(
                    fn, params, chunks, self.strategy,
                    self.max_inflight, sink, place=place, sharding=dat,
                    prefetch_depth=self.prefetch_depth, phases=phases,
                    ring=ring, donate_fn=None,
                    interleave=self.transfer_interleave, stats=stats)
        finally:
            if locked:
                self._staging_lock.release()
            if ring_locked:
                self._ring_lock.release()
        if phases is not None:
            # drain half of the phase accounting — one pair of clock
            # reads shared with transfer_wait_seconds
            phases.drain_s += sink.transfer_wait
        elapsed = time.perf_counter() - t0
        self.metrics.add(n, batches, elapsed,
                         bytes_staged=counters.bytes_staged,
                         bytes_copied=counters.bytes_copied,
                         transfer_wait_seconds=sink.transfer_wait)
        from sparkdl_tpu.obs.compile_log import compile_log
        record_run_feeds(self.model_fn, inputs, elapsed,
                         sink.transfer_wait, batches=batches,
                         flops_per_batch=(
                             getattr(fn, "last_flops", None)
                             if compile_log().armed else None),
                         shipped_bytes=(stats.shipped_bytes
                                        if stats is not None else None))
        # autotune apply point (runtime/runner.py precedent): knobs
        # move between runs only; disarmed this is one armed-check
        from sparkdl_tpu.autotune.core import poll as autotune_poll
        autotune_poll()
        from sparkdl_tpu.obs.ledger import ledger_poll
        ledger_poll()
        return sink.result()
