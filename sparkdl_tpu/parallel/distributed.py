"""Multi-host (DCN) runtime: process init, host data sharding, global mesh.

The reference's inter-host layer was Spark's (RPC task dispatch,
TorrentBroadcast, collect — SURVEY §2.5); it owned no collectives. The
TPU-native design splits that role in two:

* **inside a slice (ICI)**: XLA collectives inserted by pjit/shard_map
  against the mesh (``parallel/mesh.py``) — psum/all_gather ride ICI;
* **between hosts (DCN)**: ``jax.distributed`` — each host runs the same
  program, owns its local chips, and reads its own partitions of the
  data (this module). Arrays with global shardings + XLA handle any
  cross-host traffic; no broadcast of model bytes is needed because
  every host constructs or loads the same params (or receives serialized
  StableHLO, ``ModelFunction.export``).

Single-process use (tests, one-host TPU) is the default: everything
degrades to process_count=1 without calling ``jax.distributed``.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import jax

from sparkdl_tpu.data.frame import DataFrame


# Environment markers of a multi-host launch whose parameters
# jax.distributed can auto-detect (TPU pod metadata, Slurm, OpenMPI).
_CLUSTER_ENV_VARS = (
    "JAX_COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
    "SLURM_JOB_ID",
    "OMPI_COMM_WORLD_SIZE",
)


def _cluster_env_detected() -> bool:
    import os
    if any(os.environ.get(v) for v in _CLUSTER_ENV_VARS):
        return True
    # TPU_WORKER_HOSTNAMES is set even on single-worker setups; only a
    # multi-entry list signals a pod.
    return "," in os.environ.get("TPU_WORKER_HOSTNAMES", "")


def _already_initialized() -> bool:
    """Whether this process already joined a jax.distributed cluster —
    read from the distributed client state, NOT via jax.process_count()
    (which would itself initialize the XLA backend and make a later
    jax.distributed.initialize impossible)."""
    try:
        from jax._src import distributed as _dist
        return _dist.global_state.client is not None
    except Exception:
        return False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host runtime (wraps ``jax.distributed.initialize``).

    Call this before any other jax use on each host of a multi-host job.
    With no arguments, initialization runs only when a recognized
    cluster environment is detected (TPU pod / Slurm / MPI env vars —
    jax auto-detects the parameters there); a plain single-process run
    is a no-op. Calling it after jax has already initialized its backend
    raises (from jax) — that ordering bug should be loud, not silent.
    """
    if _already_initialized():
        return
    explicit = coordinator_address is not None or (
        num_processes is not None and num_processes > 1)
    if not explicit and not _cluster_env_detected():
        return  # single-process: nothing to join
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
    except ValueError as e:
        if explicit:
            raise
        # env marker present but jax couldn't derive the parameters —
        # not actually a recognized cluster; degrade to single-process
        # loudly enough to be found in logs
        import logging
        logging.getLogger(__name__).warning(
            "cluster env detected but jax.distributed auto-detection "
            "failed (%s); continuing single-process", e)


@dataclasses.dataclass(frozen=True)
class HostInfo:
    process_index: int
    process_count: int
    local_device_count: int
    global_device_count: int


def host_info() -> HostInfo:
    return HostInfo(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_device_count=jax.local_device_count(),
        global_device_count=jax.device_count())


def host_shard_indices(num_partitions: int,
                       process_index: Optional[int] = None,
                       process_count: Optional[int] = None) -> List[int]:
    """Partition indices THIS host owns: round-robin ``i % process_count
    == process_index`` (the analogue of Spark assigning file-read tasks
    to executors; every host lists the same files, reads only its own).
    Explicit index/count args exist for tests."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    if pc < 1 or not (0 <= pi < pc):
        raise ValueError(f"invalid process {pi}/{pc}")
    return [i for i in range(num_partitions) if i % pc == pi]


def host_shard_dataframe(df: DataFrame,
                         process_index: Optional[int] = None,
                         process_count: Optional[int] = None) -> DataFrame:
    """A DataFrame containing only this host's partitions. Sources stay
    lazy: partitions owned by other hosts are never loaded here."""
    idxs = host_shard_indices(df.num_partitions, process_index,
                              process_count)
    return df.with_partition_order(idxs)


def agree_min(value: int) -> int:
    """The minimum of ``value`` across all processes (identity when
    single-process). Every process must call this at the same point —
    it launches a tiny global computation over DCN."""
    if jax.process_count() == 1:
        return int(value)
    import numpy as np
    from jax.experimental import multihost_utils
    vals = multihost_utils.process_allgather(np.int64(value))
    return int(np.min(vals))


def agree_resume_step(local_best: int,
                      available: Sequence[int],
                      _agree=None) -> int:
    """Globally agree which checkpoint step to resume from, given this
    host's newest usable step and its full usable list. Hosts write
    checkpoints in lockstep but views can diverge (a crash mid-save, a
    replaced machine): resume from the newest step EVERY host still
    holds, or from scratch when no common step exists — one host
    restoring a different epoch than the others would silently fork the
    replicated state and deadlock the first collective.

    The descent agrees round by round: each host proposes its best step
    ``<= candidate`` and the global min becomes the next candidate, so
    the loop converges on ``max(intersection)`` (not merely testing one
    candidate, which would drop to 0 when the min-of-bests is missing
    somewhere despite a lower common step). The candidate is a
    globally-agreed value, so every host runs the SAME number of
    collectives. ``_agree`` is injectable for single-process tests."""
    agree = _agree or agree_min
    avail = sorted(set(int(s) for s in available))
    candidate = agree(int(local_best))
    while candidate > 0:
        below = [s for s in avail if s <= candidate]
        mine = below[-1] if below else 0
        agreed = agree(mine)
        if agreed == candidate:
            return candidate
        candidate = agreed
    return 0


def global_mesh(spec=None) -> "jax.sharding.Mesh":
    """The ("data", "model") mesh over ALL processes' devices —
    ``jax.devices()`` is global after :func:`initialize`, so the same
    ``make_mesh`` call yields the pod-wide mesh and XLA routes
    data-axis collectives over ICI within a slice and DCN across."""
    from sparkdl_tpu.parallel.mesh import make_mesh
    return make_mesh(spec)
