"""Multi-chip parallelism: meshes, sharded inference, DP training.

The reference owned no collective-communication layer at all (SURVEY
§2.5: Spark RPC + broadcast + py4j + JNI was its complete inter-process
inventory). The TPU-native equivalent lives here: a
``jax.sharding.Mesh`` over the slice, data-parallel inference sharding,
and a pjit training step whose gradient all-reduce rides ICI — the
north-star capability that *exceeds* the reference (BASELINE.json
mandates a pjit DP fine-tune where the reference only had per-task
single-machine Keras fits).
"""

from sparkdl_tpu.parallel.mesh import (
    MeshSpec,
    make_mesh,
    data_sharding,
    replicated,
    param_shardings,
)
from sparkdl_tpu.parallel.distributed import (
    HostInfo,
    global_mesh,
    host_info,
    host_shard_dataframe,
    host_shard_indices,
    initialize,
)
from sparkdl_tpu.parallel.inference import ShardedBatchRunner
from sparkdl_tpu.parallel.train import (
    TrainState,
    create_train_state,
    make_train_step,
    make_eval_step,
    shard_train_step,
)

__all__ = [
    "MeshSpec",
    "HostInfo",
    "initialize",
    "host_info",
    "host_shard_indices",
    "host_shard_dataframe",
    "global_mesh",
    "make_mesh",
    "data_sharding",
    "replicated",
    "param_shardings",
    "ShardedBatchRunner",
    "TrainState",
    "create_train_state",
    "make_train_step",
    "make_eval_step",
    "shard_train_step",
]
