"""Data-parallel training step (the north-star fine-tune path).

The reference's only "training" was one single-machine Keras
``model.fit`` per Spark task (SURVEY §3.4) — no gradient sync anywhere.
BASELINE.json's north-star replaces that with a real pjit data-parallel
loop: the step below is jitted against a ``Mesh`` with the batch split
over the ``data`` axis and params replicated (or weight-sharded over the
``model`` axis), so XLA inserts the gradient all-reduce over ICI
automatically. No hand-written collectives, no NCCL translation.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state
from jax.sharding import Mesh

from sparkdl_tpu.parallel.mesh import (
    data_sharding,
    param_shardings,
    replicated,
)


class TrainState(train_state.TrainState):
    """flax TrainState + BatchNorm running statistics."""

    batch_stats: Any = None


def create_train_state(module, variables: Dict[str, Any],
                       tx: optax.GradientTransformation) -> TrainState:
    """Wrap zoo/flax variables ({"params", "batch_stats"}) + an optax
    optimizer into a TrainState."""
    return TrainState.create(
        apply_fn=module.apply,
        params=variables["params"],
        batch_stats=variables.get("batch_stats"),
        tx=tx)


def make_train_step(module, preprocess: Callable,
                    num_classes: int,
                    label_smoothing: float = 0.0) -> Callable:
    """One SGD step on a zoo-style module (``__call__(x, train,
    features_only)``): softmax cross-entropy on logits, BatchNorm stats
    updated via flax ``mutable``. Pure function of (state, batch) —
    shard it with :func:`shard_train_step`."""

    def train_step(state: TrainState, batch: Dict[str, jax.Array]
                   ) -> Tuple[TrainState, Dict[str, jax.Array]]:
        images, labels = batch["image"], batch["label"]
        onehot = optax.smooth_labels(
            jax.nn.one_hot(labels, num_classes), label_smoothing)

        def loss_fn(params):
            variables = {"params": params}
            if state.batch_stats is not None:
                variables["batch_stats"] = state.batch_stats
            logits, updates = module.apply(
                variables, preprocess(images), train=True,
                features_only=False, mutable=["batch_stats"])
            loss = optax.softmax_cross_entropy(logits, onehot).mean()
            return loss, (updates.get("batch_stats"), logits)

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
        (loss, (new_stats, logits)), grads = grad_fn(state.params)
        state = state.apply_gradients(grads=grads)
        if new_stats is not None:
            state = state.replace(batch_stats=new_stats)
        metrics = {
            "loss": loss,
            "accuracy": jnp.mean(
                (jnp.argmax(logits, -1) == labels).astype(jnp.float32)),
        }
        return state, metrics

    return train_step


def make_eval_step(module, preprocess: Callable,
                   num_classes: int) -> Callable:
    """Loss/accuracy on a batch with frozen stats (for CrossValidator
    scoring)."""

    def eval_step(state: TrainState, batch: Dict[str, jax.Array]
                  ) -> Dict[str, jax.Array]:
        images, labels = batch["image"], batch["label"]
        variables = {"params": state.params}
        if state.batch_stats is not None:
            variables["batch_stats"] = state.batch_stats
        logits = module.apply(variables, preprocess(images), train=False,
                              features_only=False)
        onehot = jax.nn.one_hot(labels, num_classes)
        return {
            "loss": optax.softmax_cross_entropy(logits, onehot).mean(),
            "accuracy": jnp.mean(
                (jnp.argmax(logits, -1) == labels).astype(jnp.float32)),
        }

    return eval_step


def shard_train_step(train_step: Callable, mesh: Mesh, state: TrainState,
                     shard_model_axis: bool = True
                     ) -> Tuple[Callable, TrainState]:
    """Compile ``train_step`` against the mesh and lay out the state.

    Returns ``(jitted_step, sharded_state)``: batch leading dim split
    over ``data``; params/opt_state replicated (pure DP) or largest-dim
    sharded over ``model`` (weight sharding) per
    :func:`param_shardings`. The returned step donates the input state
    so param memory is reused across steps.
    """
    p_shard = param_shardings(state.params, mesh, shard_model_axis)

    # Build a pytree of shardings shaped like the state. TrainState is a
    # pytree whose static fields (apply_fn, tx) drop out of tree_map.
    rep = replicated(mesh)
    shardings = jax.tree.map(lambda _: rep, state)
    shardings = shardings.replace(params=p_shard)
    shardings = shardings.replace(
        opt_state=_opt_state_shardings(state.opt_state, state.params,
                                       p_shard, rep))
    batch_shard = data_sharding(mesh)

    jitted = jax.jit(
        train_step,
        in_shardings=(shardings, batch_shard),
        out_shardings=(shardings, rep),
        donate_argnums=(0,))
    sharded_state = jax.device_put(state, shardings)
    return jitted, sharded_state


def _opt_state_shardings(opt_state, params, p_shard, rep):
    """Optimizer-state leaves with param-shaped arrays (momenta, nu)
    shard like their params; scalars replicate."""
    shape_to_shard = {}
    for p, s in zip(jax.tree.leaves(params), jax.tree.leaves(p_shard)):
        shape_to_shard.setdefault(getattr(p, "shape", ()), s)

    def for_leaf(leaf):
        shape = getattr(leaf, "shape", ())
        if shape and shape in shape_to_shard:
            return shape_to_shard[shape]
        return rep

    return jax.tree.map(for_leaf, opt_state)
