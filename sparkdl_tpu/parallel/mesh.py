"""Device meshes and sharding rules.

The slice topology is expressed once as a ``jax.sharding.Mesh`` with
axes ``("data", "model")``; everything else (inference sharding, the DP
train step, the estimator) derives `NamedSharding`s from it. The
reference's counterpart was Spark's executor topology — implicit, owned
by the cluster manager; here it is an explicit, testable object
(simulated CPU devices in tests, real chips in prod).
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkdl_tpu.obs import default_registry, span
from sparkdl_tpu.obs import watchdog as _watchdog
from sparkdl_tpu.resilience.faults import maybe_fail

DATA_AXIS = "data"
MODEL_AXIS = "model"

# Threads launching collective (multi-device) programs onto the same
# local devices must enqueue them in ONE global order: each device's
# execution queue is FIFO, so if thread A's program lands on device 0
# ahead of thread B's but behind it on device 1, A's all-reduce waits
# for device 1 (busy running B) while B's waits for device 0 (busy
# running A) — both stall forever. This is the single-process analogue
# of the multi-host launch-order rule fitMultiple enforces by
# serializing trials across processes. In-process launchers take this
# lock around the DISPATCH only; execution stays async, so concurrent
# trials still overlap device compute with host work.
_COLLECTIVE_LAUNCH_LOCK = threading.Lock()


class _CollectiveLaunch:
    """The launch lock with its contention made visible: entering
    times the acquire into a ``collective_lock_wait`` span (ship lane)
    and the ``collective.*`` registry counters — the PR-2 deadlock
    fix's serialization cost, previously unmeasurable. The span is
    recorded on EVERY entry (dur ≈ 0 uncontended) so an armed trace
    always shows the launch-ordering points; ``collective.lock_waits``
    counts only genuinely contended acquires.

    One instance wraps THE process lock; no per-entry state lives on
    the instance, so concurrent threads enter the same object safely —
    each blocks in ``acquire`` exactly as they did on the raw lock.
    """

    def __init__(self, lock: threading.Lock):
        self._lock = lock

    def __enter__(self):
        # fault-injection site (resilience/faults.py): fires BEFORE
        # any acquire, so an injected launch failure exercises the
        # caller's recovery without ever holding (or leaking) the
        # process lock
        maybe_fail("collective.launch")
        t0 = time.perf_counter()
        held = False
        # anything that raises WHILE the lock is held (span recording,
        # a registry kind collision, an async KeyboardInterrupt) must
        # release it before propagating — __exit__ never runs when
        # __enter__ raises, and a leaked hold here deadlocks every
        # future collective launch; hence both acquires sit inside the
        # release-on-failure block
        try:
            held = self._lock.acquire(blocking=False)
            contended = not held
            with span("collective_lock_wait", lane="ship",
                      contended=contended):
                if contended:
                    self._lock.acquire()
                    held = True
            wait = time.perf_counter() - t0
            reg = default_registry()
            reg.counter("collective.launches").add()
            reg.counter("collective.lock_wait_seconds").add(wait)
            if contended:
                reg.counter("collective.lock_waits").add()
            # stall-watchdog activity: the hold itself is the watched
            # window — no beats happen while held, so a hold past the
            # threshold (the PR-2 deadlock signature: a collective
            # program that never completes its dispatch) trips the
            # stall verdict and dumps the flight recorder
            _watchdog.begin("collective.hold")
            return self
        except BaseException:
            if held:
                self._lock.release()
            raise

    def __exit__(self, exc_type, exc, tb):
        _watchdog.end("collective.hold")
        self._lock.release()
        return False

    # The wrapped lock doesn't pickle, and the wrapper IS process-wide
    # state: a closure that captured it deserializes to the RECEIVING
    # process's singleton (whose lock guards that process's devices) —
    # the H3 drop-and-recreate discipline, in __reduce__ form because
    # identity, not field values, is what must survive the wire.
    def __reduce__(self):
        return (_collective_launch_singleton, ())


_COLLECTIVE_LAUNCH = _CollectiveLaunch(_COLLECTIVE_LAUNCH_LOCK)


def _collective_launch_singleton() -> _CollectiveLaunch:
    return _COLLECTIVE_LAUNCH


def collective_launch(mesh: Optional[Mesh]):
    """Context manager for dispatching one program compiled against
    ``mesh``: the (instrumented) process-wide launch lock when the
    program spans more than one device (collectives possible), a no-op
    otherwise."""
    if mesh is None or mesh.size <= 1:
        return contextlib.nullcontext()
    return _COLLECTIVE_LAUNCH


def mesh_has_collectives(mesh: Optional[Mesh]) -> bool:
    """THE policy for whether an inference program compiled against
    ``mesh`` carries cross-device edges and therefore must dispatch
    under :func:`collective_launch`: only a real model axis introduces
    them (weight-shard all-gathers/reduce-scatters); the pure-DP
    forward splits the batch with no collective and stays lock-free.
    Centralized here so ShardedBatchRunner and the serve layer
    (serve/server.py session dispatch accounting) agree — training
    steps are different: their grad psum is a collective at ANY mesh
    size > 1, which is why they pass the mesh to collective_launch
    unconditionally."""
    return mesh is not None and mesh.shape.get(MODEL_AXIS, 1) > 1


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh request: how many devices along each axis.

    ``data=-1`` means "all remaining devices" (the common case: pure DP
    over every chip, model axis 1).
    """

    data: int = -1
    model: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        if self.model < 1:
            raise ValueError(f"model axis must be >= 1, got {self.model}")
        if self.data != -1 and self.data < 1:
            raise ValueError(
                f"data axis must be >= 1 (or -1 for 'all remaining'), "
                f"got {self.data}")
        model = self.model
        data = self.data
        if data == -1:
            if n_devices % model:
                raise ValueError(
                    f"{n_devices} devices not divisible by model={model}")
            data = n_devices // model
        if data * model != n_devices:
            raise ValueError(
                f"mesh {data}x{model} != {n_devices} devices")
        return {DATA_AXIS: data, MODEL_AXIS: model}


def make_mesh(spec: Optional[MeshSpec] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a 2-D ("data", "model") mesh over the given devices
    (default: all local devices)."""
    devices = list(devices if devices is not None else jax.devices())
    spec = spec or MeshSpec()
    sizes = spec.resolve(len(devices))
    arr = np.asarray(devices).reshape(sizes[DATA_AXIS], sizes[MODEL_AXIS])
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch-dim sharding over the data axis (leading dim split across
    chips; each chip sees its shard only — the DP layout)."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _largest_divisible_dim(shape: Sequence[int], n: int) -> Optional[int]:
    best = None
    for i, d in enumerate(shape):
        if n > 1 and d % n == 0 and d >= n and (
                best is None or d > shape[best]):
            best = i
    return best


def param_shardings(params: Any, mesh: Mesh,
                    shard_model_axis: bool = True) -> Any:
    """Per-leaf NamedShardings for a params pytree.

    With ``model`` axis size 1 (pure DP) every leaf is replicated and
    XLA's gradient psum over the data axis is the only collective. With
    a real model axis, each leaf's largest divisible dim is sharded over
    it (weight sharding in the FSDP/TP family); XLA's sharding
    propagation inserts the all-gathers/reduce-scatters over ICI.
    """
    model_n = mesh.shape.get(MODEL_AXIS, 1)

    def leaf_sharding(leaf):
        shape = getattr(leaf, "shape", ())
        if shard_model_axis and model_n > 1:
            dim = _largest_divisible_dim(shape, model_n)
            if dim is not None:
                spec = [None] * len(shape)
                spec[dim] = MODEL_AXIS
                return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree.map(leaf_sharding, params)
