"""Device meshes and sharding rules.

The slice topology is expressed once as a ``jax.sharding.Mesh`` with
axes ``("data", "model")``; everything else (inference sharding, the DP
train step, the estimator) derives `NamedSharding`s from it. The
reference's counterpart was Spark's executor topology — implicit, owned
by the cluster manager; here it is an explicit, testable object
(simulated CPU devices in tests, real chips in prod).
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"

# Threads launching collective (multi-device) programs onto the same
# local devices must enqueue them in ONE global order: each device's
# execution queue is FIFO, so if thread A's program lands on device 0
# ahead of thread B's but behind it on device 1, A's all-reduce waits
# for device 1 (busy running B) while B's waits for device 0 (busy
# running A) — both stall forever. This is the single-process analogue
# of the multi-host launch-order rule fitMultiple enforces by
# serializing trials across processes. In-process launchers take this
# lock around the DISPATCH only; execution stays async, so concurrent
# trials still overlap device compute with host work.
_COLLECTIVE_LAUNCH_LOCK = threading.Lock()


def collective_launch(mesh: Optional[Mesh]):
    """Context manager for dispatching one program compiled against
    ``mesh``: the process-wide launch lock when the program spans more
    than one device (collectives possible), a no-op otherwise."""
    if mesh is None or mesh.size <= 1:
        return contextlib.nullcontext()
    return _COLLECTIVE_LAUNCH_LOCK


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh request: how many devices along each axis.

    ``data=-1`` means "all remaining devices" (the common case: pure DP
    over every chip, model axis 1).
    """

    data: int = -1
    model: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        if self.model < 1:
            raise ValueError(f"model axis must be >= 1, got {self.model}")
        if self.data != -1 and self.data < 1:
            raise ValueError(
                f"data axis must be >= 1 (or -1 for 'all remaining'), "
                f"got {self.data}")
        model = self.model
        data = self.data
        if data == -1:
            if n_devices % model:
                raise ValueError(
                    f"{n_devices} devices not divisible by model={model}")
            data = n_devices // model
        if data * model != n_devices:
            raise ValueError(
                f"mesh {data}x{model} != {n_devices} devices")
        return {DATA_AXIS: data, MODEL_AXIS: model}


def make_mesh(spec: Optional[MeshSpec] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a 2-D ("data", "model") mesh over the given devices
    (default: all local devices)."""
    devices = list(devices if devices is not None else jax.devices())
    spec = spec or MeshSpec()
    sizes = spec.resolve(len(devices))
    arr = np.asarray(devices).reshape(sizes[DATA_AXIS], sizes[MODEL_AXIS])
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch-dim sharding over the data axis (leading dim split across
    chips; each chip sees its shard only — the DP layout)."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _largest_divisible_dim(shape: Sequence[int], n: int) -> Optional[int]:
    best = None
    for i, d in enumerate(shape):
        if n > 1 and d % n == 0 and d >= n and (
                best is None or d > shape[best]):
            best = i
    return best


def param_shardings(params: Any, mesh: Mesh,
                    shard_model_axis: bool = True) -> Any:
    """Per-leaf NamedShardings for a params pytree.

    With ``model`` axis size 1 (pure DP) every leaf is replicated and
    XLA's gradient psum over the data axis is the only collective. With
    a real model axis, each leaf's largest divisible dim is sharded over
    it (weight sharding in the FSDP/TP family); XLA's sharding
    propagation inserts the all-gathers/reduce-scatters over ICI.
    """
    model_n = mesh.shape.get(MODEL_AXIS, 1)

    def leaf_sharding(leaf):
        shape = getattr(leaf, "shape", ())
        if shard_model_axis and model_n > 1:
            dim = _largest_divisible_dim(shape, model_n)
            if dim is not None:
                spec = [None] * len(shape)
                spec[dim] = MODEL_AXIS
                return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree.map(leaf_sharding, params)
