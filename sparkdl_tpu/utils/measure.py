"""Shared measurement primitives for bench.py and tools/measure_transfer.py.

One home for the forced-sync methodology (VERDICT r1 weak #3): on the
tunneled TPU, ``jax.block_until_ready`` returns at enqueue, so timing
must force a tiny DEPENDENT readback instead. Both the driver bench and
the strategy-selection tool import from here so a methodology fix can
never apply to one and not the other.
"""

from __future__ import annotations

import time

import numpy as np


def sync_readback(x) -> float:
    """Force completion of everything ``x`` depends on via a 1-element
    dependent readback (reliable where block_until_ready is not)."""
    import jax.numpy as jnp
    return float(jnp.reshape(x, (-1,))[0].astype(jnp.float32))


def measure_link(n_mb: int) -> dict:
    """Host↔device bandwidth in MB/s: ``device_put`` timed against a
    dependent 1-element readback (the sum can't run before the transfer
    lands), then ``device_get`` of the resident buffer."""
    import jax

    x = np.random.default_rng(0).integers(
        0, 255, size=(n_mb * 1024 * 1024,), dtype=np.uint8)
    sync_readback(jax.device_put(x[:1024]).sum())  # warm the path
    t0 = time.perf_counter()
    d = jax.device_put(x)
    sync_readback(d.sum())
    up = time.perf_counter() - t0
    t0 = time.perf_counter()
    h = jax.device_get(d)
    down = time.perf_counter() - t0
    assert h[0] == x[0]
    return {"h2d_MBps": round(n_mb / up, 1),
            "d2h_MBps": round(n_mb / down, 1)}


def measure_device_resident(mf, batch_size: int, n_batches: int) -> dict:
    """A ModelFunction's compute-side throughput with input already in
    HBM: no host transfer inside the timed region. ``n_batches`` sets
    the timed window — it must be large enough to amortize per-call
    dispatch latency (RPC on tunneled platforms: 4 batches measured
    ~4,600 img/s where 16 measured ~6,400 for the same program)."""
    import jax

    fn = mf.jitted()
    params = mf.device_params()
    (in_name, (shape, dtype)), = mf.input_signature.items()
    out_name = mf.output_names[0]
    rng = np.random.default_rng(1)
    x = rng.integers(0, 255, size=(batch_size,) + tuple(shape)) \
        .astype(dtype)
    dx = {in_name: jax.device_put(x)}
    sync_readback(fn(params, dx)[out_name])  # compile + warm

    t0 = time.perf_counter()
    out = None
    for _ in range(n_batches):
        out = fn(params, dx)
    sync_readback(out[out_name])
    dt = time.perf_counter() - t0
    ips = batch_size * n_batches / dt
    return {"ips": round(ips, 1),
            "batch_ms": round(dt / n_batches * 1000, 2)}


def measure_host_copy(mf, batch_size: int, n_batches: int = 4) -> dict:
    """Host-side staging-copy micro-shape: the SAME program run through
    the production BatchRunner twice — batch-ALIGNED (N a multiple of
    the device batch: the zero-copy hot path, both byte counters must
    read 0) and TAIL-padded (N = aligned + half a batch: only the tail
    stages, through the persistent pad buffer). Reports RunnerMetrics'
    bytes-staged/bytes-copied/transfer-wait counters plus throughput
    for each, so the bench PROVES the ship-path copies went away
    rather than asserting it (the round-1 transfer-strategy lesson
    applied to host copies)."""
    from sparkdl_tpu.runtime.runner import BatchRunner, RunnerMetrics

    (in_name, (shape, dtype)), = mf.input_signature.items()
    rng = np.random.default_rng(3)

    def one(n_rows: int) -> dict:
        size = (n_rows,) + tuple(shape)
        if np.issubdtype(np.dtype(dtype), np.integer):
            # dtype at draw time: the default int64 draw would allocate
            # an 8x transient for a large image corpus before .astype
            x = rng.integers(0, 255, size=size, dtype=dtype)
        else:
            x = rng.integers(0, 255, size=size).astype(dtype)
        metrics = RunnerMetrics()
        runner = BatchRunner(mf, batch_size=batch_size, metrics=metrics)
        runner.run({in_name: x[:batch_size]})  # compile + warm
        # every counter deltas off the warm run: the warmup's
        # device_get stalls on jit compile + first transfer (seconds on
        # the tunnel) and would otherwise dominate transfer_wait_s
        warm_staged = metrics.bytes_staged
        warm_copied = metrics.bytes_copied
        warm_wait = metrics.transfer_wait_seconds
        t0 = time.perf_counter()
        runner.run({in_name: x})
        dt = time.perf_counter() - t0
        return {"ips": round(n_rows / dt, 1),
                "bytes_staged": int(metrics.bytes_staged - warm_staged),
                "bytes_copied": int(metrics.bytes_copied - warm_copied),
                "transfer_wait_s": round(
                    metrics.transfer_wait_seconds - warm_wait, 4)}

    return {"aligned": one(batch_size * n_batches),
            "tail": one(batch_size * n_batches + batch_size // 2)}
