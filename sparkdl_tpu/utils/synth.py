"""Synthetic TEXTURED image corpora for benchmarks.

Random-noise JPEGs are near-incompressible, so they mis-state decode
cost in both directions: Huffman decode dominates and scales with the
(bloated) byte count, while a real photo's smooth regions compress well
and decode faster per pixel (VERDICT r3 weak #8). These generators
synthesize photo-like content — smooth multi-scale gradients plus mild
detail noise — whose JPEG size/pixel sits in the range of real photos
(~0.5–1.5 bits/pixel at quality 90 vs ~7 for noise).
"""

from __future__ import annotations

import os
from typing import List, Tuple

import numpy as np


def textured_image(rng: np.random.Generator, h: int, w: int) -> np.ndarray:
    """One photo-like uint8 RGB image: per-channel sums of low-frequency
    sinusoids (smooth structure JPEG compresses like real content) plus
    low-amplitude pixel noise (so detail blocks aren't empty)."""
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    chans = []
    for _ in range(3):
        img = np.zeros((h, w), np.float32)
        for _ in range(3):  # a few octaves of smooth structure
            fx = rng.uniform(1.0, 6.0) * np.pi / w
            fy = rng.uniform(1.0, 6.0) * np.pi / h
            amp = rng.uniform(20.0, 60.0)
            img += amp * np.sin(fx * xx + rng.uniform(0, 2 * np.pi)) \
                * np.cos(fy * yy + rng.uniform(0, 2 * np.pi))
        chans.append(img)
    arr = np.stack(chans, axis=-1) + 128.0
    arr += rng.normal(0.0, 6.0, size=arr.shape)  # mild sensor-like noise
    return np.clip(arr, 0, 255).astype(np.uint8)


def write_textured_jpegs(directory: str, n: int,
                         src_hw: Tuple[int, int] = (375, 500),
                         seed: int = 7, quality: int = 90) -> List[str]:
    """Write ``n`` textured JPEGs (tf_flowers-like source dims) under
    ``directory``; returns the file paths."""
    from PIL import Image

    rng = np.random.default_rng(seed)
    os.makedirs(directory, exist_ok=True)
    paths = []
    for i in range(n):
        arr = textured_image(rng, src_hw[0], src_hw[1])
        p = os.path.join(directory, f"img{i:04d}.jpg")
        Image.fromarray(arr, "RGB").save(p, quality=quality)
        paths.append(p)
    return paths
