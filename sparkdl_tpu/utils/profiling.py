"""Profiling and per-stage throughput metrics.

SURVEY §5 ("tracing/profiling: absent in the reference — add
jax.profiler trace + per-stage images/sec counters, needed to prove the
north-star number"). Two tools:

* :func:`trace` — context manager around ``jax.profiler`` producing a
  TensorBoard-loadable device trace (XLA ops, infeed gaps, HBM);
* :class:`StageMetrics` — cumulative wall-time/row counters per plan
  stage, collected by the engine when attached, so a pipeline run can
  report where its time went (decode vs resize vs device apply).

Both publish into the unified observability layer
(:mod:`sparkdl_tpu.obs`): ``StageMetrics.publish`` /
``RunnerMetrics.publish`` set registry gauges and
:func:`throughput_report` renders from the registry snapshot; for
TIMELINES (who waited on whom, one shared clock) arm
``SPARKDL_TPU_TRACE=1`` and see docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, Optional


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False
          ) -> Iterator[None]:
    """Capture a device/host profiler trace for the enclosed block into
    ``log_dir`` (view with TensorBoard's profile plugin)."""
    import jax.profiler
    jax.profiler.start_trace(log_dir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@dataclass
class _StageStat:
    seconds: float = 0.0
    calls: int = 0
    rows: int = 0


@dataclass
class StageMetrics:
    """Thread-safe per-stage counters. Attach to a
    :class:`~sparkdl_tpu.data.engine.LocalEngine` via
    ``LocalEngine(stage_metrics=...)`` (or set ``engine.stage_metrics``)
    and run any DataFrame materialization."""

    _stats: Dict[str, _StageStat] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)

    # Locks don't pickle; an engine carrying metrics can ship inside a
    # stage closure (spark_binding) — drop the lock on the wire and
    # recreate on arrival, like RunnerMetrics. Counts collected on the
    # remote side stay remote (same boundary as RunnerMetrics: driver
    # metrics are a LocalEngine feature).
    def __getstate__(self):
        state = self.__dict__.copy()
        del state["_lock"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()

    def add(self, stage_name: str, seconds: float, rows: int):
        with self._lock:
            st = self._stats.setdefault(stage_name, _StageStat())
            st.seconds += seconds
            st.calls += 1
            st.rows += rows

    def as_dict(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                name: {
                    "seconds": st.seconds,
                    "calls": st.calls,
                    "rows": st.rows,
                    "rows_per_second": (st.rows / st.seconds
                                        if st.seconds else 0.0),
                }
                for name, st in self._stats.items()
            }

    def publish(self, registry) -> None:
        """Set the cumulative per-stage counters as
        ``engine.stage.<name>.<field>`` gauges in an
        :class:`~sparkdl_tpu.obs.registry.MetricsRegistry` —
        idempotent (gauges, not counter adds), so reports can publish
        on every render without double counting."""
        for name, st in self.as_dict().items():
            for field_name in ("seconds", "calls", "rows"):
                registry.gauge(
                    f"engine.stage.{name}.{field_name}"
                ).set(st[field_name])

    def report(self) -> str:
        """Human-readable table, slowest stage first."""
        return _format_stage_table(self.as_dict())


def _format_stage_table(stats: Dict[str, Dict[str, float]]) -> str:
    rows = sorted(stats.items(), key=lambda kv: -kv[1]["seconds"])
    if not rows:
        return "(no stages recorded)"
    width = max(len(n) for n, _ in rows)
    lines = [f"{'stage'.ljust(width)}  seconds  calls    rows   rows/s"]
    for name, st in rows:
        rps = (st["rows"] / st["seconds"] if st["seconds"] else 0.0)
        lines.append(
            f"{name.ljust(width)}  {st['seconds']:7.3f}  "
            f"{int(st['calls']):5d}  {int(st['rows']):6d}  "
            f"{rps:7.0f}")
    return "\n".join(lines)


def _stage_stats_from_snapshot(snap: Dict[str, float]
                               ) -> Dict[str, Dict[str, float]]:
    """Invert ``StageMetrics.publish``: ``engine.stage.<name>.<field>``
    snapshot keys back into per-stage stat dicts (stage names may
    themselves contain dots — the field is always the LAST segment)."""
    prefix = "engine.stage."
    stats: Dict[str, Dict[str, float]] = {}
    for key, value in snap.items():
        if not key.startswith(prefix):
            continue
        name, _, field_name = key[len(prefix):].rpartition(".")
        if name and field_name in ("seconds", "calls", "rows"):
            stats.setdefault(
                name, {"seconds": 0.0, "calls": 0, "rows": 0}
            )[field_name] = value
    return stats


def throughput_report(stage_metrics: Optional[StageMetrics] = None,
                      runner_metrics=None, registry=None) -> str:
    """Combined engine-stage + device-runner report, routed through the
    obs registry: both inputs publish into ``registry`` (a fresh
    :class:`~sparkdl_tpu.obs.registry.MetricsRegistry` when not given)
    and the text renders FROM its ``snapshot()``, so the printed
    numbers and the machine-readable ones can never diverge. The
    device line carries the host-copy proof counters
    (``bytes_staged`` / ``bytes_copied`` / ``transfer_wait_seconds``),
    not just throughput."""
    from sparkdl_tpu.obs import MetricsRegistry
    reg = registry if registry is not None else MetricsRegistry()
    if stage_metrics is not None:
        stage_metrics.publish(reg)
    if runner_metrics is not None:
        runner_metrics.publish(reg)
    snap = reg.snapshot()
    parts = []
    if stage_metrics is not None:
        # values come from the snapshot, but only for the stages THIS
        # StageMetrics holds — a reused registry (default_registry())
        # keeps gauges from earlier runs, and a report must not list a
        # stage the current run never executed
        current = set(stage_metrics.as_dict())
        stats = {name: st for name, st
                 in _stage_stats_from_snapshot(snap).items()
                 if name in current}
        parts.append(_format_stage_table(stats))
    if runner_metrics is not None:
        rows = snap.get("ship.rows", 0.0)
        secs = snap.get("ship.seconds", 0.0)
        rps = rows / secs if secs else 0.0
        parts.append(
            f"device: {int(rows)} rows in {secs:.3f}s = "
            f"{rps:.0f} rows/s "
            f"({int(snap.get('ship.batches', 0))} batches, "
            f"{int(snap.get('ship.bytes_staged', 0))} B staged, "
            f"{int(snap.get('ship.bytes_copied', 0))} B copied, "
            f"{snap.get('ship.transfer_wait_seconds', 0.0):.3f}s "
            "transfer wait)")
    if parts:
        # the bottleneck verdict, from THE one attribution code path
        # (obs/ledger.py — the same ledger.attribute() bench.py and
        # the live ledger.bound_by gauge use): the last closed window
        # when the ledger ran, else cumulative process totals
        from sparkdl_tpu.obs.ledger import ledger
        v = ledger().current_verdict()
        parts.append(f"bound by: {v['bound_by']} "
                     f"(headroom {v['headroom_pct']:.0f}%, "
                     f"{v['basis']})")
    return "\n".join(parts) if parts else "(no metrics)"
