"""Profiling and per-stage throughput metrics.

SURVEY §5 ("tracing/profiling: absent in the reference — add
jax.profiler trace + per-stage images/sec counters, needed to prove the
north-star number"). Two tools:

* :func:`trace` — context manager around ``jax.profiler`` producing a
  TensorBoard-loadable device trace (XLA ops, infeed gaps, HBM);
* :class:`StageMetrics` — cumulative wall-time/row counters per plan
  stage, collected by the engine when attached, so a pipeline run can
  report where its time went (decode vs resize vs device apply).
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, Optional


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False
          ) -> Iterator[None]:
    """Capture a device/host profiler trace for the enclosed block into
    ``log_dir`` (view with TensorBoard's profile plugin)."""
    import jax.profiler
    jax.profiler.start_trace(log_dir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@dataclass
class _StageStat:
    seconds: float = 0.0
    calls: int = 0
    rows: int = 0


@dataclass
class StageMetrics:
    """Thread-safe per-stage counters. Attach to a
    :class:`~sparkdl_tpu.data.engine.LocalEngine` via
    ``LocalEngine(stage_metrics=...)`` (or set ``engine.stage_metrics``)
    and run any DataFrame materialization."""

    _stats: Dict[str, _StageStat] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)

    # Locks don't pickle; an engine carrying metrics can ship inside a
    # stage closure (spark_binding) — drop the lock on the wire and
    # recreate on arrival, like RunnerMetrics. Counts collected on the
    # remote side stay remote (same boundary as RunnerMetrics: driver
    # metrics are a LocalEngine feature).
    def __getstate__(self):
        state = self.__dict__.copy()
        del state["_lock"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()

    def add(self, stage_name: str, seconds: float, rows: int):
        with self._lock:
            st = self._stats.setdefault(stage_name, _StageStat())
            st.seconds += seconds
            st.calls += 1
            st.rows += rows

    def as_dict(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                name: {
                    "seconds": st.seconds,
                    "calls": st.calls,
                    "rows": st.rows,
                    "rows_per_second": (st.rows / st.seconds
                                        if st.seconds else 0.0),
                }
                for name, st in self._stats.items()
            }

    def report(self) -> str:
        """Human-readable table, slowest stage first."""
        rows = sorted(self.as_dict().items(),
                      key=lambda kv: -kv[1]["seconds"])
        if not rows:
            return "(no stages recorded)"
        width = max(len(n) for n, _ in rows)
        lines = [f"{'stage'.ljust(width)}  seconds  calls    rows   rows/s"]
        for name, st in rows:
            lines.append(
                f"{name.ljust(width)}  {st['seconds']:7.3f}  "
                f"{st['calls']:5d}  {st['rows']:6d}  "
                f"{st['rows_per_second']:7.0f}")
        return "\n".join(lines)


def throughput_report(stage_metrics: Optional[StageMetrics] = None,
                      runner_metrics=None) -> str:
    """Combined engine-stage + device-runner report."""
    parts = []
    if stage_metrics is not None:
        parts.append(stage_metrics.report())
    if runner_metrics is not None:
        parts.append(
            f"device: {runner_metrics.rows} rows in "
            f"{runner_metrics.seconds:.3f}s = "
            f"{runner_metrics.rows_per_second:.0f} rows/s "
            f"({runner_metrics.batches} batches)")
    return "\n".join(parts) if parts else "(no metrics)"
