"""Subprocess environment sanitization for CPU-only JAX workers.

The build/test host reaches its TPU through a tunnel whose sitecustomize
(injected via PYTHONPATH) registers the device plugin at interpreter
start and overrides JAX_PLATFORMS through jax.config — so a subprocess
that must run on plain CPU (virtual-device meshes, multi-process
jax.distributed tests, the driver's multichip dryrun) needs the tunnel's
environment stripped, not just JAX_PLATFORMS set. One shared helper so
every spawner strips the same set.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

# Prefixes/names that mark the device tunnel's environment; grown here
# (only here) when the tunnel adds markers.
_TUNNEL_PREFIXES = ("PALLAS_", "AXON", "TPU_")


def sanitized_cpu_env(pythonpath: Optional[str] = None,
                      n_devices: Optional[int] = None) -> Dict[str, str]:
    """A copy of os.environ prepared for a CPU-only JAX subprocess:
    tunnel vars and PYTHONPATH stripped, ``JAX_PLATFORMS=cpu``, and —
    when ``n_devices`` is given — the virtual host-device-count XLA flag
    (replacing any inherited one)."""
    env = {k: v for k, v in os.environ.items()
           if not (k == "PYTHONPATH"
                   or any(k.startswith(p) for p in _TUNNEL_PREFIXES))}
    if pythonpath is not None:
        env["PYTHONPATH"] = pythonpath
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if not f.startswith(
                     "--xla_force_host_platform_device_count")]
        flags.append(
            f"--xla_force_host_platform_device_count={n_devices}")
        env["XLA_FLAGS"] = " ".join(flags)
    return env
