"""Cross-cutting utilities (profiling, observability).

The reference had no tracing/metrics of its own (SURVEY §5: it
inherited the Spark UI and nothing else); these exist because the
north-star throughput claim needs to be provable.
"""

from sparkdl_tpu.utils.profiling import (
    StageMetrics,
    trace,
    throughput_report,
)

__all__ = ["trace", "StageMetrics", "throughput_report"]
