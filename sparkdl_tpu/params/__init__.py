"""Typed parameter system and ML-pipeline base classes.

TPU-native re-design of the reference's config layer
(``python/sparkdl/param/__init__.py::SparkDLTypeConverters`` and the
``Has*`` mixins), which itself sat on ``pyspark.ml.param.Params``. Since
this framework is Spark-free, the pipeline substrate (``Params``,
``Transformer``, ``Estimator``, ``Pipeline``, ``CrossValidator``) is
implemented in-tree with the same composition semantics, so param maps and
CrossValidator-style sweeps work the way reference users expect.
"""

from sparkdl_tpu.params.base import (  # noqa: F401
    Param,
    Params,
    TypeConverters,
    keyword_only,
)
from sparkdl_tpu.params.pipeline import (  # noqa: F401
    Estimator,
    Evaluator,
    Model,
    Pipeline,
    PipelineModel,
    Transformer,
)
from sparkdl_tpu.params.tuning import (  # noqa: F401
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
    TrainValidationSplit,
)
from sparkdl_tpu.params.shared import (  # noqa: F401
    HasBatchSize,
    HasDeviceResizeFrom,
    HasUseMesh,
    HasInputCol,
    HasInputMapping,
    HasTFHParams,
    HasKerasLoss,
    HasKerasModel,
    HasKerasOptimizer,
    HasLabelCol,
    HasModelFunction,
    HasOutputCol,
    HasOutputMapping,
    HasOutputMode,
)
from sparkdl_tpu.params.image import CanLoadImage  # noqa: F401

__all__ = [
    "Param",
    "Params",
    "TypeConverters",
    "keyword_only",
    "Transformer",
    "Estimator",
    "Model",
    "Pipeline",
    "PipelineModel",
    "Evaluator",
    "ParamGridBuilder",
    "CrossValidator",
    "CrossValidatorModel",
    "TrainValidationSplit",
    "HasInputCol",
    "HasOutputCol",
    "HasLabelCol",
    "HasOutputMode",
    "HasBatchSize",
    "HasDeviceResizeFrom",
    "HasUseMesh",
    "HasKerasModel",
    "HasKerasOptimizer",
    "HasKerasLoss",
    "HasInputMapping",
    "HasTFHParams",
    "HasOutputMapping",
    "HasModelFunction",
    "CanLoadImage",
]
