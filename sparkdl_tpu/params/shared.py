"""Shared param mixins.

Re-design of the reference's ``python/sparkdl/param/shared_params.py``
(``HasInputCol``/``HasOutputCol``/``HasLabelCol``, ``HasKerasModel``,
``HasKerasOptimizer``, ``HasKerasLoss``, ``HasOutputMode``,
``HasInputMapping``/``HasOutputMapping``, ``HasTFInputGraph``). TF-graph
params become ModelFunction params; Keras params keep their names because
Keras 3 (JAX backend) is the supported user-model format.
"""

from __future__ import annotations

from sparkdl_tpu.params.base import Param, Params, TypeConverters


class HasInputCol(Params):
    inputCol = Param("HasInputCol", "inputCol", "input column name",
                     TypeConverters.toString)

    def setInputCol(self, value: str):
        return self._set(inputCol=value)

    def getInputCol(self) -> str:
        return self.getOrDefault("inputCol")


class HasOutputCol(Params):
    outputCol = Param("HasOutputCol", "outputCol", "output column name",
                      TypeConverters.toString)

    def setOutputCol(self, value: str):
        return self._set(outputCol=value)

    def getOutputCol(self) -> str:
        return self.getOrDefault("outputCol")


class HasLabelCol(Params):
    labelCol = Param("HasLabelCol", "labelCol", "label column name",
                     TypeConverters.toString)

    def setLabelCol(self, value: str):
        return self._set(labelCol=value)

    def getLabelCol(self) -> str:
        return self.getOrDefault("labelCol")


def _toOutputMode(value):
    if value not in ("vector", "image"):
        raise TypeError(f"outputMode must be 'vector' or 'image', "
                        f"got {value!r}")
    return value


class HasOutputMode(Params):
    """'vector' → flat float features column; 'image' → image struct column
    (reference ``transformers/tf_image.py`` outputMode)."""

    outputMode = Param("HasOutputMode", "outputMode",
                       "output mode: 'vector' or 'image'", _toOutputMode)

    def setOutputMode(self, value: str):
        return self._set(outputMode=value)

    def getOutputMode(self) -> str:
        return self.getOrDefault("outputMode")


class HasBatchSize(Params):
    """Device batch size for the partition runner (TPU-era addition: static
    shapes are required for XLA; batches are padded to this size)."""

    batchSize = Param("HasBatchSize", "batchSize",
                      "device batch size (batches padded to this for XLA "
                      "static shapes)", TypeConverters.toInt)

    def setBatchSize(self, value: int):
        return self._set(batchSize=value)

    def getBatchSize(self) -> int:
        return self.getOrDefault("batchSize")


class HasUseMesh(Params):
    """Run the device stage data-parallel over this host's local mesh
    (batch split over the ``data`` axis, params replicated) instead of
    single-device — the pipeline-surface switch for SURVEY §2.4's core
    DP-inference strategy. Runner selection lives in
    ``transformers/utils.py::make_runner``."""

    useMesh = Param("HasUseMesh", "useMesh",
                    "shard device batches over all local chips",
                    TypeConverters.toBoolean)

    def setUseMesh(self, value: bool):
        return self._set(useMesh=value)

    def getUseMesh(self) -> bool:
        return self.getOrDefault("useMesh")


class HasDeviceResizeFrom(Params):
    """Move the resample on-device: pack images at their uniform native
    (h, w) — host CPUs only decode — and fuse a bilinear resize to the
    model's input size into the model's XLA program (Pallas kernel on
    real TPU; ``transformers/utils.py::deviceResizeModel``). None keeps
    the reference-equivalent host resize."""

    deviceResizeFrom = Param(
        "HasDeviceResizeFrom", "deviceResizeFrom",
        "(h, w) the images actually have; pack at that size and resize "
        "on-device inside the model's XLA program (None = resize on "
        "host)", TypeConverters.toIntPairOrNone)

    def setDeviceResizeFrom(self, value):
        return self._set(deviceResizeFrom=value)

    def getDeviceResizeFrom(self):
        return self.getOrDefault("deviceResizeFrom")


class HasKerasModel(Params):
    """Path to a user Keras model file (.h5 / .keras), loaded with the JAX
    backend (reference ``HasKerasModel.modelFile`` + ``kerasFitParams``)."""

    modelFile = Param("HasKerasModel", "modelFile",
                      "path to Keras model file (.h5 or .keras)",
                      TypeConverters.toString)
    kerasFitParams = Param("HasKerasModel", "kerasFitParams",
                           "kwargs dict for the training loop "
                           "(epochs, batch_size, ...)")

    def setModelFile(self, value: str):
        return self._set(modelFile=value)

    def getModelFile(self) -> str:
        return self.getOrDefault("modelFile")

    def setKerasFitParams(self, value: dict):
        return self._set(kerasFitParams=dict(value))

    def getKerasFitParams(self) -> dict:
        return dict(self.getOrDefault("kerasFitParams"))


class HasKerasOptimizer(Params):
    kerasOptimizer = Param("HasKerasOptimizer", "kerasOptimizer",
                           "optax optimizer name or GradientTransformation",
                           TypeConverters.toOptimizer)

    def setKerasOptimizer(self, value):
        return self._set(kerasOptimizer=value)

    def getKerasOptimizer(self):
        return self.getOrDefault("kerasOptimizer")


class HasKerasLoss(Params):
    kerasLoss = Param("HasKerasLoss", "kerasLoss",
                      "loss name or callable(params_out, labels) -> scalar",
                      TypeConverters.toLoss)

    def setKerasLoss(self, value):
        return self._set(kerasLoss=value)

    def getKerasLoss(self):
        return self.getOrDefault("kerasLoss")


class HasInputMapping(Params):
    """DataFrame column → model input name (reference
    ``TFTransformer.inputMapping``)."""

    inputMapping = Param("HasInputMapping", "inputMapping",
                         "dict: input column name -> model input name",
                         TypeConverters.toStringDict)

    def setInputMapping(self, value):
        return self._set(inputMapping=value)

    def getInputMapping(self) -> dict:
        return self.getOrDefault("inputMapping")


class HasTFHParams(Params):
    """Named hyperparameter constants fed to matching model inputs
    (reference ``TFTransformer.tfHParams``, a tf.contrib HParams bag
    shipped into the graph; here each entry feeds the model input of
    the same name as a row-broadcast constant)."""

    tfHParams = Param("HasTFHParams", "tfHParams",
                      "dict: model input name -> constant value",
                      TypeConverters.toHParams)

    def __init__(self):
        super().__init__()
        # the mixin owns its default (pyspark Has* convention) so any
        # stage mixing it in gets a working getTFHParams for free
        self._setDefault(tfHParams=None)

    def setTFHParams(self, value):
        return self._set(tfHParams=value)

    def getTFHParams(self) -> dict:
        return self.getOrDefault("tfHParams") or {}


class HasOutputMapping(Params):
    """Model output name → DataFrame column (reference
    ``TFTransformer.outputMapping``)."""

    outputMapping = Param("HasOutputMapping", "outputMapping",
                          "dict: model output name -> output column name",
                          TypeConverters.toStringDict)

    def setOutputMapping(self, value):
        return self._set(outputMapping=value)

    def getOutputMapping(self) -> dict:
        return self.getOrDefault("outputMapping")


class HasModelFunction(Params):
    """The compiled-model param — TPU-era successor of the reference's
    ``HasTFInputGraph`` (a frozen TF GraphDef bundle becomes a
    :class:`sparkdl_tpu.graph.function.ModelFunction`)."""

    modelFunction = Param("HasModelFunction", "modelFunction",
                          "ModelFunction (jittable fn + params + signature)",
                          TypeConverters.toModelFunction)

    def setModelFunction(self, value):
        return self._set(modelFunction=value)

    def getModelFunction(self):
        return self.getOrDefault("modelFunction")
