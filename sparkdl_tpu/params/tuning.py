"""Hyperparameter tuning: ParamGridBuilder, CrossValidator.

The reference composed ``KerasImageFileEstimator`` with
``pyspark.ml.tuning.CrossValidator`` (reference
``estimators/keras_image_file_estimator.py`` docs and tests). This module
provides the same tuning surface natively: k-fold splits over partitioned
Arrow data, fitMultiple-driven parallel trial execution.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence

import numpy as np

from sparkdl_tpu.params.base import Param, Params, TypeConverters, keyword_only
from sparkdl_tpu.params.pipeline import Estimator, Evaluator, Model


class ParamGridBuilder:
    """Cartesian-product grid of param maps (pyspark-compatible API)."""

    def __init__(self):
        self._grid: Dict[Param, Sequence] = {}

    def addGrid(self, param: Param, values: Sequence) -> "ParamGridBuilder":
        self._grid[param] = list(values)
        return self

    def baseOn(self, *args) -> "ParamGridBuilder":
        if len(args) == 1 and isinstance(args[0], dict):
            args = list(args[0].items())
        for param, value in args:
            self._grid[param] = [value]
        return self

    def build(self) -> List[dict]:
        keys = list(self._grid)
        if not keys:
            return [{}]
        out = []
        for combo in itertools.product(*(self._grid[k] for k in keys)):
            out.append(dict(zip(keys, combo)))
        return out


class CrossValidatorModel(Model):
    def __init__(self, bestModel: Model, avgMetrics: List[float]):
        super().__init__()
        self.bestModel = bestModel
        self.avgMetrics = avgMetrics

    def _transform(self, dataset):
        return self.bestModel.transform(dataset)

    def _extra_state(self):
        return {"avgMetrics": [float(m) for m in self.avgMetrics]}

    def _child_stages(self):
        return {"bestModel": self.bestModel}

    @classmethod
    def _from_saved(cls, params, extra, children):
        return cls(children["bestModel"], extra["avgMetrics"])


class CrossValidator(Estimator):
    """k-fold cross validation over an estimator + param grid."""

    estimator = Param("CrossValidator", "estimator", "estimator to tune")
    estimatorParamMaps = Param("CrossValidator", "estimatorParamMaps",
                               "param grid", TypeConverters.toList)
    evaluator = Param("CrossValidator", "evaluator", "metric evaluator")
    numFolds = Param("CrossValidator", "numFolds", "number of folds",
                     TypeConverters.toInt)
    seed = Param("CrossValidator", "seed", "random seed",
                 TypeConverters.toInt)

    @keyword_only
    def __init__(self, *, estimator=None, estimatorParamMaps=None,
                 evaluator=None, numFolds=3, seed=42):
        super().__init__()
        self._setDefault(numFolds=3, seed=42)
        self._set(estimator=estimator, estimatorParamMaps=estimatorParamMaps,
                  evaluator=evaluator, numFolds=numFolds, seed=seed)

    def _kfold(self, dataset):
        """Split rows into k (train, validation) DataFrame pairs."""
        k = self.getOrDefault("numFolds")
        n = dataset.count()
        rng = np.random.default_rng(self.getOrDefault("seed"))
        fold_of_row = rng.integers(0, k, size=n)
        for fold in range(k):
            train = dataset.filter_rows(fold_of_row != fold)
            valid = dataset.filter_rows(fold_of_row == fold)
            yield train, valid

    def _fit(self, dataset) -> CrossValidatorModel:
        est: Estimator = self.getOrDefault("estimator")
        maps: List[dict] = self.getOrDefault("estimatorParamMaps")
        ev: Evaluator = self.getOrDefault("evaluator")
        metrics = np.zeros(len(maps))
        nfolds = self.getOrDefault("numFolds")
        # Materialize the dataset ONCE; every fold's filter_rows and the
        # final refit then slice the cached table. Without this, each of
        # the 2×numFolds filter_rows calls re-ran the full plan — a
        # decode-bearing pipeline was fully decoded 2k times before any
        # training started (VERDICT r2 weak #2).
        dataset = dataset.cache()
        for train, valid in self._kfold(dataset):
            for idx, model in est.fitMultiple(train, maps):
                metrics[idx] += ev.evaluate(model.transform(valid)) / nfolds
        best = int(np.argmax(metrics) if ev.isLargerBetter()
                   else np.argmin(metrics))
        bestModel = est.fit(dataset, maps[best])
        return CrossValidatorModel(bestModel, list(metrics))


class TrainValidationSplitModel(Model):
    def __init__(self, bestModel: Model, validationMetrics: List[float]):
        super().__init__()
        self.bestModel = bestModel
        self.validationMetrics = validationMetrics

    def _transform(self, dataset):
        return self.bestModel.transform(dataset)

    def _extra_state(self):
        return {"validationMetrics": [float(m)
                                      for m in self.validationMetrics]}

    def _child_stages(self):
        return {"bestModel": self.bestModel}

    @classmethod
    def _from_saved(cls, params, extra, children):
        return cls(children["bestModel"], extra["validationMetrics"])


class TrainValidationSplit(Estimator):
    """Single random train/validation split over a param grid."""

    estimator = Param("TrainValidationSplit", "estimator", "estimator to tune")
    estimatorParamMaps = Param("TrainValidationSplit", "estimatorParamMaps",
                               "param grid", TypeConverters.toList)
    evaluator = Param("TrainValidationSplit", "evaluator", "metric evaluator")
    trainRatio = Param("TrainValidationSplit", "trainRatio",
                       "fraction of rows used for training",
                       TypeConverters.toFloat)
    seed = Param("TrainValidationSplit", "seed", "random seed",
                 TypeConverters.toInt)

    @keyword_only
    def __init__(self, *, estimator=None, estimatorParamMaps=None,
                 evaluator=None, trainRatio=0.75, seed=42):
        super().__init__()
        self._setDefault(trainRatio=0.75, seed=42)
        self._set(estimator=estimator, estimatorParamMaps=estimatorParamMaps,
                  evaluator=evaluator, trainRatio=trainRatio, seed=seed)

    def _fit(self, dataset) -> TrainValidationSplitModel:
        est: Estimator = self.getOrDefault("estimator")
        maps: List[dict] = self.getOrDefault("estimatorParamMaps")
        ev: Evaluator = self.getOrDefault("evaluator")
        dataset = dataset.cache()  # one materialization, like CV above
        n = dataset.count()
        rng = np.random.default_rng(self.getOrDefault("seed"))
        is_train = rng.random(n) < self.getOrDefault("trainRatio")
        train = dataset.filter_rows(is_train)
        valid = dataset.filter_rows(~is_train)
        metrics = [0.0] * len(maps)
        for idx, model in est.fitMultiple(train, maps):
            metrics[idx] = ev.evaluate(model.transform(valid))
        best = int(np.argmax(metrics) if ev.isLargerBetter()
                   else np.argmin(metrics))
        bestModel = est.fit(dataset, maps[best])
        return TrainValidationSplitModel(bestModel, metrics)
