"""Hyperparameter tuning: ParamGridBuilder, CrossValidator.

The reference composed ``KerasImageFileEstimator`` with
``pyspark.ml.tuning.CrossValidator`` (reference
``estimators/keras_image_file_estimator.py`` docs and tests). This module
provides the same tuning surface natively: k-fold splits over partitioned
Arrow data, fitMultiple-driven parallel trial execution.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence

import numpy as np

from sparkdl_tpu.params.base import Param, Params, TypeConverters, keyword_only
from sparkdl_tpu.params.pipeline import Estimator, Evaluator, Model


def _seeded_split(dataset, seed: int, name: str, draw, keep_a: bool):
    """Membership as a PLAN STAGE: each partition draws a boolean
    "side A" mask from a generator seeded by (seed, partition logical
    index) via ``draw(rng, n_rows)``, so membership is deterministic
    per row across re-materializations, and the two sides (``keep_a``
    True/False) recompute the identical draw — disjoint and covering
    by construction, without ever knowing the global row count. This
    single helper carries that invariant for BOTH CV folds and the TVS
    split; it is what lets tuning run over a disk spill instead of a
    collected table (VERDICT r3 missing #4): no stage here holds more
    than one partition batch."""
    import pyarrow as pa

    def _stage(batch: "pa.RecordBatch", index: int) -> "pa.RecordBatch":
        rng = np.random.default_rng((seed, index))
        side_a = draw(rng, batch.num_rows)
        return batch.filter(pa.array(side_a if keep_a else ~side_a))

    return dataset.map_batches(_stage, name=name,
                               row_preserving=False, with_index=True)


def _fold_split(dataset, k: int, fold: int, seed: int, keep_train: bool):
    """CV fold membership over :func:`_seeded_split`: rows drawing fold
    id != ``fold`` are the train side."""
    side = "train" if keep_train else "valid"
    return _seeded_split(
        dataset, seed, f"fold{fold}/{side}",
        lambda rng, n: rng.integers(0, k, size=n) != fold, keep_train)


def _cached_for_tuning(dataset, cache_dir):
    """Materialize the upstream plan ONCE for the 2×k fold passes.

    ``cache_dir=None`` (default): eager in-memory :meth:`cache` — right
    for frames that fit in RAM. With a directory: per-fit
    :meth:`cache_to_disk` spill in a fresh subdirectory, so a
    larger-than-RAM decoded table never lives in driver memory and a
    reused ``cacheDir`` can never serve another fit's rows. Returns
    ``(frame, cleanup)``."""
    if cache_dir is None:
        return dataset.cache(), (lambda: None)
    import shutil
    import tempfile

    import os
    os.makedirs(cache_dir, exist_ok=True)
    spill = tempfile.mkdtemp(prefix="tuning_spill_", dir=cache_dir)
    return (dataset.cache_to_disk(spill),
            lambda: shutil.rmtree(spill, ignore_errors=True))


class ParamGridBuilder:
    """Cartesian-product grid of param maps (pyspark-compatible API)."""

    def __init__(self):
        self._grid: Dict[Param, Sequence] = {}

    def addGrid(self, param: Param, values: Sequence) -> "ParamGridBuilder":
        self._grid[param] = list(values)
        return self

    def baseOn(self, *args) -> "ParamGridBuilder":
        if len(args) == 1 and isinstance(args[0], dict):
            args = list(args[0].items())
        for param, value in args:
            self._grid[param] = [value]
        return self

    def build(self) -> List[dict]:
        keys = list(self._grid)
        if not keys:
            return [{}]
        out = []
        for combo in itertools.product(*(self._grid[k] for k in keys)):
            out.append(dict(zip(keys, combo)))
        return out


class CrossValidatorModel(Model):
    """``subModels`` (``collectSubModels=True`` only, else None) is
    ``[fold][candidate] -> Model`` — pyspark 2.3's layout. In-memory
    only: like pyspark, sub-models are a debugging/inspection aid and
    are NOT persisted by ``save`` (only ``bestModel`` round-trips).

    ``avgMetrics[i]`` is candidate *i*'s mean metric over the COMMON
    fold subset: a fold whose validation side scored 0 rows for ANY
    candidate (``EmptyScoredFrameError`` — e.g. a candidate transform
    that filters the fold empty) is excluded from EVERY candidate's
    average, so candidates are always compared on the same folds — a
    candidate must never win merely because it skipped a hard fold the
    others were scored on. Values are therefore always finite: a fit
    where no common fold survives raises instead of returning NaN
    averages."""

    def __init__(self, bestModel: Model, avgMetrics: List[float],
                 subModels: Optional[List[List[Model]]] = None):
        super().__init__()
        self.bestModel = bestModel
        self.avgMetrics = avgMetrics
        self.subModels = subModels

    def _transform(self, dataset):
        return self.bestModel.transform(dataset)

    def _extra_state(self):
        return {"avgMetrics": [float(m) for m in self.avgMetrics]}

    def _child_stages(self):
        return {"bestModel": self.bestModel}

    @classmethod
    def _from_saved(cls, params, extra, children):
        return cls(children["bestModel"], extra["avgMetrics"])


class CrossValidator(Estimator):
    """k-fold cross validation over an estimator + param grid.

    The upstream plan materializes ONCE for all 2×k fold passes:
    in memory by default (``cacheDir=None``), or spilled to Arrow IPC
    files under ``cacheDir`` so a decoded table larger than driver RAM
    still cross-validates (fold membership is computed per partition
    batch as a plan stage — no global mask over a collected table)."""

    estimator = Param("CrossValidator", "estimator", "estimator to tune")
    estimatorParamMaps = Param("CrossValidator", "estimatorParamMaps",
                               "param grid", TypeConverters.toList)
    evaluator = Param("CrossValidator", "evaluator", "metric evaluator")
    numFolds = Param("CrossValidator", "numFolds", "number of folds",
                     TypeConverters.toInt)
    seed = Param("CrossValidator", "seed", "random seed",
                 TypeConverters.toInt)
    cacheDir = Param("CrossValidator", "cacheDir",
                     "spill directory for larger-than-RAM datasets",
                     TypeConverters.toString)
    collectSubModels = Param(
        "CrossValidator", "collectSubModels",
        "keep every (fold, candidate) fitted model on the result "
        "(memory scales with numFolds * len(paramMaps))",
        TypeConverters.toBoolean)

    @keyword_only
    def __init__(self, *, estimator=None, estimatorParamMaps=None,
                 evaluator=None, numFolds=3, seed=42, cacheDir=None,
                 collectSubModels=False):
        super().__init__()
        self._setDefault(numFolds=3, seed=42, cacheDir=None,
                         collectSubModels=False)
        self._set(estimator=estimator, estimatorParamMaps=estimatorParamMaps,
                  evaluator=evaluator, numFolds=numFolds, seed=seed,
                  cacheDir=cacheDir, collectSubModels=collectSubModels)

    def _kfold(self, dataset):
        """Split rows into k (train, validation) DataFrame pairs —
        lazy plan-stage filters, disjoint and covering by construction
        (both sides recompute the same seeded per-partition fold ids)."""
        k = self.getOrDefault("numFolds")
        seed = self.getOrDefault("seed")
        for fold in range(k):
            yield (_fold_split(dataset, k, fold, seed, True),
                   _fold_split(dataset, k, fold, seed, False))

    def _fit(self, dataset) -> CrossValidatorModel:
        import logging

        from sparkdl_tpu.params.pipeline import EmptyScoredFrameError

        est: Estimator = self.getOrDefault("estimator")
        maps: List[dict] = self.getOrDefault("estimatorParamMaps")
        ev: Evaluator = self.getOrDefault("evaluator")
        nfolds = self.getOrDefault("numFolds")
        # per-(candidate, fold) scores; a fold that scored 0 rows stays
        # NaN and is EXCLUDED from that candidate's average (loudly) —
        # one degenerate fold must not crash the whole search after
        # N-1 folds of work (review r5), while standalone evaluate
        # calls still raise
        scores = np.full((len(maps), nfolds), np.nan)
        # Materialize the upstream plan ONCE (decode-once, VERDICT r2
        # weak #2); with cacheDir the materialization is a disk spill,
        # never a full collected table (ADVICE r3 / VERDICT r3 #3).
        collect_sub = bool(self.getOrDefault("collectSubModels"))
        sub: Optional[List[List[Model]]] = \
            ([[None] * len(maps) for _ in range(nfolds)]
             if collect_sub else None)
        dataset, cleanup = _cached_for_tuning(
            dataset, self.getOrDefault("cacheDir"))
        try:
            for fold, (train, valid) in enumerate(self._kfold(dataset)):
                for idx, model in est.fitMultiple(train, maps):
                    if sub is not None:
                        sub[fold][idx] = model
                    try:
                        scores[idx, fold] = ev.evaluate(
                            model.transform(valid))
                    except EmptyScoredFrameError:
                        logging.getLogger(__name__).warning(
                            "fold %d scored 0 rows for candidate %d "
                            "(validation side empty after upstream "
                            "filters); the fold will be excluded from "
                            "EVERY candidate's average so candidates "
                            "stay comparable", fold, idx)
            # Candidates must be compared on the SAME fold subset: a
            # fold any candidate nan-skipped is excluded from EVERY
            # candidate's average (per-candidate nanmeans would let a
            # candidate win merely by skipping a hard fold the others
            # were scored on — ADVICE r5).
            fold_ok = ~np.isnan(scores).any(axis=0)
            if not fold_ok.any():
                raise ValueError(
                    f"no fold was scored by every candidate "
                    f"(fold validation sides scored 0 rows for "
                    f"{int(np.isnan(scores).any(axis=0).sum())} of "
                    f"{nfolds} folds across {len(maps)} candidates) — "
                    "the dataset is too small for numFolds or an "
                    "upstream/candidate filter drops everything")
            if not fold_ok.all():
                logging.getLogger(__name__).warning(
                    "excluding fold(s) %s from every candidate's "
                    "average (some candidate scored 0 validation rows "
                    "there); candidates are compared on the common "
                    "%d-fold subset",
                    [int(f) for f in np.nonzero(~fold_ok)[0]],
                    int(fold_ok.sum()))
            metrics = scores[:, fold_ok].mean(axis=1)
            best = int(np.argmax(metrics) if ev.isLargerBetter()
                       else np.argmin(metrics))
            bestModel = est.fit(dataset, maps[best])
        finally:
            cleanup()
        return CrossValidatorModel(bestModel, list(metrics),
                                   subModels=sub)


class TrainValidationSplitModel(Model):
    """``subModels`` (``collectSubModels=True`` only, else None) is
    ``[candidate] -> Model``. In-memory only, like pyspark — not
    persisted by ``save``."""

    def __init__(self, bestModel: Model, validationMetrics: List[float],
                 subModels: Optional[List[Model]] = None):
        super().__init__()
        self.bestModel = bestModel
        self.validationMetrics = validationMetrics
        self.subModels = subModels

    def _transform(self, dataset):
        return self.bestModel.transform(dataset)

    def _extra_state(self):
        return {"validationMetrics": [float(m)
                                      for m in self.validationMetrics]}

    def _child_stages(self):
        return {"bestModel": self.bestModel}

    @classmethod
    def _from_saved(cls, params, extra, children):
        return cls(children["bestModel"], extra["validationMetrics"])


class TrainValidationSplit(Estimator):
    """Single random train/validation split over a param grid.

    Same out-of-core contract as :class:`CrossValidator`: split
    membership is a per-partition plan stage, and ``cacheDir`` spills
    the materialized-once upstream plan to disk instead of RAM."""

    estimator = Param("TrainValidationSplit", "estimator", "estimator to tune")
    estimatorParamMaps = Param("TrainValidationSplit", "estimatorParamMaps",
                               "param grid", TypeConverters.toList)
    evaluator = Param("TrainValidationSplit", "evaluator", "metric evaluator")
    trainRatio = Param("TrainValidationSplit", "trainRatio",
                       "fraction of rows used for training",
                       TypeConverters.toFloat)
    seed = Param("TrainValidationSplit", "seed", "random seed",
                 TypeConverters.toInt)
    cacheDir = Param("TrainValidationSplit", "cacheDir",
                     "spill directory for larger-than-RAM datasets",
                     TypeConverters.toString)
    collectSubModels = Param(
        "TrainValidationSplit", "collectSubModels",
        "keep every candidate's fitted model on the result",
        TypeConverters.toBoolean)

    @keyword_only
    def __init__(self, *, estimator=None, estimatorParamMaps=None,
                 evaluator=None, trainRatio=0.75, seed=42, cacheDir=None,
                 collectSubModels=False):
        super().__init__()
        self._setDefault(trainRatio=0.75, seed=42, cacheDir=None,
                         collectSubModels=False)
        self._set(estimator=estimator, estimatorParamMaps=estimatorParamMaps,
                  evaluator=evaluator, trainRatio=trainRatio, seed=seed,
                  cacheDir=cacheDir, collectSubModels=collectSubModels)

    def _split(self, dataset):
        """(train, valid) via :func:`_seeded_split`: a per-partition
        seeded coin decides each row's side; both frames recompute the
        identical draw, so they are disjoint and covering."""
        ratio = self.getOrDefault("trainRatio")
        seed = self.getOrDefault("seed")

        def draw(rng, n):
            return rng.random(n) < ratio

        return (_seeded_split(dataset, seed, "split/train", draw, True),
                _seeded_split(dataset, seed, "split/valid", draw, False))

    def _fit(self, dataset) -> TrainValidationSplitModel:
        est: Estimator = self.getOrDefault("estimator")
        maps: List[dict] = self.getOrDefault("estimatorParamMaps")
        ev: Evaluator = self.getOrDefault("evaluator")
        dataset, cleanup = _cached_for_tuning(
            dataset, self.getOrDefault("cacheDir"))
        try:
            from sparkdl_tpu.params.pipeline import EmptyScoredFrameError

            train, valid = self._split(dataset)
            metrics = [0.0] * len(maps)
            sub: Optional[List[Model]] = \
                ([None] * len(maps)
                 if self.getOrDefault("collectSubModels") else None)
            for idx, model in est.fitMultiple(train, maps):
                if sub is not None:
                    sub[idx] = model
                try:
                    metrics[idx] = ev.evaluate(model.transform(valid))
                except EmptyScoredFrameError as e:
                    # unlike a CV fold, the ONE validation side is
                    # shared by every candidate — nothing to skip to
                    raise ValueError(
                        "the validation side of the split scored 0 "
                        f"rows (trainRatio="
                        f"{self.getOrDefault('trainRatio')}); the "
                        "dataset is too small or an upstream filter "
                        "drops everything") from e
            best = int(np.argmax(metrics) if ev.isLargerBetter()
                       else np.argmin(metrics))
            bestModel = est.fit(dataset, maps[best])
        finally:
            cleanup()
        return TrainValidationSplitModel(bestModel, metrics,
                                         subModels=sub)
