"""Image-loading param mixin.

Re-design of the reference's ``python/sparkdl/param/image_params.py``
(``CanLoadImage``): stages that consume a column of image URIs and a
user-supplied ``imageLoader(uri) -> ndarray`` callable.
"""

from __future__ import annotations

import numpy as np

from sparkdl_tpu.params.base import Param, Params, TypeConverters


class CanLoadImage(Params):
    """Mixin for stages taking an image-URI column plus a user loader.

    ``imageLoader`` maps a URI string to a float/uint8 ndarray of the
    model's expected HWC input shape — exactly the reference's contract
    (``image_params.py::CanLoadImage``), decoded on host CPU threads here
    rather than in Spark python workers.
    """

    imageLoader = Param("CanLoadImage", "imageLoader",
                        "callable(uri: str) -> np.ndarray (HWC)",
                        TypeConverters.toCallable)

    def setImageLoader(self, value):
        return self._set(imageLoader=value)

    def getImageLoader(self):
        return self.getOrDefault("imageLoader")

    def loadImagesInternal(self, dataframe, uri_col: str, out_col: str):
        """Append a decoded-tensor column by mapping the loader over the
        URI column on host threads (the reference built a hidden
        image-loading column the same way)."""
        loader = self.getImageLoader()

        def _load(batch):
            from sparkdl_tpu.data.frame import column_index
            uris = batch.column(column_index(batch, uri_col)).to_pylist()
            arrs = [np.asarray(loader(u), dtype=np.float32) for u in uris]
            if not arrs:
                return np.zeros((0, 1), dtype=np.float32)
            first = arrs[0].shape
            bad = next((i for i, a in enumerate(arrs)
                        if a.shape != first), None)
            if bad is not None:
                # np.stack's bare "all input arrays must have the same
                # shape" names neither the loader nor the row
                raise ValueError(
                    f"imageLoader returned differing shapes: row 0 is "
                    f"{first} but row {bad} ({uris[bad]!r}) is "
                    f"{arrs[bad].shape}; the loader must produce one "
                    "fixed shape (resize inside it)")
            return np.stack(arrs)

        return dataframe.with_column(out_col, _load)
