"""Fitted-stage persistence: ``stage.save(dir)`` / ``load_model(dir)``.

The reference era got ``Pipeline.save``/``load`` semantics from pyspark
ML for Params-based stages (SURVEY §2.1 param-system row); this build
reimplements Pipeline/CrossValidator natively, so persistence is native
too. A saved stage is a directory:

* ``metadata.json`` — ``{"format", "version", "class", "params",
  "extra", "children"}`` where ``params`` holds the stage's explicitly
  set Params and ``extra`` its non-Param fitted state (coefficients,
  training history, ...), each as a typed descriptor;
* sidecar files for values JSON can't carry: numpy arrays as ``.npy``,
  jax-backend ModelFunctions as serialized StableHLO with weights baked
  in (``ModelFunction.export`` — the same deploy form the engine
  broadcasts), callables (``imageLoader``) via cloudpickle;
* one subdirectory per child stage (PipelineModel stages,
  CrossValidatorModel's bestModel), each a saved stage itself.

``load_model`` resolves ``class`` by import path and rebuilds the stage
through ``cls._from_saved(params, extra, children)`` — the default
implementation passes explicit params straight back to the
``keyword_only`` constructor, which is exactly how pyspark's
DefaultParamsReader rebuilds a stage from its param map.
"""

from __future__ import annotations

import importlib
import json
import os
from typing import Any, Dict, Iterable, Optional

import numpy as np

FORMAT = "sparkdl_tpu.stage"
VERSION = 1


# ---------------------------------------------------------------------------
# value codecs
# ---------------------------------------------------------------------------

def _is_plain_json(value) -> bool:
    if isinstance(value, (str, int, float, bool)) or value is None:
        return True
    if isinstance(value, (list, tuple)):
        return all(_is_plain_json(v) for v in value)
    if isinstance(value, dict):
        return all(isinstance(k, str) and _is_plain_json(v)
                   for k, v in value.items())
    return False


def _encode_value(key: str, value, directory: str) -> dict:
    """Value → JSON descriptor (+ sidecar file when needed)."""
    from sparkdl_tpu.graph.function import ModelFunction

    if _is_plain_json(value):
        return {"kind": "json", "value": value}
    if isinstance(value, np.ndarray):
        fname = f"{key}.npy"
        np.save(os.path.join(directory, fname), value)
        return {"kind": "ndarray", "file": fname}
    if isinstance(value, ModelFunction):
        if value.backend != "jax":
            raise TypeError(
                f"cannot save {key!r}: host-backend ModelFunction "
                f"{value.name!r} wraps live TF runtime state — re-ingest "
                "it from its source artifact after loading instead")
        try:
            # only the export itself may fall back; IO errors while
            # writing the sidecar must propagate (a swallowed ENOSPC
            # would leave a corrupt orphan and silently record pickle)
            blob = value.export(batch_size=value._fixed_batch)
        except Exception as e:
            # Some programs can't export with a symbolic batch dim
            # (shape-polymorphism limits); fall back to cloudpickle of
            # the function object — same-environment portable, and
            # ModelFunction.__getstate__ already drops process-local
            # compiled/device state.
            import logging

            import cloudpickle
            logging.getLogger(__name__).warning(
                "StableHLO export of %s failed (%s: %s); persisting "
                "%r via cloudpickle — the save is bound to this "
                "environment, not portable", value.name,
                type(e).__name__, e, key)
            fname = f"{key}.mf.pkl"
            with open(os.path.join(directory, fname), "wb") as f:
                f.write(cloudpickle.dumps(value))
            return {"kind": "pickle", "file": fname}
        fname = f"{key}.stablehlo"
        with open(os.path.join(directory, fname), "wb") as f:
            f.write(blob)
        # no batch metadata: deserialize re-derives _fixed_batch from
        # the exported avals
        return {"kind": "model_fn", "file": fname, "name": value.name}
    import cloudpickle
    fname = f"{key}.pkl"
    with open(os.path.join(directory, fname), "wb") as f:
        f.write(cloudpickle.dumps(value))
    return {"kind": "pickle", "file": fname}


def _decode_value(desc: dict, directory: str):
    kind = desc["kind"]
    if kind == "json":
        return desc["value"]
    if kind == "ndarray":
        return np.load(os.path.join(directory, desc["file"]))
    if kind == "model_fn":
        from sparkdl_tpu.graph.function import ModelFunction
        with open(os.path.join(directory, desc["file"]), "rb") as f:
            return ModelFunction.deserialize(f.read(),
                                             name=desc.get("name",
                                                           "stablehlo"))
    if kind == "pickle":
        import cloudpickle
        with open(os.path.join(directory, desc["file"]), "rb") as f:
            return cloudpickle.loads(f.read())
    raise ValueError(f"unknown descriptor kind {kind!r}")


# ---------------------------------------------------------------------------
# save / load
# ---------------------------------------------------------------------------

def save_stage(stage, path: str) -> None:
    """Save a Transformer/Model/Estimator to ``path`` (created;
    must be empty or absent — never silently overwrites a prior save)."""
    os.makedirs(path, exist_ok=True)
    if os.listdir(path):
        # also catches a prior save that crashed before metadata.json:
        # mixing fresh sidecars with orphans would poison the artifact
        raise FileExistsError(
            f"{path} is not empty; choose a fresh directory "
            "(overwrite is never implicit)")
    cls = type(stage)
    params = {p.name: _encode_value(f"param_{p.name}", v, path)
              for p, v in stage._paramMap.items()
              if p.name not in stage._unsaved_param_names()}
    # defaults are saved too (pyspark DefaultParamsWriter): a stage
    # reloaded under a library version whose constructor defaults
    # changed must behave as it did when saved, not silently shift
    defaults = {p.name: _encode_value(f"default_{p.name}", v, path)
                for p, v in stage._defaultParamMap.items()
                if p.name not in stage._unsaved_param_names()
                and p.name not in {q.name for q in stage._paramMap}}
    extra = {k: _encode_value(f"extra_{k}", v, path)
             for k, v in stage._extra_state().items()}
    children = {}
    for name, child in stage._child_stages().items():
        child_dir = os.path.join(path, name)
        save_stage(child, child_dir)
        children[name] = True
    meta = {
        "format": FORMAT,
        "version": VERSION,
        "class": f"{cls.__module__}.{cls.__qualname__}",
        "params": params,
        "defaults": defaults,
        "extra": extra,
        "children": sorted(children),
    }
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(meta, f, indent=2, sort_keys=True)


def load_stage(path: str, *, trusted_modules: Optional[Iterable[str]] = None):
    """Load a stage saved by :func:`save_stage` (also exported as
    ``sparkdl_tpu.load_model``).

    .. warning:: Saved stages may contain cloudpickle sidecars, so
       loading ALWAYS may execute code from the artifact — only load
       directories you trust, exactly as with any pickle-based ML
       loader (Keras ``.h5``, torch ``.pt``, pyspark pickled params).
       The ``trusted_modules`` gate below is a guard against
       instantiating arbitrary classes by path, NOT a sandbox: it does
       not make loading an untrusted directory safe. Class resolution
       is restricted to ``sparkdl_tpu`` modules by default; pass
       ``trusted_modules=["my_pkg"]`` (prefix match) to load stages of
       your own classes, or ``trusted_modules=["*"]`` to disable the
       restriction entirely.
    """
    if isinstance(trusted_modules, str):
        trusted_modules = [trusted_modules]  # not char-by-char prefixes
    meta_path = os.path.join(path, "metadata.json")
    if not os.path.exists(meta_path):
        raise FileNotFoundError(
            f"{path} is not a saved stage (no metadata.json)")
    with open(meta_path) as f:
        meta = json.load(f)
    if meta.get("format") != FORMAT:
        raise ValueError(
            f"{path} was not written by sparkdl_tpu persistence "
            f"(format={meta.get('format')!r})")
    module, _, qualname = meta["class"].rpartition(".")
    allowed = ["sparkdl_tpu"] + sorted(trusted_modules or [])
    if "*" not in allowed and not any(
            module == m or module.startswith(m + ".") for m in allowed):
        raise ValueError(
            f"{path} declares stage class {meta['class']!r}, outside "
            f"the trusted module prefixes {allowed}; pass "
            "trusted_modules=[...] to load_model if you trust this "
            "artifact. (Loading any artifact can run code from it — "
            "this gate only blocks arbitrary class paths, it is not a "
            "sandbox.)")
    cls = importlib.import_module(module)
    for part in qualname.split("."):
        cls = getattr(cls, part)
    params = {name: _decode_value(d, path)
              for name, d in meta["params"].items()}
    extra = {name: _decode_value(d, path)
             for name, d in meta["extra"].items()}
    children = {name: load_stage(os.path.join(path, name),
                                 trusted_modules=trusted_modules)
                for name in meta.get("children", [])}
    stage = cls._from_saved(params, extra, children)
    # restore the SAVED defaults over whatever this library version's
    # constructor set (unknown names are skipped for forward compat)
    for name, d in meta.get("defaults", {}).items():
        if stage.hasParam(name):
            stage._defaultParamMap[stage.getParam(name)] = \
                _decode_value(d, path)
    # keyword_only constructors _set every kwarg explicitly, which would
    # shadow the restored saved defaults (getOrDefault reads _paramMap
    # before _defaultParamMap). Drop explicit entries the save did not
    # record as explicit — but only where a default still resolves the
    # param: _from_saved overrides legitimately fill params.get(name,
    # fallback) for params with no default (older-artifact compat), and
    # clearing those would leave getOrDefault raising KeyError.
    keep = set(meta["params"]) | stage._unsaved_param_names()
    for p in [p for p in stage._paramMap if p.name not in keep]:
        if p in stage._defaultParamMap:
            stage.clear(p)
    return stage
