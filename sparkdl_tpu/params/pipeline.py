"""Transformer / Estimator / Pipeline base classes.

The reference rode on Spark ML's abstractions
(``pyspark.ml.Transformer``/``Estimator``/``Pipeline``); here they are
implemented natively over the Arrow-backed :class:`sparkdl_tpu.data.DataFrame`
with the same composition semantics (``Pipeline(stages=[...]).fit(df)``,
``model.transform(df)``, param-map overrides on both).
"""

from __future__ import annotations

import logging
from abc import abstractmethod
from typing import Iterable, List, Optional, Sequence

from sparkdl_tpu.params.base import Param, Params, TypeConverters, keyword_only

logger = logging.getLogger(__name__)

# Multi-stage param claims already warned about this process run:
# CrossValidator calls copy() per candidate per fold, and repeating the
# identical line nFolds x nCandidates times would bury it.
_warned_shared_claims: set = set()


class Transformer(Params):
    """A pipeline stage mapping DataFrame → DataFrame."""

    def transform(self, dataset, params: Optional[dict] = None):
        if params:
            return self.copy(params)._transform(dataset)
        return self._transform(dataset)

    @abstractmethod
    def _transform(self, dataset):
        raise NotImplementedError


class Estimator(Params):
    """A pipeline stage fit(DataFrame) → Model."""

    def fit(self, dataset, params=None):
        if params is None:
            return self._fit(dataset)
        if isinstance(params, dict):
            return self.copy(params)._fit(dataset)
        if isinstance(params, (list, tuple)):
            return [m for _, m in self.fitMultiple(dataset, list(params))]
        raise TypeError(f"params must be dict or list of dicts, got {params!r}")

    def fitMultiple(self, dataset, paramMaps: Sequence[dict]):
        """Yield ``(index, model)`` for each param map. Subclasses with a
        parallel path (the Keras estimator) override this; the default fits
        sequentially."""
        for i, pm in enumerate(paramMaps):
            yield i, self.copy(pm)._fit(dataset)

    @abstractmethod
    def _fit(self, dataset):
        raise NotImplementedError


class Model(Transformer):
    """A Transformer produced by an Estimator."""


def _split_extra(owner: Params, extra):
    """Partition a param-map override into entries the pipeline-like
    ``owner`` itself owns vs entries destined for its stages — the
    pyspark semantic that makes
    ``CrossValidator(Pipeline([featurizer, lr]), grid_on_lr_params)``
    work: a grid entry keyed by a STAGE's Param must reach that stage's
    copy, not be resolved against the Pipeline (which owns only
    ``stages``). String keys resolve against the owner only — without
    a parent they cannot name a stage param unambiguously."""
    own, foreign = {}, {}
    for p, v in (extra or {}).items():
        if isinstance(p, str) or owner.hasParam(p.name):
            own[p] = v
        else:
            foreign[p] = v
    return own, foreign


def _child_stage_list(stage):
    """The nested stage list of a pipeline-like stage, else None.
    (``Pipeline.stages`` is a Param descriptor at class level, so the
    instance attribute probe applies only to PipelineModel.)"""
    if isinstance(stage, Pipeline):
        return stage.getStages()
    kids = getattr(stage, "stages", None)
    return kids if isinstance(kids, list) else None


def _carries_param(stage, p) -> bool:
    """Whether ``stage`` (or, recursively, a nested pipeline's stage)
    owns Param ``p`` — nested pipelines forward their sub-map through
    their own ``copy``, matching pyspark's recursive semantics."""
    if any(q == p for q in stage.params):
        return True
    kids = _child_stage_list(stage)
    return bool(kids) and any(_carries_param(k, p) for k in kids)


def _stage_subs(owner: Params, stages, foreign):
    """Per-stage sub-maps of ``foreign`` (entries owned by that stage,
    directly or through nesting); an entry no stage claims raises so
    typos stay loud. A Param carried by several stages (shared mixins
    like batchSize/inputCol — Param identity here is (owner class,
    name), not pyspark's per-instance uid) is applied to every stage
    carrying it, WITH a warning: pyspark would scope the entry to one
    stage, so a multi-stage hit is a real semantic divergence the user
    must be able to see (e.g. a CV grid on lr.batchSize silently also
    re-batching the featurizer)."""
    subs = []
    claims: dict = {}
    for s in stages:
        sub = {p: v for p, v in foreign.items() if _carries_param(s, p)}
        for p in sub:
            claims.setdefault(p, []).append(type(s).__name__)
        subs.append(sub)
    unclaimed = [p for p in foreign if p not in claims]
    if unclaimed:
        raise AttributeError(
            f"param map entries {unclaimed} belong to neither the "
            f"{type(owner).__name__} nor any of its stages")
    for p, owners in claims.items():
        key = (p, tuple(owners))
        if len(owners) > 1 and key not in _warned_shared_claims:
            _warned_shared_claims.add(key)
            logger.warning(
                "param map entry %s is carried by %d stages (%s) and "
                "applies to ALL of them — Param identity here is "
                "(owner class, name), not a per-instance uid; set the "
                "param on the intended stage directly to scope it",
                p, len(owners), ", ".join(owners))
    return subs


def _stages_as_children(stages):
    """Stage list → persistence child map (shared by Pipeline and
    PipelineModel; sorted keys are the reload order)."""
    return {f"stage_{i:04d}_{type(s).__name__}": s
            for i, s in enumerate(stages)}


def _stages_from_saved(params, children):
    """Reload order from child saves; falls back to a ``stages`` param
    value for artifacts saved before stages nested as children (the
    early save layout pickled the list into params)."""
    if children:
        return [children[k] for k in sorted(children)]
    return list(params.get("stages") or [])


class PipelineModel(Model):
    """Sequentially applies fitted stages."""

    def __init__(self, stages: List[Transformer]):
        super().__init__()
        self.stages = list(stages)

    def _transform(self, dataset):
        for stage in self.stages:
            dataset = stage.transform(dataset)
        return dataset

    def copy(self, extra: Optional[dict] = None) -> "PipelineModel":
        own, foreign = _split_extra(self, extra)
        subs = _stage_subs(self, self.stages, foreign)
        that = super().copy(own)  # preserves uid and subclass type
        that.stages = [s.copy(sub)
                       for s, sub in zip(self.stages, subs)]
        return that

    def _child_stages(self):
        return _stages_as_children(self.stages)

    @classmethod
    def _from_saved(cls, params, extra, children):
        return cls(_stages_from_saved(params, children))


class Pipeline(Estimator):
    """Chain of Transformers/Estimators, fitted front-to-back."""

    stages = Param("Pipeline", "stages", "pipeline stages",
                   TypeConverters.toList)

    @keyword_only
    def __init__(self, *, stages: Optional[List[Params]] = None):
        super().__init__()
        self._set(stages=stages or [])

    def setStages(self, stages: List[Params]) -> "Pipeline":
        return self._set(stages=stages)

    def getStages(self) -> List[Params]:
        return self.getOrDefault("stages")

    def copy(self, extra: Optional[dict] = None) -> "Pipeline":
        """Param-map entries owned by a STAGE are applied to that
        stage's copy, recursively through nested pipelines (pyspark
        semantics — what CrossValidator grids over child-stage params
        rely on); entries owned by the Pipeline itself (``stages``)
        apply to it FIRST, so stage sub-maps distribute over the
        overridden stage list; anything unclaimed raises."""
        own, foreign = _split_extra(self, extra)
        that = super().copy(own)
        stages = that.getStages()
        subs = _stage_subs(self, stages, foreign)
        that._set(stages=[s.copy(sub)
                          for s, sub in zip(stages, subs)])
        return that

    def _unsaved_param_names(self):
        return {"stages"}  # persisted as child stages, not a pickle

    def _child_stages(self):
        return _stages_as_children(self.getStages())

    @classmethod
    def _from_saved(cls, params, extra, children):
        return cls(stages=_stages_from_saved(params, children))

    def _fit(self, dataset) -> PipelineModel:
        stages = self.getStages()
        for s in stages:
            if not isinstance(s, (Transformer, Estimator)):
                raise TypeError(f"pipeline stage {s!r} is neither Transformer "
                                "nor Estimator")
        fitted: List[Transformer] = []
        last_est = max((i for i, s in enumerate(stages)
                        if isinstance(s, Estimator)), default=-1)
        for i, stage in enumerate(stages):
            if isinstance(stage, Estimator):
                model = stage.fit(dataset)
                fitted.append(model)
                if i < last_est:
                    dataset = model.transform(dataset)
            else:
                fitted.append(stage)
                if i < last_est:
                    dataset = stage.transform(dataset)
        return PipelineModel(fitted)


class EmptyScoredFrameError(ValueError):
    """Raised by evaluators when the scored frame has 0 rows (e.g. a
    validation fold whose rows were all filtered out). A TYPED error so
    tuning can distinguish "this fold had nothing to score" (skippable
    with a loud warning — CrossValidator nan-skips the fold) from a
    genuine evaluator misuse, while standalone ``evaluate`` calls still
    fail loudly (it is a ValueError)."""


class Evaluator(Params):
    """Scores a transformed DataFrame; used by CrossValidator.

    ``evaluate(dataset, params)`` with a param-map override scores
    through a copy carrying those params (pyspark convention) — the
    instance itself is never mutated. Implement ``_evaluate`` in
    subclasses (pyspark's convention too); a subclass that overrides
    ``evaluate`` itself bypasses this base and owns the params-override
    contract."""

    def evaluate(self, dataset, params: Optional[dict] = None) -> float:
        if params is not None and not isinstance(params, dict):
            raise TypeError(
                "params must be a dict of (Param | name) -> value, got "
                f"{type(params).__name__}")
        if params:
            # through the copy's own evaluate, so a subclass overriding
            # evaluate(dataset) still runs its override after the copy
            return self.copy(params).evaluate(dataset)
        return self._evaluate(dataset)

    def _evaluate(self, dataset) -> float:
        raise NotImplementedError

    def isLargerBetter(self) -> bool:
        return True
