"""Transformer / Estimator / Pipeline base classes.

The reference rode on Spark ML's abstractions
(``pyspark.ml.Transformer``/``Estimator``/``Pipeline``); here they are
implemented natively over the Arrow-backed :class:`sparkdl_tpu.data.DataFrame`
with the same composition semantics (``Pipeline(stages=[...]).fit(df)``,
``model.transform(df)``, param-map overrides on both).
"""

from __future__ import annotations

from abc import abstractmethod
from typing import Iterable, List, Optional, Sequence

from sparkdl_tpu.params.base import Param, Params, TypeConverters, keyword_only


class Transformer(Params):
    """A pipeline stage mapping DataFrame → DataFrame."""

    def transform(self, dataset, params: Optional[dict] = None):
        if params:
            return self.copy(params)._transform(dataset)
        return self._transform(dataset)

    @abstractmethod
    def _transform(self, dataset):
        raise NotImplementedError


class Estimator(Params):
    """A pipeline stage fit(DataFrame) → Model."""

    def fit(self, dataset, params=None):
        if params is None:
            return self._fit(dataset)
        if isinstance(params, dict):
            return self.copy(params)._fit(dataset)
        if isinstance(params, (list, tuple)):
            return [m for _, m in self.fitMultiple(dataset, list(params))]
        raise TypeError(f"params must be dict or list of dicts, got {params!r}")

    def fitMultiple(self, dataset, paramMaps: Sequence[dict]):
        """Yield ``(index, model)`` for each param map. Subclasses with a
        parallel path (the Keras estimator) override this; the default fits
        sequentially."""
        for i, pm in enumerate(paramMaps):
            yield i, self.copy(pm)._fit(dataset)

    @abstractmethod
    def _fit(self, dataset):
        raise NotImplementedError


class Model(Transformer):
    """A Transformer produced by an Estimator."""


def _stages_as_children(stages):
    """Stage list → persistence child map (shared by Pipeline and
    PipelineModel; sorted keys are the reload order)."""
    return {f"stage_{i:04d}_{type(s).__name__}": s
            for i, s in enumerate(stages)}


def _stages_from_saved(params, children):
    """Reload order from child saves; falls back to a ``stages`` param
    value for artifacts saved before stages nested as children (the
    early save layout pickled the list into params)."""
    if children:
        return [children[k] for k in sorted(children)]
    return list(params.get("stages") or [])


class PipelineModel(Model):
    """Sequentially applies fitted stages."""

    def __init__(self, stages: List[Transformer]):
        super().__init__()
        self.stages = list(stages)

    def _transform(self, dataset):
        for stage in self.stages:
            dataset = stage.transform(dataset)
        return dataset

    def copy(self, extra: Optional[dict] = None) -> "PipelineModel":
        that = PipelineModel([s.copy(extra) for s in self.stages])
        return that

    def _child_stages(self):
        return _stages_as_children(self.stages)

    @classmethod
    def _from_saved(cls, params, extra, children):
        return cls(_stages_from_saved(params, children))


class Pipeline(Estimator):
    """Chain of Transformers/Estimators, fitted front-to-back."""

    stages = Param("Pipeline", "stages", "pipeline stages",
                   TypeConverters.toList)

    @keyword_only
    def __init__(self, *, stages: Optional[List[Params]] = None):
        super().__init__()
        self._set(stages=stages or [])

    def setStages(self, stages: List[Params]) -> "Pipeline":
        return self._set(stages=stages)

    def getStages(self) -> List[Params]:
        return self.getOrDefault("stages")

    def _unsaved_param_names(self):
        return {"stages"}  # persisted as child stages, not a pickle

    def _child_stages(self):
        return _stages_as_children(self.getStages())

    @classmethod
    def _from_saved(cls, params, extra, children):
        return cls(stages=_stages_from_saved(params, children))

    def _fit(self, dataset) -> PipelineModel:
        stages = self.getStages()
        for s in stages:
            if not isinstance(s, (Transformer, Estimator)):
                raise TypeError(f"pipeline stage {s!r} is neither Transformer "
                                "nor Estimator")
        fitted: List[Transformer] = []
        last_est = max((i for i, s in enumerate(stages)
                        if isinstance(s, Estimator)), default=-1)
        for i, stage in enumerate(stages):
            if isinstance(stage, Estimator):
                model = stage.fit(dataset)
                fitted.append(model)
                if i < last_est:
                    dataset = model.transform(dataset)
            else:
                fitted.append(stage)
                if i < last_est:
                    dataset = stage.transform(dataset)
        return PipelineModel(fitted)


class Evaluator(Params):
    """Scores a transformed DataFrame; used by CrossValidator."""

    @abstractmethod
    def evaluate(self, dataset) -> float:
        raise NotImplementedError

    def isLargerBetter(self) -> bool:
        return True
