"""Core Param/Params machinery.

Re-designed equivalent of pyspark's ``Params`` plus the reference's
``python/sparkdl/param/__init__.py::SparkDLTypeConverters`` and
``keyword_only`` decorator. Params are typed, copy-on-write, and support
param maps (dict[Param, value]) so grid search / CrossValidator semantics
match what reference users expect.
"""

from __future__ import annotations

import copy
import functools
import inspect
from typing import Any, Callable, Dict, Iterable, Optional


class Param:
    """A typed parameter slot attached to a ``Params`` owner class.

    Unlike pyspark, the canonical identity of a Param is
    ``(owner class qualname, name)`` so Params survive instance copies.
    """

    __slots__ = ("parent", "name", "doc", "typeConverter")

    def __init__(self, parent: str, name: str, doc: str,
                 typeConverter: Optional[Callable[[Any], Any]] = None):
        self.parent = parent
        self.name = name
        self.doc = doc
        self.typeConverter = typeConverter or (lambda x: x)

    def __repr__(self) -> str:
        return f"Param({self.parent}.{self.name})"

    def __hash__(self) -> int:
        return hash((self.parent, self.name))

    def __eq__(self, other: object) -> bool:
        return (isinstance(other, Param)
                and self.parent == other.parent and self.name == other.name)


def keyword_only(func):
    """Decorator forcing keyword-only construction and capturing kwargs.

    Mirror of the reference's ``keyword_only`` (upstream
    ``python/sparkdl/param/__init__.py``): the wrapped method sees its
    keyword arguments in ``self._input_kwargs``.
    """

    @functools.wraps(func)
    def wrapper(self, *args, **kwargs):
        if args:
            raise TypeError(
                f"{func.__name__}() only accepts keyword arguments; "
                f"got {len(args)} positional")
        self._input_kwargs = kwargs
        return func(self, **kwargs)

    wrapper._keyword_only = True
    return wrapper


class Params:
    """Base class for anything carrying typed params.

    Semantics follow pyspark: a class-level ``Param`` descriptor registry,
    per-instance ``_paramMap`` (explicitly set) over ``_defaultParamMap``.
    """

    def __init__(self):
        # no lock: param maps are written at construction / explicit
        # set() and read afterwards; keeping instances lock-free also
        # keeps every stage picklable (Spark task shipping, the
        # persistence layer's pickle codec for estimator-valued params)
        self._paramMap: Dict[Param, Any] = {}
        self._defaultParamMap: Dict[Param, Any] = {}
        uid_cls = type(self).__name__
        self.uid = f"{uid_cls}_{id(self):x}"

    # -- registry -----------------------------------------------------------

    @property
    def params(self) -> list:
        """All Params declared on the class hierarchy, name-sorted."""
        seen = {}
        for klass in reversed(type(self).__mro__):
            for name, attr in vars(klass).items():
                if isinstance(attr, Param):
                    seen[attr.name] = attr
        return [seen[k] for k in sorted(seen)]

    def hasParam(self, paramName: str) -> bool:
        return any(p.name == paramName for p in self.params)

    def getParam(self, paramName: str) -> Param:
        for p in self.params:
            if p.name == paramName:
                return p
        raise AttributeError(
            f"{type(self).__name__} has no param '{paramName}'")

    def _resolveParam(self, param) -> Param:
        if isinstance(param, Param):
            return self.getParam(param.name)
        if isinstance(param, str):
            return self.getParam(param)
        raise TypeError(f"cannot resolve param from {param!r}")

    # -- get/set ------------------------------------------------------------

    def isSet(self, param) -> bool:
        return self._resolveParam(param) in self._paramMap

    def isDefined(self, param) -> bool:
        p = self._resolveParam(param)
        return p in self._paramMap or p in self._defaultParamMap

    def getOrDefault(self, param):
        p = self._resolveParam(param)
        if p in self._paramMap:
            return self._paramMap[p]
        if p in self._defaultParamMap:
            return self._defaultParamMap[p]
        raise KeyError(
            f"param {p.name!r} of {type(self).__name__} is not set "
            "and has no default")

    def set(self, param, value) -> "Params":
        p = self._resolveParam(param)
        self._paramMap[p] = p.typeConverter(value)
        return self

    def setParams(self, **kwargs) -> "Params":
        """Set several params by name in one call (the pyspark
        convention — ``lr.setParams(maxIter=10, labelCol="y")``).
        Unknown names raise; values pass through the same typed
        converters as :meth:`set`. An explicit ``None`` CLEARS the
        param back to its default (the typed converters don't accept
        None, and for nullable params like ``cacheDir`` the default is
        None — so this is how you set them back)."""
        for name, value in kwargs.items():
            if value is None:
                self.clear(name)
            else:
                self.set(name, value)
        return self

    def _set(self, **kwargs) -> "Params":
        for name, value in kwargs.items():
            if value is None:
                continue
            p = self.getParam(name)
            self._paramMap[p] = p.typeConverter(value)
        return self

    def _setDefault(self, **kwargs) -> "Params":
        for name, value in kwargs.items():
            p = self.getParam(name)
            self._defaultParamMap[p] = value
        return self

    def clear(self, param) -> "Params":
        self._paramMap.pop(self._resolveParam(param), None)
        return self

    def extractParamMap(self, extra: Optional[dict] = None) -> dict:
        pm = dict(self._defaultParamMap)
        pm.update(self._paramMap)
        if extra:
            pm.update(extra)
        return pm

    def explainParam(self, param) -> str:
        """One param's doc + current value (pyspark convention;
        accepts a Param or its name). A Param OBJECT from another class
        raises, as in pyspark — name-resolving it against this instance
        would explain a plausible-but-wrong same-named param."""
        if isinstance(param, Param) \
                and not any(p == param for p in self.params):
            # == (the class's declared (owner, name) identity), not
            # `is`: Params round-trip through cloudpickle on executors
            raise ValueError(
                f"Param {param.name!r} does not belong to "
                f"{type(self).__name__}")
        p = self._resolveParam(param)
        cur = (repr(self.getOrDefault(p))
               if self.isDefined(p) else "undefined")
        return f"{p.name}: {p.doc} (current: {cur})"

    def explainParams(self) -> str:
        return "\n".join(self.explainParam(p) for p in self.params)

    # -- copy ---------------------------------------------------------------

    def copy(self, extra: Optional[dict] = None) -> "Params":
        that = copy.copy(self)
        that._paramMap = dict(self._paramMap)
        that._defaultParamMap = dict(self._defaultParamMap)
        if extra:
            for p, v in extra.items():
                rp = that._resolveParam(p)
                that._paramMap[rp] = rp.typeConverter(v)
        return that

    def _copyValues(self, to: "Params", extra: Optional[dict] = None):
        pm = self.extractParamMap(extra)
        for p, v in pm.items():
            if to.hasParam(p.name):
                to._set(**{p.name: v})
        return to

    # -- persistence (pyspark ML save/load semantics) -----------------------

    def save(self, path: str) -> None:
        """Persist this stage (params + fitted state + child stages) to
        a directory; reload with :func:`sparkdl_tpu.load_model`."""
        from sparkdl_tpu.params.persistence import save_stage
        save_stage(self, path)

    def _extra_state(self) -> Dict[str, Any]:
        """Non-Param fitted state to persist (coefficients, histories,
        model functions). Subclasses override; keys are restored through
        ``_from_saved``'s ``extra``."""
        return {}

    def _child_stages(self) -> Dict[str, "Params"]:
        """Nested stages persisted as subdirectories (PipelineModel
        stages, CV bestModel). Keys are directory names; sorted order is
        the reload order."""
        return {}

    def _unsaved_param_names(self) -> set:
        """Params excluded from persistence (process-local handles)."""
        return set()

    @classmethod
    def _from_saved(cls, params: Dict[str, Any], extra: Dict[str, Any],
                    children: Dict[str, "Params"]) -> "Params":
        """Rebuild from saved state. Default: explicit params go
        straight back into the ``keyword_only`` constructor (pyspark's
        DefaultParamsReader pattern). Stages with required non-Param
        constructor args or children override this."""
        if extra or children:
            raise NotImplementedError(
                f"{cls.__name__} saved extra state/children but does "
                "not override _from_saved")
        return cls(**params)


class TypeConverters:
    """Typed converters for Param values.

    Re-design of the reference's
    ``python/sparkdl/param/__init__.py::SparkDLTypeConverters`` — the
    TF-specific converters (``toTFGraph``, ``toStringOrTFTensor``) become
    their TPU-era counterparts (model functions, tensor-name strings).
    """

    @staticmethod
    def toString(value) -> str:
        if isinstance(value, str):
            return value
        raise TypeError(f"expected str, got {type(value).__name__}")

    @staticmethod
    def toInt(value) -> int:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise TypeError(f"expected int, got {type(value).__name__}")
        if int(value) != value:
            raise TypeError(f"expected integral value, got {value}")
        return int(value)

    @staticmethod
    def toFloat(value) -> float:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise TypeError(f"expected float, got {type(value).__name__}")
        return float(value)

    @staticmethod
    def toBoolean(value) -> bool:
        if not isinstance(value, bool):
            raise TypeError(f"expected bool, got {type(value).__name__}")
        return value

    @staticmethod
    def toList(value) -> list:
        if isinstance(value, (list, tuple)):
            return list(value)
        raise TypeError(f"expected list, got {type(value).__name__}")

    @staticmethod
    def toListString(value) -> list:
        value = TypeConverters.toList(value)
        if not all(isinstance(v, str) for v in value):
            raise TypeError("expected list of str")
        return value

    @staticmethod
    def toIntPairOrNone(value):
        if value is None:
            return None
        value = TypeConverters.toList(value)
        if len(value) != 2:
            raise TypeError(f"expected (h, w) pair, got {value!r}")
        return (TypeConverters.toInt(value[0]),
                TypeConverters.toInt(value[1]))

    @staticmethod
    def toCallable(value):
        if callable(value):
            return value
        raise TypeError(f"expected callable, got {type(value).__name__}")

    @staticmethod
    def toStringDict(value) -> dict:
        """{str: str} mapping — column↔tensor maps, reference's
        column-to-tensor-name converters in SparkDLTypeConverters."""
        if isinstance(value, dict):
            items = value.items()
        elif isinstance(value, (list, tuple)):
            items = list(value)
        else:
            raise TypeError(
                f"expected dict or pair-list, got {type(value).__name__}")
        out = {}
        for k, v in items:
            if not isinstance(k, str) or not isinstance(v, str):
                raise TypeError("mapping keys and values must be str")
            out[k] = v
        return out

    @staticmethod
    def toHParams(value) -> dict:
        """{str: number/array} hyperparameter dict (reference:
        ``SparkDLTypeConverters.toTFHParams`` — a tf.contrib HParams
        bag; here a plain dict of named constants)."""
        import numpy as np
        if not isinstance(value, dict):
            raise TypeError(
                f"expected hyperparams dict, got {type(value).__name__}")
        out = {}
        for k, v in value.items():
            if not isinstance(k, str):
                raise TypeError("hyperparam names must be str")
            if not isinstance(v, (int, float, bool, np.ndarray, list, tuple)):
                raise TypeError(
                    f"hyperparam {k!r} must be numeric or array-like, "
                    f"got {type(v).__name__}")
            out[k] = v
        return out

    @staticmethod
    def toModelFunction(value):
        """Accepts a ModelFunction (the XlaFunction/StableHLO bundle) —
        TPU-era replacement of ``toTFGraph``/``toTFInputGraph``."""
        from sparkdl_tpu.graph.function import ModelFunction
        if isinstance(value, ModelFunction):
            return value
        raise TypeError(
            f"expected ModelFunction, got {type(value).__name__}")

    @staticmethod
    def toOptimizer(value):
        """Accepts an optax GradientTransformation or its factory name
        (reference: ``toKerasOptimizer``)."""
        import optax
        if isinstance(value, str):
            if not hasattr(optax, value):
                raise TypeError(f"unknown optax optimizer '{value}'")
            return value
        if isinstance(value, optax.GradientTransformation):
            return value
        raise TypeError(
            f"expected optimizer name or optax transform, got {value!r}")

    @staticmethod
    def toLoss(value):
        """Accepts a loss callable or an optax loss name
        (reference: ``toKerasLoss``)."""
        import optax
        if isinstance(value, str):
            if not hasattr(optax, value) and value not in (
                    "categorical_crossentropy", "binary_crossentropy", "mse"):
                raise TypeError(f"unknown loss '{value}'")
            return value
        if callable(value):
            return value
        raise TypeError(f"expected loss name or callable, got {value!r}")
