"""The accelerator-host side of the decode fleet: fan-out + ordered
re-merge over the socket transport.

:class:`RemotePipeline` is the client of one or more
:class:`~sparkdl_tpu.inputsvc.server.DecodeServer` workers
(``SPARKDL_TPU_INPUTSVC_WORKERS="host:port,host:port"`` or the
engine's ``inputsvc_endpoints`` ctor arg). Per stream it:

* pings every configured endpoint and DROPS unreachable ones loudly
  (``inputsvc.endpoints_down`` + one warning — a half-provisioned
  fleet streams on what answered; an empty one returns ``None`` so
  :class:`~sparkdl_tpu.data.engine.LocalEngine` falls back to its
  local path, counted in ``inputsvc.fallbacks``);
* fans partitions out round-robin across the live endpoints and
  re-merges fragments strictly in partition order with a bounded
  look-ahead window (the engine's live ``pipeline_read_ahead`` knob)
  — row identity and order are EXACT through the remote path;
* classifies every wire failure TYPED-transient
  (:class:`~sparkdl_tpu.inputsvc.transport.TransportError`, plus the
  ``inputsvc.rpc`` fault site) and re-runs the partition through the
  engine's shared :class:`~sparkdl_tpu.resilience.policy.RetryPolicy`;
  a partition whose transient budget is exhausted — or whose last
  endpoint died mid-stream — FAILS OVER to local decode
  (``inputsvc.local_decodes`` + one warning), so a killed worker
  costs throughput, never a row;
* ingests the telemetry frame riding each result tuple into the
  parent aggregator (``obs/remote.py``) — remote workers land in
  ``/statusz``'s ``workers`` list and the clock-aligned trace merge
  exactly like pool workers — and folds each fragment's reported
  decode busy-seconds into ``engine.busy_seconds`` (the ledger's ONE
  decode-lane feed).

The utilization ledger scales its decode ceiling by the live remote
fleet: this module mirrors the host pipeline's worker bookkeeping
(``inputsvc.workers`` gauge + window/alltime peaks), and
``obs/ledger.py`` ADDS the remote peak to the local pooled peak — N
remote workers are N additional decode lanes beyond the host's own
(``decode_workers`` in every ledger window; docs/DATA_SERVICE.md).
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import pyarrow as pa

from sparkdl_tpu.inputsvc import transport
from sparkdl_tpu.obs import default_registry, span
from sparkdl_tpu.resilience.errors import TransientError
from sparkdl_tpu.resilience.faults import maybe_fail

logger = logging.getLogger(__name__)

#: the fleet env knob: comma-separated ``host:port`` endpoints. Unset =
#: no remote decode; a malformed spec degrades to none with one warning
#: + ``inputsvc.config_errors`` (the repo-wide config-typo discipline)
ENV_ENDPOINTS = "SPARKDL_TPU_INPUTSVC_WORKERS"

#: connect + handshake timeout per endpoint — an unreachable worker
#: must cost seconds at stream START, not a hung stream
CONNECT_TIMEOUT_S = 5.0

#: per-RPC reply timeout: a wedged worker surfaces as a TYPED transient
#: (socket timeout → TransportError) that retries on a live sibling and
#: fails over to local decode — never a silently hung stream
DEFAULT_RPC_TIMEOUT_S = 120.0


def _count(what: str, amount: float = 1.0) -> None:
    default_registry().counter(f"inputsvc.{what}").add(amount)


def resolve_endpoints(explicit=None) -> List[Tuple[str, int]]:
    """The configured fleet: an explicit ctor value (comma string or
    list of ``host:port``) wins, then :data:`ENV_ENDPOINTS`. ANY
    malformed entry degrades the whole spec to no-fleet with one
    warning + ``inputsvc.config_errors`` — a typo'd fleet must never
    make the engine unusable, and silently dropping one endpoint of
    three would quietly re-shape the fleet instead."""
    if explicit is None:
        raw = os.environ.get(ENV_ENDPOINTS, "")
    elif isinstance(explicit, str):
        raw = explicit
    else:
        raw = ",".join(str(e) for e in explicit)
    raw = raw.strip()
    if not raw:
        return []
    out: List[Tuple[str, int]] = []
    for entry in raw.split(","):
        if not entry.strip():
            continue
        ep = transport.parse_endpoint(entry)
        if ep is None:
            logger.warning(
                "%s entry %r is not host:port; remote decode disabled "
                "(fix the full spec — a partial fleet would be a "
                "different deployment than configured)",
                ENV_ENDPOINTS if explicit is None else
                "inputsvc_endpoints", entry)
            _count("config_errors")
            return []
        out.append(ep)
    return out


_warned_once: set = set()
_warn_lock = threading.Lock()


def _warn_once(key: str, msg: str, *args) -> None:
    with _warn_lock:
        fire = key not in _warned_once
        _warned_once.add(key)
    if fire:
        from sparkdl_tpu.obs import remote
        if remote.capture_degrade(f"inputsvc:{key}",
                                  msg % args if args else msg):
            return
        logger.warning(msg, *args)


# the live remote-worker bookkeeping the utilization ledger reads
# (obs/ledger.py): the decode lane's ceiling ADDS the remote fleet's
# window peak to the local pooled peak — same shape, same reasoning as
# data/pipeline.py's _workers_peak (a remote stream that ended
# mid-window already banked its N workers' busy-seconds)
_active_streams: Dict[int, Tuple[int, float]] = {}  # sid -> (workers, t0)
_active_lock = threading.Lock()
_stream_seq = 0
_workers_peak = 0
_workers_alltime = 0


def _enter_stream(workers: int) -> int:
    global _stream_seq, _workers_peak, _workers_alltime
    with _active_lock:
        _stream_seq += 1
        sid = _stream_seq
        _active_streams[sid] = (workers, time.perf_counter())
        live = max(w for w, _ in _active_streams.values())
        _workers_peak = max(_workers_peak, live)
        _workers_alltime = max(_workers_alltime, live)
    default_registry().gauge("inputsvc.workers").set(live)
    return sid


def _exit_stream(sid: int) -> None:
    with _active_lock:
        entry = _active_streams.pop(sid, None)
        live = max((w for w, _ in _active_streams.values()), default=0)
    default_registry().gauge("inputsvc.workers").set(live)
    if entry is not None:
        _count("stream_seconds", time.perf_counter() - entry[1])


def consume_workers_peak() -> int:
    """Max live remote workers since the previous call — the ledger's
    per-window read (obs/ledger.py), mirroring the host pipeline's
    contract: resets to the current live count so each window consumes
    exactly its own history."""
    global _workers_peak
    with _active_lock:
        live = max((w for w, _ in _active_streams.values()), default=0)
        peak = max(_workers_peak, live)
        _workers_peak = live
        return peak


def alltime_workers_peak() -> int:
    """Process-lifetime remote-worker high-water mark — the ledger's
    cumulative-verdict ceiling component."""
    with _active_lock:
        live = max((w for w, _ in _active_streams.values()), default=0)
        return max(_workers_alltime, live)


# the last-resolved fleet picture, for /statusz, flight bundles, and
# bench's input_service block (one shape everywhere)
_last_state: Dict[str, Any] = {}
_state_lock = threading.Lock()


def _record_state(**kv) -> None:
    with _state_lock:
        _last_state.update(kv)


def state() -> Dict[str, Any]:
    """The scrape-able input-service state (``/statusz`` ``inputsvc``,
    flight bundles): the last stream's resolved fleet + the live
    ``inputsvc.*`` counters (the snapshot tier's counters share the
    prefix and ride along)."""
    snap = default_registry().snapshot()
    with _state_lock:
        out = dict(_last_state)
    with _active_lock:
        out["streams_active"] = len(_active_streams)
        out["workers_live"] = max(
            (w for w, _ in _active_streams.values()), default=0)
    out["counters"] = {k: v for k, v in snap.items()
                       if k.startswith("inputsvc.")}
    return out


class _FleetUnavailable(TransientError):
    """No live endpoint remains for this RPC — transient (a sibling
    retry may land after a reconnect), and past the retry budget the
    caller's local-decode failover owns it."""


class _Endpoint:
    """One connected decode worker: a socket and the lock serializing
    RPCs on it (one in-flight request per connection — the framing has
    no request ids; parallelism comes from the fleet width)."""

    # sparkdl-lint H3 contract: RPCs and death-marking race from the
    # fan-out pool's threads — socket use holds self._lock
    _lock_guards = ("sock", "alive")

    def __init__(self, host: str, port: int,
                 rpc_timeout_s: float = DEFAULT_RPC_TIMEOUT_S):
        self.host = host
        self.port = port
        self.rpc_timeout_s = rpc_timeout_s
        self.sock: Optional[socket.socket] = None
        self.alive = False
        self._lock = threading.Lock()

    def __getstate__(self):
        state = self.__dict__.copy()
        del state["_lock"]
        state["sock"] = None
        state["alive"] = False
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()

    def connect(self) -> bool:
        """Dial + ping handshake; False (never raises) on an
        unreachable/refusing/mis-speaking peer — stream start owns the
        loud accounting."""
        try:
            sock = socket.create_connection(
                (self.host, self.port), timeout=CONNECT_TIMEOUT_S)
            transport.send_msg(sock, {"op": "ping"})
            header, _ = transport.recv_msg(sock)
            if not header.get("ok"):
                raise transport.TransportError(
                    f"ping rejected: {header!r}")
            sock.settimeout(self.rpc_timeout_s)
        except (OSError, transport.TransportError) as e:
            logger.debug("inputsvc: endpoint %s:%d unreachable: %s",
                         self.host, self.port, e)
            return False
        with self._lock:
            self.sock = sock
            self.alive = True
        return True

    def rpc_decode(self, token: str, plan_blob: bytes, src_blob: bytes,
                   index: int, tel: Optional[dict]) -> tuple:
        """One partition's remote decode → the raw result tuple. Any
        wire failure marks this endpoint dead and raises TYPED
        transient; the caller retries (possibly on a sibling) through
        the engine's shared RetryPolicy."""
        import cloudpickle
        with self._lock:
            sock = self.sock
            if not self.alive or sock is None:
                raise _FleetUnavailable(
                    f"endpoint {self.host}:{self.port} is down")
            try:
                transport.send_msg(
                    sock,
                    {"op": "decode", "token": token, "index": index,
                     "plan_len": len(plan_blob), "tel": tel},
                    plan_blob + src_blob)
                # sparkdl-lint: allow[H8] -- the hold IS the RPC slot: each endpoint socket is a serial request/response channel, so the reply recv must stay inside the lock that serialized the send; fan-out parallelism lives ACROSS endpoints, not on one socket
                header, payload = transport.recv_msg(sock)
            except (OSError, transport.TransportError) as e:
                self._mark_dead_locked()
                _count("rpc_errors")
                if isinstance(e, transport.TransportError):
                    raise
                raise transport.TransportError(
                    f"decode RPC to {self.host}:{self.port} "
                    f"failed: {e}") from e
        if not header.get("ok"):
            _count("rpc_errors")
            raise transport.TransportError(
                f"endpoint {self.host}:{self.port} rejected the "
                f"decode RPC: {header.get('error')!r}")
        _count("bytes", len(payload))
        return cloudpickle.loads(payload)

    def _mark_dead_locked(self) -> None:
        # deferred import mirrors data/pipeline.py: rare path, and the
        # data layer must not pull the jax-importing runtime package
        # at module load
        from sparkdl_tpu.runtime.sanitize import assert_lock_owned
        assert_lock_owned(self._lock, "_Endpoint._mark_dead_locked")
        sock, self.sock = self.sock, None
        # sparkdl-lint: allow[H3] -- caller holds self._lock, asserted by assert_lock_owned above (the _locked-suffix private-helper pattern data/pipeline.py uses)
        self.alive = False
        if sock is not None:
            try:
                sock.close()
            except OSError as e:
                logger.debug("inputsvc: closing a dead endpoint "
                             "socket failed: %s", e)

    def close(self) -> None:
        with self._lock:
            self._mark_dead_locked()

    def is_alive(self) -> bool:
        with self._lock:
            return self.alive


class RemotePipeline:
    """Fan partitions out to the configured decode fleet and re-merge
    fragments in order (module docstring). One instance per stream —
    connections are per-stream, so a shipped/pickled engine never
    carries a live socket (H3)."""

    def __init__(self, endpoints: Sequence[Tuple[str, int]],
                 rpc_timeout_s: float = DEFAULT_RPC_TIMEOUT_S):
        self.endpoints = [_Endpoint(h, p, rpc_timeout_s)
                          for h, p in endpoints]

    def _connect_fleet(self) -> List[_Endpoint]:
        live: List[_Endpoint] = []
        for ep in self.endpoints:
            if ep.connect():
                live.append(ep)
            else:
                _count("endpoints_down")
                _warn_once(
                    f"down:{ep.host}:{ep.port}",
                    "inputsvc: decode worker %s:%d is unreachable; "
                    "streaming on the remaining fleet (local decode "
                    "if none remains)", ep.host, ep.port)
        return live

    def _pickle_payload(self, sources: Sequence, plan: Sequence
                        ) -> Optional[Tuple[bytes, List[bytes]]]:
        """(plan blob, per-source blobs) when the H3 shipping
        discipline holds, else None — the local-fallback trigger (a
        plan that cannot cross a process boundary cannot cross a
        socket either)."""
        import cloudpickle
        try:
            plan_blob = cloudpickle.dumps(list(plan))
            src_blobs = [cloudpickle.dumps(s) for s in sources]
            return plan_blob, src_blobs
        except Exception as e:
            _warn_once(f"pickle:{type(e).__name__}",
                       "inputsvc: plan/source does not survive the "
                       "cloudpickle round-trip (%s: %s); decoding "
                       "locally", type(e).__name__, e)
            _count("fallbacks")
            return None

    def stream(self, sources: Sequence, plan: Sequence, engine
               ) -> Optional[Iterator[Tuple[int, pa.RecordBatch]]]:
        """Yield ``(logical_index, fragment)`` in partition order via
        the remote fleet, or ``None`` when no remote stream can run
        (nothing picklable, or zero endpoints answered) — the engine
        then falls through to its local path, loudly
        (``inputsvc.fallbacks``)."""
        import uuid
        plan = list(plan)
        payload = self._pickle_payload(sources, plan)
        if payload is None:
            return None
        live = self._connect_fleet()
        _record_state(
            endpoints=[f"{ep.host}:{ep.port}" for ep in self.endpoints],
            live_endpoints=[f"{ep.host}:{ep.port}" for ep in live])
        if not live:
            _count("fallbacks")
            _warn_once("fleet-empty",
                       "inputsvc: no configured decode worker is "
                       "reachable; falling back to LOCAL decode (the "
                       "fleet is provisioned but absent — this is a "
                       "deployment problem, not a data one)")
            return None
        plan_blob, src_blobs = payload
        token = uuid.uuid4().hex
        from sparkdl_tpu.obs import remote
        tel = remote.telemetry_config()
        return self._merge(sources, plan, engine, live, plan_blob,
                           src_blobs, token, tel)

    def _merge(self, sources, plan, engine, live, plan_blob, src_blobs,
               token, tel):
        from sparkdl_tpu.data.pipeline import _consume_result
        drain = (any(getattr(st, "effectful", False) for st in plan)
                 or any(getattr(src, "effectful", False)
                        for src in sources))
        rr_lock = threading.Lock()
        rr = [0]

        def _logical(pos: int) -> int:
            logical = getattr(sources[pos], "logical_index", None)
            return pos if logical is None else logical

        def _pick() -> _Endpoint:
            with rr_lock:
                rr[0] += 1
                start = rr[0]
            for i in range(len(live)):
                ep = live[(start + i) % len(live)]
                if ep.is_alive():
                    return ep
            raise _FleetUnavailable(
                "every connected decode worker died mid-stream")

        def _fetch(pos: int) -> pa.RecordBatch:
            logical = _logical(pos)

            def once() -> pa.RecordBatch:
                # the fragment-RPC fault site: the drill that proves
                # zero lost/duplicated rows under a lossy wire
                # (tools/ci.sh; docs/RESILIENCE.md)
                maybe_fail("inputsvc.rpc")
                ep = _pick()
                result = ep.rpc_decode(token, plan_blob,
                                       src_blobs[pos], logical, tel)
                # same consume as the pool transport: frame ingest,
                # typed re-raise of ("err", ...), zero-copy batch
                batch, busy, timings = _consume_result(result)
                default_registry().counter(
                    "engine.busy_seconds").add(busy)
                if engine.stage_metrics is not None:
                    for name, seconds, rows in timings:
                        engine.stage_metrics.add(name, seconds, rows)
                return batch

            try:
                return engine.retry_policy.call(
                    once, key=f"inputsvc:{logical}",
                    on_retry=engine._log_retry(
                        f"remote partition {logical}"))
            except TransientError as exc:
                # retry budget exhausted (or the whole fleet died):
                # LOCAL failover — a dead worker costs throughput,
                # never a row. Loud: counted + one warning; permanent
                # errors propagate typed (a decode that fails on bad
                # data fails locally too — retrying it here would
                # just mask it).
                _count("local_decodes")
                _warn_once("local-failover",
                           "inputsvc: remote decode failed past the "
                           "retry budget (%s); failing over to local "
                           "decode for affected partitions",
                           type(exc).__name__)
                return engine._run_partition(sources[pos], plan, pos)

        def _gen():
            sid = _enter_stream(len(live))
            pool = ThreadPoolExecutor(
                max_workers=len(live),
                thread_name_prefix="sparkdl-inputsvc")
            pending: Dict[int, Future] = {}
            next_to_submit = 0
            next_to_yield = 0
            n = len(sources)
            try:
                while next_to_yield < n:
                    window = max(len(live), int(getattr(
                        engine, "pipeline_read_ahead", 0) or 1))
                    while (next_to_submit < n
                           and len(pending) < window):
                        pending[next_to_submit] = pool.submit(
                            _fetch, next_to_submit)
                        next_to_submit += 1
                    pos = next_to_yield
                    fut = pending.pop(pos)
                    with span("inputsvc.fragment", lane="engine",
                              partition=_logical(pos),
                              workers=len(live)):
                        batch = fut.result()
                    _count("tasks")
                    _count("rows", batch.num_rows)
                    yield _logical(pos), batch
                    next_to_yield += 1
            finally:
                for fut in pending.values():
                    fut.cancel()
                if drain:
                    # the engine's quiesce discipline: an effectful
                    # straggler finishing AFTER the caller's cleanup
                    # corrupts the cleanup's outcome
                    for fut in pending.values():
                        if not fut.cancelled():
                            try:
                                fut.result()
                            except Exception as drain_err:
                                logger.debug(
                                    "inputsvc quiesce drain error: %s",
                                    drain_err)
                pool.shutdown(wait=False, cancel_futures=True)
                for ep in live:
                    ep.close()
                _exit_stream(sid)

        return _gen()
