"""The decode-fleet worker: a socket server running partition decode.

One :class:`DecodeServer` is one remote decode worker (the tf.data
*service mode* shape, PAPERS.md arxiv 2101.12127). It accepts framed
requests (``inputsvc/transport.py``) and runs each partition through
**the same task the process pool runs**
(:func:`~sparkdl_tpu.data.pipeline._pooled_partition_task`): source
load + the host-stage prefix, fault sites, worker-lane spans, watchdog
pulses, busy-second accounting — with shared memory disabled (a socket
peer cannot attach a POSIX segment), so every fragment comes back as
the ``("buf", payload, busy, timings, rows)`` tuple the client already
knows how to consume.

Telemetry crosses the same wire: the client forwards its parent-side
:func:`~sparkdl_tpu.obs.remote.telemetry_config` in each decode
request, the server-process :class:`~sparkdl_tpu.obs.remote.TelemetryAgent`
arms once and appends one frame to each result tuple, and the client
ingests it into the parent aggregator exactly as the pool transport
does — a remote worker shows up in ``/statusz``'s ``workers`` list,
the clock-aligned trace merge, and flight bundles like any pooled
worker.

Ops:

* ``ping`` — handshake/liveness: replies ``{ok, pid, version}``. The
  client pings each endpoint at stream start and drops unreachable
  ones loudly.
* ``decode`` — header carries ``token`` (plan-cache key), ``index``,
  ``plan_len``, and the optional ``tel`` config; the payload is the
  cloudpickled plan blob followed by the cloudpickled source blob.
  The reply payload is the cloudpickled result tuple.

A handler failure that can still be reported replies
``{ok: False, error}``; one that cannot (broken socket) drops the
connection — either way the CLIENT owns recovery (retry through the
shared RetryPolicy, then local-decode failover), so a dying worker can
never lose or duplicate a row. Accounting:
``inputsvc.server_requests`` / ``inputsvc.server_errors``.

``python -m sparkdl_tpu.inputsvc serve --port N`` runs one server in
the foreground (``__main__.py``) and prints a READY line naming the
bound port — the two-process CI drill's handle (tools/ci.sh).
"""

from __future__ import annotations

import logging
import socket
import threading
from typing import Any, Dict, Optional

from sparkdl_tpu.inputsvc import transport
from sparkdl_tpu.obs import default_registry

logger = logging.getLogger(__name__)

#: shared-memory floor passed to the pooled task: effectively infinite,
#: so every fragment rides the result tuple ("buf") — a socket peer
#: cannot attach this process's POSIX segments
_NO_SHM = 1 << 62


def _count(what: str, amount: float = 1.0) -> None:
    default_registry().counter(f"inputsvc.{what}").add(amount)


class DecodeServer:
    """One decode-fleet worker process (module docstring). Thread-per-
    connection: decode is process-heavy, connection counts are tiny
    (one client connection per stream per client), and the pooled task
    it runs is already thread-safe."""

    # sparkdl-lint H3 contract: the accept loop and close() race on the
    # listener and connection bookkeeping — both hold self._lock
    _lock_guards = ("_conns", "_closed")

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self._sock = socket.create_server((host, port))
        self.port = self._sock.getsockname()[1]
        self._lock = threading.Lock()
        self._conns: list = []
        self._closed = False
        self._accept_thread: Optional[threading.Thread] = None

    # a server never ships (sockets don't pickle — H3): refuse loudly
    # rather than arriving somewhere as a dead listener
    def __getstate__(self):
        raise TypeError("DecodeServer holds live sockets and cannot "
                        "be pickled; ship its host:port endpoint "
                        "instead")

    def start(self) -> "DecodeServer":
        """Serve in a background thread (tests, in-process fleets);
        returns self so ``DecodeServer(port=0).start()`` composes."""
        t = threading.Thread(target=self.serve_forever,
                             name=f"inputsvc-accept:{self.port}",
                             daemon=True)
        self._accept_thread = t
        t.start()
        return self

    def serve_forever(self) -> None:
        """Accept connections until :meth:`close` (the CLI's foreground
        loop)."""
        while True:
            try:
                conn, addr = self._sock.accept()
            except OSError:
                # listener closed (close()) — the clean exit path
                return
            with self._lock:
                if self._closed:
                    conn.close()
                    return
                self._conns.append(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn, addr),
                name=f"inputsvc-conn:{addr[1]}", daemon=True).start()

    def _serve_conn(self, conn: socket.socket, addr) -> None:
        try:
            while True:
                try:
                    header, payload = transport.recv_msg(conn)
                except transport.TransportError as e:
                    # normal client hang-up lands here too — log at
                    # debug; a mid-frame corruption is the client's
                    # problem to retry (its send will see the close)
                    logger.debug("inputsvc server: connection %s "
                                 "ended: %s", addr, e)
                    return
                self._dispatch(conn, header, payload)
        finally:
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)
            conn.close()

    def _dispatch(self, conn: socket.socket, header: dict,
                  payload: bytes) -> None:
        _count("server_requests")
        op = header.get("op")
        try:
            if op == "ping":
                import os
                transport.send_msg(conn, {
                    "ok": True, "pid": os.getpid(),
                    "version": transport.WIRE_VERSION})
                return
            if op == "decode":
                self._handle_decode(conn, header, payload)
                return
            _count("server_errors")
            transport.send_msg(conn, {
                "ok": False,
                "error": f"unknown op {op!r}"})
        except transport.TransportError:
            # the reply could not be sent — nothing left to tell this
            # client; it will classify the dead socket as transient
            # and retry/fail over on its side
            _count("server_errors")
            logger.warning("inputsvc server: reply to %r failed; "
                           "dropping connection", op)
            raise

    def _handle_decode(self, conn: socket.socket, header: dict,
                       payload: bytes) -> None:
        import cloudpickle

        from sparkdl_tpu.data.pipeline import _pooled_partition_task
        token = str(header.get("token", ""))
        index = int(header.get("index", 0))
        plan_len = int(header.get("plan_len", 0))
        tel = header.get("tel") or None
        if not 0 <= plan_len <= len(payload):
            _count("server_errors")
            transport.send_msg(conn, {
                "ok": False,
                "error": f"plan_len {plan_len} out of range for a "
                         f"{len(payload)}-byte payload"})
            return
        plan_blob = payload[:plan_len]
        src_blob = payload[plan_len:]
        # the pooled task NEVER raises — failures come back as a typed
        # ("err", ...) tuple the client re-raises, so the transport
        # only ever carries a well-formed reply
        result = _pooled_partition_task(token, plan_blob, src_blob,
                                        index, _NO_SHM, tel)
        transport.send_msg(conn, {"ok": True},
                           cloudpickle.dumps(result))

    def close(self) -> None:
        """Stop accepting and drop live connections (in-flight replies
        abort — the client's transient-retry/failover path owns
        recovery)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            conns = list(self._conns)
        try:
            # close() alone does not wake a thread parked in accept()
            # on Linux — shutdown() does, so the accept thread exits
            # instead of leaking one parked thread per server
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError as e:
            logger.debug("inputsvc server: listener shutdown "
                         "failed: %s", e)
        self._sock.close()
        for conn in conns:
            try:
                conn.close()
            except OSError as e:
                logger.debug("inputsvc server: closing a connection "
                             "failed: %s", e)
        t = self._accept_thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            return {"host": self.host, "port": self.port,
                    "connections": len(self._conns),
                    "closed": self._closed}
