"""Disaggregated input service: a socket-transport decode fleet + a
content-addressed corpus snapshot cache (docs/DATA_SERVICE.md).

The tf.data *service mode* pair (PAPERS.md, arxiv 2101.12127) on top
of the parallel host pipeline:

* :class:`~sparkdl_tpu.inputsvc.server.DecodeServer` — one remote
  decode worker, running the SAME partition task the process pool
  runs, over the length-prefixed socket transport
  (``python -m sparkdl_tpu.inputsvc serve --port N``);
* :class:`~sparkdl_tpu.inputsvc.client.RemotePipeline` — the
  accelerator-host client: fan-out, ordered re-merge with exact row
  identity, typed-transient retry, loud local-decode failover
  (engaged by :class:`~sparkdl_tpu.data.engine.LocalEngine` via
  ``inputsvc_endpoints`` / ``SPARKDL_TPU_INPUTSVC_WORKERS``);
* :func:`~sparkdl_tpu.inputsvc.snapshot.snapshot_sources` — the
  epoch-amortized packed-tensor store behind
  :meth:`DataFrame.snapshot <sparkdl_tpu.data.frame.DataFrame.snapshot>`.
"""

from sparkdl_tpu.inputsvc.client import (
    ENV_ENDPOINTS,
    RemotePipeline,
    resolve_endpoints,
    state,
)
from sparkdl_tpu.inputsvc.server import DecodeServer
from sparkdl_tpu.inputsvc.snapshot import (
    SNAPSHOT_VERSION,
    snapshot_key,
    snapshot_sources,
)
from sparkdl_tpu.inputsvc.transport import (
    WIRE_VERSION,
    TransportError,
    recv_msg,
    send_msg,
)

__all__ = [
    "ENV_ENDPOINTS",
    "SNAPSHOT_VERSION",
    "WIRE_VERSION",
    "DecodeServer",
    "RemotePipeline",
    "TransportError",
    "recv_msg",
    "resolve_endpoints",
    "send_msg",
    "snapshot_key",
    "snapshot_sources",
    "state",
]
