"""The corpus snapshot cache: a content-addressed store of decoded
packed tensors, amortizing decode to ~zero across epochs and tenants.

The tf.data paper's second disaggregation lever (PAPERS.md, arxiv
2101.12127): once a corpus has been decoded under a given decode
configuration, NOBODY should pay that decode again — not the second
epoch, not the second job, not the second tenant sharing the store.
:meth:`~sparkdl_tpu.data.frame.DataFrame.snapshot` extends
``cache_to_disk`` with the three properties a SHARED multi-run store
needs that a private spill dir does not:

* **content addressing** — the store key is
  ``blake2b(SNAPSHOT_VERSION | corpus fingerprint | decode-config
  key)``: a corpus content change, a decode-config change, or a
  snapshot-format version bump each lands in a DIFFERENT key
  directory and decodes cold. Stale data is unreachable by
  construction, not by bookkeeping.
* **self-validating chunks** — each partition's Arrow IPC payload is
  wrapped in a framed chunk file carrying its own blake2b digest. A
  truncated or corrupted chunk fails CLOSED on read: the bad chunk is
  deleted and that partition re-decodes cleanly
  (``inputsvc.snapshot_corruptions``) — never a silent stale read,
  never a crash.
* **versioned manifest** — ``MANIFEST.json`` pins version /
  fingerprint / decode key / schema / partition count. A manifest
  that is unreadable or disagrees with the expected identity (a
  tampered or half-written store) is wiped and rebuilt
  (``inputsvc.snapshot_invalidations``).

Warm reads run through the ``snapshot.read`` fault site
(``SPARKDL_TPU_FAULTS``), so the corrupt/missing-chunk recovery path
is drillable on demand; the second-epoch payoff — ``pipeline.decode``
busy-seconds ≈ 0 at ≥ serial-decode throughput — is gated in
tools/ci.sh (docs/DATA_SERVICE.md).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import struct
import threading
from typing import List, Optional, Sequence

import pyarrow as pa

from sparkdl_tpu.obs import default_registry
from sparkdl_tpu.resilience.errors import TransientError
from sparkdl_tpu.resilience.faults import maybe_fail

logger = logging.getLogger(__name__)

#: snapshot FORMAT version: part of the store key (a bump makes every
#: old snapshot unreachable-cold, never misread) AND pinned in the
#: manifest + each chunk header (so a hand-edited store fails closed)
SNAPSHOT_VERSION = 1

#: chunk-file magic
CHUNK_MAGIC = b"SNP1"

#: chunk header: magic | u16 version | u64 payload_len | blake2b-32
_CHUNK_HEADER = struct.Struct(">4sHQ32s")

MANIFEST_NAME = "MANIFEST.json"


def _count(what: str, amount: float = 1.0) -> None:
    default_registry().counter(f"inputsvc.{what}").add(amount)


class SnapshotCorruption(TransientError):
    """A chunk file failed validation (bad magic/version/digest,
    truncation). TRANSIENT by design: the reader deletes the chunk and
    re-decodes the partition — recovery is always possible because the
    snapshot is a cache, never the only copy."""


def snapshot_key(fingerprint: str, decode_key: str) -> str:
    """The content address: corpus identity x decode configuration x
    format version → one hex store key."""
    h = hashlib.blake2b(digest_size=16)
    h.update(f"v{SNAPSHOT_VERSION}|{fingerprint}|{decode_key}"
             .encode("utf-8"))
    return h.hexdigest()


def _encode_chunk(payload: bytes) -> bytes:
    digest = hashlib.blake2b(payload, digest_size=32).digest()
    return _CHUNK_HEADER.pack(CHUNK_MAGIC, SNAPSHOT_VERSION,
                              len(payload), digest) + payload


def _read_chunk(path: str) -> bytes:
    """Read + validate one chunk file → the Arrow IPC payload bytes.
    Raises :class:`SnapshotCorruption` on ANY validation failure."""
    with open(path, "rb") as f:
        raw = f.read()
    if len(raw) < _CHUNK_HEADER.size:
        raise SnapshotCorruption(
            f"snapshot chunk {path!r} is truncated below its header")
    magic, version, payload_len, digest = _CHUNK_HEADER.unpack(
        raw[:_CHUNK_HEADER.size])
    if magic != CHUNK_MAGIC:
        raise SnapshotCorruption(
            f"snapshot chunk {path!r} has bad magic {magic!r}")
    if version != SNAPSHOT_VERSION:
        raise SnapshotCorruption(
            f"snapshot chunk {path!r} is format v{version}; this "
            f"process reads v{SNAPSHOT_VERSION}")
    payload = raw[_CHUNK_HEADER.size:]
    if len(payload) != payload_len:
        raise SnapshotCorruption(
            f"snapshot chunk {path!r} is truncated: header promises "
            f"{payload_len} payload bytes, file holds {len(payload)}")
    if hashlib.blake2b(payload, digest_size=32).digest() != digest:
        raise SnapshotCorruption(
            f"snapshot chunk {path!r} failed its digest check "
            "(corrupted on disk)")
    return payload


def _decode_payload(payload: bytes) -> pa.RecordBatch:
    reader = pa.ipc.open_stream(pa.py_buffer(payload))
    return reader.read_next_batch()


def _encode_batch(batch: pa.RecordBatch) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, batch.schema) as writer:
        writer.write_batch(batch)
    return sink.getvalue().to_pybytes()


#: in-process lock for manifest check-then-act (the cache_to_disk
#: precedent: concurrent callers sharing a store must not race the
#: validation into spurious wipes)
_manifest_lock = threading.Lock()


def _wipe_store(directory: str) -> None:
    """Delete a store directory's contents (invalid manifest) so the
    caller rebuilds cold — the CLEAN re-decode contract: stale data
    must be unreachable the moment identity stops matching."""
    for name in os.listdir(directory):
        path = os.path.join(directory, name)
        try:
            os.remove(path)
        except OSError as e:
            logger.warning("inputsvc snapshot: could not remove "
                           "stale %r: %s", path, e)


def _ensure_manifest(directory: str, manifest: dict) -> None:
    """Validate-or-create the store manifest (caller-locked pattern
    inside): a matching manifest is a warm store; a missing one is
    cold; an unreadable or MISMATCHED one (hand-edited version field,
    foreign fingerprint — identity says this is not our store) is
    wiped and rebuilt, counted + logged, never silently read."""
    manifest_path = os.path.join(directory, MANIFEST_NAME)
    with _manifest_lock:
        if os.path.exists(manifest_path):
            existing = None
            try:
                # sparkdl-lint: allow[H8] -- the hold is the point: validate-wipe-rewrite must be atomic vs sibling streams of this process, and a manifest is tens of bytes
                with open(manifest_path) as f:
                    existing = json.load(f)
            except (OSError, ValueError) as e:
                logger.warning("inputsvc snapshot: manifest %r is "
                               "unreadable (%s); invalidating the "
                               "store", manifest_path, e)
            if existing == manifest:
                return
            if existing is not None:
                logger.warning(
                    "inputsvc snapshot: store %r manifest does not "
                    "match this corpus/decode-config/version; "
                    "invalidating and re-decoding cold", directory)
            _count("snapshot_invalidations")
            _wipe_store(directory)
        tmp = (f"{manifest_path}.tmp.{os.getpid()}"
               f".{threading.get_ident()}")
        # sparkdl-lint: allow[H8] -- same atomic validate-wipe-rewrite section: a second stream must not read the store between the wipe and this rewrite
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
        os.replace(tmp, manifest_path)


def snapshot_sources(sources: Sequence, plan: Sequence,
                     schema: pa.Schema, root: str, fingerprint: str,
                     decode_key: Optional[str] = None) -> List:
    """Build the snapshot-backed source list for
    :meth:`DataFrame.snapshot` (data/frame.py): each source's first
    load decodes through ``plan`` and writes a validated chunk; every
    later load — this process, the next epoch, another tenant sharing
    ``root`` — streams the chunk back with decode busy-seconds ≈ 0.
    ``decode_key`` defaults to the plan's stage-name signature; pass
    an explicit key when stage behavior changes under a stable name
    (the fingerprint discipline of ``cache_to_disk``)."""
    from sparkdl_tpu.data.frame import Source
    plan = list(plan)
    if decode_key is None:
        decode_key = ",".join(st.name for st in plan)
    key = snapshot_key(str(fingerprint), str(decode_key))
    directory = os.path.join(root, key)
    os.makedirs(directory, exist_ok=True)
    manifest = {"version": SNAPSHOT_VERSION, "key": key,
                "fingerprint": str(fingerprint),
                "decode_key": str(decode_key),
                "schema": schema.to_string(),
                "num_partitions": len(sources)}
    _ensure_manifest(directory, manifest)
    preserving = all(st.row_preserving for st in plan)

    def make(i: int, src) -> "Source":
        logical = (src.logical_index
                   if src.logical_index is not None else i)
        path = os.path.join(directory, f"chunk_{logical:05d}.snap")

        def _load(src=src, logical=logical, path=path
                  ) -> pa.RecordBatch:
            if os.path.exists(path):
                try:
                    # the corrupt/missing-chunk drill's seam
                    # (resilience/faults.py; docs/RESILIENCE.md)
                    maybe_fail("snapshot.read")
                    payload = _read_chunk(path)
                    _count("snapshot_hits")
                    _count("snapshot_bytes", len(payload))
                    return _decode_payload(payload)
                except (OSError, TransientError) as e:
                    # failed CLOSED: drop the bad chunk, re-decode
                    # cleanly below — never a stale read, never a
                    # crash (permanent injected faults propagate:
                    # the fail-fast drill must stay fail-fast)
                    _count("snapshot_corruptions")
                    logger.warning(
                        "inputsvc snapshot: chunk %r failed "
                        "validation (%s: %s); re-decoding the "
                        "partition", path, type(e).__name__, e)
                    try:
                        os.remove(path)
                    except OSError as rm_err:
                        logger.debug(
                            "inputsvc snapshot: removing bad chunk "
                            "failed: %s", rm_err)
            _count("snapshot_misses")
            from sparkdl_tpu.data.spark_binding import apply_plan
            batch = apply_plan(plan, src.load(), logical)
            # tmp unique per pid AND thread (the cache_to_disk
            # overlap reasoning), atomic publish via rename
            os.makedirs(directory, exist_ok=True)
            tmp = (f"{path}.tmp.{os.getpid()}"
                   f".{threading.get_ident()}")
            with open(tmp, "wb") as f:
                f.write(_encode_chunk(_encode_batch(batch)))
            os.replace(tmp, path)
            _count("snapshot_writes")
            return batch

        # effectful: the first load WRITES the chunk — the engine
        # drains straggler loads on error/abandonment so none can
        # re-create a chunk after a cleanup rmtree (the cache_to_disk
        # Source contract)
        return Source(_load,
                      src.num_rows if preserving else None,
                      logical_index=src.logical_index,
                      effectful=True)

    return [make(i, s) for i, s in enumerate(sources)]
