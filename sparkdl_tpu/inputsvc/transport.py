"""Length-prefixed socket framing for the disaggregated input service.

The parallel host pipeline (``data/pipeline.py``) already ships
finished Arrow IPC fragments across a process boundary — but only a
POSIX one (shared memory / the pool result pipe). This module
generalizes that hand-off to a SOCKET: one message is a fixed binary
prefix followed by a small JSON header and an opaque payload, so a
:class:`~sparkdl_tpu.inputsvc.server.DecodeServer` on another process
(or another host) can carry the exact same cloudpickled task blobs and
result tuples the pool transport carries today.

Wire format (all integers big-endian)::

    MAGIC (4)  | WIRE_VERSION (u16) | header_len (u32) | payload_len (u64)
    header JSON (header_len bytes)  | payload (payload_len bytes)

The header is a plain JSON object (op, token, index, flags — never
bulk data); the payload carries the bulk bytes (cloudpickled plan and
source blobs on the request, the cloudpickled task result tuple on the
response). Sizes are bounded (:data:`MAX_HEADER_BYTES`,
:data:`MAX_PAYLOAD_BYTES`) so a corrupt or hostile peer cannot make
the receiver allocate unbounded memory from one length field.

Every framing failure — short read, bad magic, oversized length,
version mismatch — raises :class:`TransportError`, a TYPED transient
(``resilience/errors.py``): a dropped fragment RPC is exactly the
failure the client's retry-through-``RetryPolicy`` path and the
``inputsvc.rpc`` fault drill exist for, and the local-decode failover
catches what retry cannot (docs/DATA_SERVICE.md).
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Optional, Tuple

from sparkdl_tpu.resilience.errors import TransientError

#: frame magic — a reader that sees anything else is not talking to a
#: DecodeServer (or lost sync mid-stream) and must drop the connection
MAGIC = b"SDLT"

#: wire schema version: bumped on any frame/header change so an old
#: client and a new server fail the handshake TYPED instead of
#: misparsing each other's bytes
WIRE_VERSION = 1

#: the fixed prefix: magic + version + header_len + payload_len
_PREFIX = struct.Struct(">4sHIQ")

#: headers are small JSON control dicts; 1 MiB of header is corruption
MAX_HEADER_BYTES = 1 << 20

#: payload ceiling (1 GiB) — far above any sane decoded fragment, low
#: enough that a garbage length field cannot OOM the receiver
MAX_PAYLOAD_BYTES = 1 << 30


class TransportError(TransientError):
    """A framing/socket failure on the input-service wire (short read,
    bad magic, oversized frame, version mismatch). TRANSIENT: the
    client re-runs the partition through the shared RetryPolicy, and
    past the retry budget fails over to local decode — never a lost or
    duplicated row."""


def send_msg(sock: socket.socket, header: dict,
             payload: bytes = b"") -> None:
    """Send one framed message. ``header`` must be JSON-serializable;
    ``payload`` is opaque bytes (``b""`` for control messages)."""
    hdr = json.dumps(header, separators=(",", ":")).encode("utf-8")
    if len(hdr) > MAX_HEADER_BYTES:
        raise TransportError(
            f"header of {len(hdr)} bytes exceeds the "
            f"{MAX_HEADER_BYTES}-byte bound")
    if len(payload) > MAX_PAYLOAD_BYTES:
        raise TransportError(
            f"payload of {len(payload)} bytes exceeds the "
            f"{MAX_PAYLOAD_BYTES}-byte bound")
    try:
        sock.sendall(_PREFIX.pack(MAGIC, WIRE_VERSION, len(hdr),
                                  len(payload)))
        sock.sendall(hdr)
        if payload:
            sock.sendall(payload)
    except OSError as e:
        raise TransportError(
            f"input-service send failed: {e}") from e


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes or raise :class:`TransportError` — a
    peer that hangs up mid-frame must surface as a typed transient,
    never a silently short message."""
    chunks = []
    remaining = n
    while remaining:
        try:
            chunk = sock.recv(min(remaining, 1 << 20))
        except OSError as e:
            raise TransportError(
                f"input-service recv failed: {e}") from e
        if not chunk:
            raise TransportError(
                f"peer closed the connection {remaining} bytes short "
                f"of a {n}-byte read")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket) -> Tuple[dict, bytes]:
    """Receive one framed message → ``(header, payload)``. Raises
    :class:`TransportError` on any framing violation."""
    prefix = _recv_exact(sock, _PREFIX.size)
    magic, version, hdr_len, payload_len = _PREFIX.unpack(prefix)
    if magic != MAGIC:
        raise TransportError(
            f"bad frame magic {magic!r} (expected {MAGIC!r}) — the "
            "peer is not a DecodeServer or the stream lost sync")
    if version != WIRE_VERSION:
        raise TransportError(
            f"wire version mismatch: peer speaks v{version}, this "
            f"process speaks v{WIRE_VERSION}")
    if hdr_len > MAX_HEADER_BYTES:
        raise TransportError(
            f"header length {hdr_len} exceeds the "
            f"{MAX_HEADER_BYTES}-byte bound")
    if payload_len > MAX_PAYLOAD_BYTES:
        raise TransportError(
            f"payload length {payload_len} exceeds the "
            f"{MAX_PAYLOAD_BYTES}-byte bound")
    try:
        header = json.loads(_recv_exact(sock, hdr_len))
    except ValueError as e:
        raise TransportError(
            f"frame header is not valid JSON: {e}") from e
    if not isinstance(header, dict):
        raise TransportError(
            f"frame header must be a JSON object, got "
            f"{type(header).__name__}")
    payload = _recv_exact(sock, payload_len) if payload_len else b""
    return header, payload


def parse_endpoint(raw: str) -> Optional[Tuple[str, int]]:
    """``"host:port"`` → ``(host, port)``, or None when malformed (the
    caller owns the degrade accounting — config parsing must never
    raise out of an env read)."""
    raw = raw.strip()
    host, sep, port = raw.rpartition(":")
    if not sep or not host:
        return None
    try:
        port_i = int(port)
    except ValueError:
        return None
    if not 0 < port_i < 65536:
        return None
    return host, port_i
