"""CLI entrypoint: ``python -m sparkdl_tpu.inputsvc serve --port N``.

Runs one :class:`~sparkdl_tpu.inputsvc.server.DecodeServer` in the
foreground and prints ONE machine-parseable READY line naming the
bound host:port — the handle the two-process CI drill (tools/ci.sh)
and any process supervisor waits on. ``--port 0`` (the default) binds
an ephemeral port, so fleets can launch without port bookkeeping.

Fault drills and telemetry arm exactly as everywhere else:
``SPARKDL_TPU_FAULTS`` parses at import, and a client's decode
requests carry its telemetry config, so frames flow home over the
same socket without any flag here.
"""

from __future__ import annotations

import argparse
import logging
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sparkdl_tpu.inputsvc",
        description="sparkdl_tpu disaggregated input service "
                    "(docs/DATA_SERVICE.md)")
    sub = parser.add_subparsers(dest="command", required=True)
    serve = sub.add_parser(
        "serve", help="run one decode worker in the foreground")
    serve.add_argument("--host", default="127.0.0.1",
                       help="bind address (default 127.0.0.1)")
    serve.add_argument("--port", type=int, default=0,
                       help="bind port (default 0 = ephemeral; the "
                            "READY line names the bound port)")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    from sparkdl_tpu.inputsvc.server import DecodeServer
    server = DecodeServer(host=args.host, port=args.port)
    # ONE parseable line, flushed before serving: the launcher's
    # readiness handle (and with --port 0, its only way to learn
    # the bound port)
    print(f"SPARKDL_TPU_INPUTSVC READY {server.host}:{server.port}",
          flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
