"""Tensor columns over Arrow.

Numeric tensors ride in Arrow as FixedSizeList<float32/...> columns with
the row shape recorded in field metadata (key ``tensor_shape``). This is
the TPU build's replacement for the reference's Spark ``ml.linalg.Vector``
output columns and TensorFrames' row-block tensor conversion.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

TENSOR_SHAPE_KEY = b"tensor_shape"

_PA_BY_NP = {
    np.dtype(np.float32): pa.float32(),
    np.dtype(np.float64): pa.float64(),
    np.dtype(np.int32): pa.int32(),
    np.dtype(np.int64): pa.int64(),
    np.dtype(np.uint8): pa.uint8(),
    np.dtype(np.bool_): pa.bool_(),
}


def _shape_to_meta(shape: Sequence[int]) -> bytes:
    return ",".join(str(int(d)) for d in shape).encode()


def _meta_to_shape(meta: bytes) -> Tuple[int, ...]:
    if not meta:
        return ()
    return tuple(int(d) for d in meta.decode().split(","))


def tensor_field(name: str, shape: Sequence[int],
                 dtype=np.float32) -> pa.Field:
    """Arrow field for a tensor column of per-row ``shape``."""
    pa_type = _PA_BY_NP[np.dtype(dtype)]
    size = int(np.prod(shape)) if len(shape) else 1
    return pa.field(name, pa.list_(pa_type, size),
                    metadata={TENSOR_SHAPE_KEY: _shape_to_meta(shape)})


def tensor_to_arrow(array: np.ndarray) -> Tuple[pa.Array, bytes]:
    """[N, *shape] ndarray → (FixedSizeListArray, shape-metadata bytes)."""
    array = np.ascontiguousarray(array)
    n = array.shape[0]
    row_shape = array.shape[1:]
    size = int(np.prod(row_shape)) if row_shape else 1
    pa_type = _PA_BY_NP[array.dtype]
    flat = pa.array(array.reshape(-1), type=pa_type)
    fsl = pa.FixedSizeListArray.from_arrays(flat, size)
    return fsl, _shape_to_meta(row_shape)


def append_tensor_column(batch: pa.RecordBatch, name: str,
                         array: np.ndarray,
                         replace: bool = False) -> pa.RecordBatch:
    """Append ndarray [N, *shape] as a tensor column to a record batch.

    A name collision RAISES by default (Spark ML's "output column
    already exists" semantics — Arrow happily stores duplicate names,
    and every by-name lookup would then silently serve the ORIGINAL
    column, not this output). ``replace=True`` swaps the column
    in-place instead (pyspark ``withColumn`` semantics — used by
    ``DataFrame.with_column``)."""
    fsl, meta = tensor_to_arrow(array)
    field = pa.field(name, fsl.type, metadata={TENSOR_SHAPE_KEY: meta})
    # get_all_field_indices, NOT get_field_index: the latter returns -1
    # for DUPLICATED names too (post-join batches), which would read as
    # "absent" and silently append another duplicate
    idxs = batch.schema.get_all_field_indices(name)
    if idxs:
        if not replace:
            raise ValueError(
                f"output column {name!r} already exists; choose a "
                "different output column or drop/rename the existing "
                "one first")
        if len(idxs) > 1:
            raise ValueError(
                f"cannot replace column {name!r}: {len(idxs)} columns "
                "share that name (e.g. after a join); rename/drop "
                "first")
        return batch.set_column(idxs[0], field, fsl)
    return batch.append_column(field, fsl)


def append_unique_column(batch: pa.RecordBatch, field,
                         col) -> pa.RecordBatch:
    """``append_column`` with the same Spark-ML collision error as
    :func:`append_tensor_column` — for plain (non-tensor) output
    columns. (Joins deliberately bypass this: Spark joins DO produce
    duplicate names.)"""
    name = field.name if isinstance(field, pa.Field) else field
    if batch.schema.get_all_field_indices(name):
        raise ValueError(
            f"output column {name!r} already exists; choose a "
            "different output column or drop/rename the existing one "
            "first")
    return batch.append_column(field, col)


def tensor_shape_of(field: pa.Field) -> Optional[Tuple[int, ...]]:
    """Row shape recorded on the field, if any."""
    md = field.metadata or {}
    if TENSOR_SHAPE_KEY in md:
        return _meta_to_shape(md[TENSOR_SHAPE_KEY])
    if pa.types.is_fixed_size_list(field.type):
        return (field.type.list_size,)
    return None


def arrow_to_tensor(column, field: Optional[pa.Field] = None) -> np.ndarray:
    """Tensor / numeric column → ndarray [N, *shape].

    Accepts FixedSizeList (tensor), variable List (ragged rows must agree
    in length), or plain numeric columns (→ [N]).

    The FixedSizeList path is ZERO-COPY for single-chunk null-free
    columns: the returned ndarray is a (read-only) view over the Arrow
    values buffer — exactly what the batch runners' copy-minimal chunk
    path consumes, so an engine-aligned block flows from Arrow to the
    device transfer with no host-side staging copy at all. Multi-chunk
    columns pay one consolidating copy (combine_chunks).
    """
    if isinstance(column, pa.ChunkedArray):
        # single-chunk fast path: unwrap without the combine machinery
        # so the zero-copy view below is taken from the original buffer
        column = (column.chunk(0) if column.num_chunks == 1
                  else column.combine_chunks())
    typ = column.type
    if pa.types.is_fixed_size_list(typ):
        size = typ.list_size
        values = column.flatten()
        np_vals = values.to_numpy(zero_copy_only=False)
        out = np_vals.reshape(len(column), size)
        shape = tensor_shape_of(field) if field is not None else None
        if shape:
            out = out.reshape((len(column),) + tuple(shape))
        return out
    if pa.types.is_list(typ) or pa.types.is_large_list(typ):
        rows = column.to_pylist()
        return np.asarray(rows)
    return column.to_numpy(zero_copy_only=False)
