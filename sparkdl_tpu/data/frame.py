"""Partitioned, lazily-transformed Arrow DataFrame.

Plays the role Spark DataFrames played for the reference: rows live in
partitions (one ``pyarrow.RecordBatch`` each), transformations are
recorded as a per-partition plan of batch functions and only run when the
frame is materialized (``collect``/``stream``/``count``). Host stages run
in parallel across CPU threads; device stages (jitted TPU applies) are
serialized by the engine so the chip sees an orderly batch stream.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import threading
from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np
import pyarrow as pa


Row = dict  # a collected row is a plain dict, keyed by column name


class _DeferredSide:
    """One side of a different-plan :meth:`DataFrame.union`, materialized
    lazily exactly once per process.

    Materialization runs on a PRIVATE small thread pool: running on the
    engine's own pool from a pool worker deadlocks once outer partitions
    saturate it (``max_inflight >= num_workers``), while fully-inline
    materialization serializes an N-partition decode. Each partition
    runs through the engine's retrying ``_run_partition`` when it has
    one (LocalEngine: device stages still serialize on its device
    lock); duck-typed engines without it (SparkEngine) get the plain
    stage contract (``apply_plan``).

    Pickle-safe for Spark task shipping: the lock, the cached batches,
    and the engine are process-local and dropped on the wire — a remote
    task computes ONLY the side partition it asks for via
    ``apply_plan`` (per-task copies share nothing, so full
    materialization there would cost O(P²) partition decodes
    cluster-wide; Spark's own different-plan unions likewise recompute
    or shuffle)."""

    def __init__(self, engine, plan, sources):
        self._engine = engine
        self._plan = list(plan)
        self._sources = list(sources)
        self._lock = threading.Lock()
        self._batches: Optional[List[pa.RecordBatch]] = None

    def __getstate__(self):
        state = self.__dict__.copy()
        del state["_lock"]
        state["_batches"] = None
        state["_engine"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()

    def _run_partition(self, s: "Source", j: int) -> pa.RecordBatch:
        runner = getattr(self._engine, "_run_partition", None)
        if runner is not None:
            return runner(s, self._plan, j)
        from sparkdl_tpu.data.spark_binding import apply_plan
        idx = s.logical_index if s.logical_index is not None else j
        return apply_plan(self._plan, s.load(), idx)

    def get(self, i: int) -> pa.RecordBatch:
        if self._engine is None:
            # Post-pickle (remote task) path: there is no process-local
            # cache another partition could reuse — compute just this
            # partition instead of pool-mapping the whole side.
            # sparkdl-lint: allow[H17] -- _sources is immutable after __init__ (bound once, never rebound/mutated); the lock guards the _batches memoization, the source list just rides inside it
            return self._run_partition(self._sources[i], i)
        with self._lock:
            if self._batches is None:
                from concurrent.futures import ThreadPoolExecutor
                n_workers = min(4, max(1, len(self._sources)))
                with ThreadPoolExecutor(
                        max_workers=n_workers,
                        thread_name_prefix="sparkdl-union") as pool:
                    self._batches = list(pool.map(
                        self._run_partition, self._sources,
                        range(len(self._sources))))
            return self._batches[i]


class _CoalescedGroup:
    """One :meth:`DataFrame.coalesce` output partition: runs its input
    partitions through the baked plan SEQUENTIALLY — via the owning
    engine's retrying, device-locked ``_run_partition`` when it has one
    (so device stages never run concurrently from multiple coalesced
    loads) — and concatenates. Pickle-safe for Spark task shipping: the
    engine is process-local and drops on the wire; a remote task
    applies the plain stage contract."""

    def __init__(self, engine, plan, sources, base_index, schema):
        self._engine = engine
        self._plan = list(plan)
        self._sources = list(sources)
        self._base = base_index
        self._schema = schema

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_engine"] = None
        return state

    def _run_partition(self, s: "Source", j: int) -> pa.RecordBatch:
        runner = getattr(self._engine, "_run_partition", None)
        if runner is not None:
            return runner(s, self._plan, j)
        from sparkdl_tpu.data.spark_binding import apply_plan
        idx = s.logical_index if s.logical_index is not None else j
        return apply_plan(self._plan, s.load(), idx)

    def load(self) -> pa.RecordBatch:
        batches = []
        for off, src in enumerate(self._sources):
            b = self._run_partition(src, self._base + off)
            if b.num_rows:
                batches.append(b)
        if not batches:
            return pa.RecordBatch.from_pylist([], schema=self._schema)
        if len(batches) == 1:
            return batches[0]
        return pa.Table.from_batches(batches).combine_chunks() \
            .to_batches()[0]


def column_index(data, name: str) -> int:
    """Resolve a column name to its index in a RecordBatch/Table/Schema,
    raising KeyError for unknown names (pyarrow's get_field_index
    returns -1, which would silently negative-index the last column —
    and it returns -1 for DUPLICATED names too, so the ambiguous case
    gets its own message instead of reading as 'missing')."""
    schema = data if isinstance(data, pa.Schema) else data.schema
    idx = schema.get_field_index(name)
    if idx < 0:
        dups = schema.get_all_field_indices(name)
        if len(dups) > 1:
            raise KeyError(
                f"column {name!r} is ambiguous: {len(dups)} columns "
                "share that name (e.g. after a join); drop the "
                "unwanted one by position or avoid the collision "
                "upstream")
        raise KeyError(
            f"column {name!r} not in batch ({schema.names})")
    return idx


class LiveBatchHint:
    """A ``Stage.batch_hint`` that follows its runner's
    ``preferred_chunk`` LIVE instead of freezing the value at plan
    build. The engine reads hints through ``int(...)`` / ``bool(...)``
    (``LocalEngine._stream_rechunk`` re-reads between blocks), so a
    runner whose device batch the autotune controller moves along its
    pre-warmed shape ladder (``sparkdl_tpu/autotune``) pulls the
    engine's re-chunk cut along with it — blocks cut after the change
    align to the new batch, already-cut blocks stay row-exact (the
    runner pads/truncates any N). Duck-typed: anything with a
    ``preferred_chunk`` attribute works; pickles with its runner (the
    stage-closure shipping discipline)."""

    __slots__ = ("runner",)

    def __init__(self, runner):
        self.runner = runner

    def __int__(self) -> int:
        return int(self.runner.preferred_chunk)

    __index__ = __int__

    def __bool__(self) -> bool:
        return int(self.runner.preferred_chunk) > 0

    def __repr__(self) -> str:
        return f"LiveBatchHint({int(self)})"

    # pickle via __reduce__ keeps the __slots__ class cloudpickle-safe
    def __reduce__(self):
        return (LiveBatchHint, (self.runner,))


@dataclasses.dataclass(frozen=True)
class Stage:
    """One plan step: RecordBatch → RecordBatch. With ``with_index``,
    ``fn(batch, partition_index)`` — for per-partition determinism
    (sampling, sharded IO), the mapPartitionsWithIndex affordance.

    ``batch_hint`` (device stages): the stage's preferred input row
    count — its device batch (or global mesh batch). A row-preserving,
    index-free device stage with a hint may be RE-CHUNKED by the engine:
    fed row blocks cut at multiples of the hint from the ordered
    partition stream instead of per-partition blocks, so partitions
    smaller than the device batch stop padding up to the static shape
    (the 2.4× small-partition tax measured in BASELINE.md). The
    reference had no such constraint to absorb — TensorFrames blocks
    were whatever size the partition was (SURVEY §3.2); static-shape
    XLA makes batch alignment the engine's job, not the user's."""
    fn: Callable[..., pa.RecordBatch]
    kind: str = "host"            # "host" (thread-parallel) | "device" (serial)
    name: str = "stage"
    row_preserving: bool = True
    with_index: bool = False
    batch_hint: Optional[int] = None
    # True for stages with externally visible side effects (parquet
    # part writers): on error/abandonment the engine then DRAINS
    # in-flight siblings before returning control, so a straggler
    # can't e.g. re-create a staging dir after cleanup swept it. Pure
    # plans skip the drain — take(1)/first() must not block for a
    # full in-flight wave of decodes.
    effectful: bool = False


@dataclasses.dataclass(frozen=True)
class Source:
    """One partition source. ``load`` materializes the partition's batch;
    ``num_rows`` is a hint for count() fast-path (None = unknown).
    ``logical_index``, when set, is the partition's identity for
    ``with_index`` stages — so reordering/subsetting partitions
    (``with_partition_order``, host sharding, per-epoch shuffles) never
    changes what a deterministic stage like ``sample`` draws for a
    given partition. None = use the positional index.
    ``schema_hint``, when set, must EQUAL ``load()``'s schema — it lets
    ``DataFrame.schema`` probe the plan on an empty prototype without
    materializing the first partition (decoding a whole image partition
    to answer ``.columns`` is the trap; only leaf constructors whose
    schema is statically known set it).
    ``effectful`` marks a ``load`` with externally visible side effects
    (cache_to_disk spill sources write Arrow IPC files inside load):
    the engine then QUIESCES in-flight sibling loads before returning
    control on error/abandonment, so a straggler load can't e.g.
    re-create spill files after the owner's cleanup rmtree ran — the
    Source twin of ``Stage.effectful``."""
    load: Callable[[], pa.RecordBatch]
    num_rows: Optional[int] = None
    logical_index: Optional[int] = None
    schema_hint: Optional[pa.Schema] = None
    effectful: bool = False


def _empty_batch(schema: pa.Schema) -> pa.RecordBatch:
    """Zero-row batch carrying ``schema`` (field metadata included)."""
    return pa.RecordBatch.from_arrays(
        [pa.array([], f.type) for f in schema], schema=schema)


class DataFrame:
    """Immutable partitioned frame; transforms return new frames."""

    def __init__(self, sources: Sequence[Source], plan: Sequence[Stage] = (),
                 engine=None):
        from sparkdl_tpu.data.engine import default_engine
        self._sources: List[Source] = list(sources)
        self._plan: List[Stage] = list(plan)
        self._engine = engine or default_engine()
        self._schema: Optional[pa.Schema] = None

    # -- constructors -------------------------------------------------------

    @staticmethod
    def from_table(table: pa.Table, num_partitions: int = 8,
                   engine=None) -> "DataFrame":
        table = table.combine_chunks()
        n = table.num_rows
        num_partitions = max(1, min(num_partitions, n) if n else 1)
        bounds = np.linspace(0, n, num_partitions + 1).astype(int)
        sources = []
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            lo_i, hi_i = int(lo), int(hi)
            sub = table.slice(lo_i, hi_i - lo_i)

            def _load(sub=sub) -> pa.RecordBatch:
                batches = sub.combine_chunks().to_batches()
                if not batches:
                    return pa.RecordBatch.from_pylist([], schema=sub.schema)
                if len(batches) == 1:
                    return batches[0]
                return pa.Table.from_batches(batches).combine_chunks() \
                    .to_batches()[0]

            sources.append(Source(_load, hi_i - lo_i,
                                  schema_hint=table.schema))
        return DataFrame(sources, engine=engine)

    @staticmethod
    def from_pandas(df, num_partitions: int = 8, engine=None) -> "DataFrame":
        return DataFrame.from_table(pa.Table.from_pandas(df),
                                    num_partitions, engine)

    @staticmethod
    def from_pylist(rows: List[dict], num_partitions: int = 8,
                    engine=None) -> "DataFrame":
        return DataFrame.from_table(pa.Table.from_pylist(rows),
                                    num_partitions, engine)

    @staticmethod
    def from_batches(batches: Sequence[pa.RecordBatch],
                     engine=None) -> "DataFrame":
        sources = [Source((lambda b=b: b), b.num_rows,
                          schema_hint=b.schema) for b in batches]
        return DataFrame(sources, engine=engine)

    @staticmethod
    def read_parquet(path: str, engine=None,
                     allow_uncommitted: bool = False) -> "DataFrame":
        """Lazy frame over a parquet directory written by
        :meth:`write_parquet` (or any directory of part files): one
        partition per file, loaded on demand; row counts come from
        parquet footers so ``count()`` never reads data. Tensor-column
        shape metadata survives the round-trip (Arrow schema is stored
        in the parquet file).

        A directory holding part files plus a ``_tmp.*`` staging
        remnant is a DEFINITIVE interrupted :meth:`write_parquet`
        commit — refused by default (Spark's committer semantics:
        uncommitted output is not readable); ``allow_uncommitted=True``
        overrides. A marker-less directory with no staging remnant was
        written by another tool (pyarrow/pandas, or Spark with the
        marker suppressed — neither requires ``_SUCCESS`` on read):
        served with a warning."""
        import glob

        import pyarrow.parquet as pq

        if os.path.isdir(path):
            files = sorted(glob.glob(os.path.join(path, "*.parquet")))
            if files and not os.path.exists(
                    os.path.join(path, "_SUCCESS")):
                staging = glob.glob(os.path.join(path, "_tmp.*"))
                if staging and not allow_uncommitted:
                    raise FileNotFoundError(
                        f"{path!r} holds part files, no _SUCCESS "
                        f"marker, and a staging remnant "
                        f"({os.path.basename(staging[0])}): a "
                        "write_parquet was interrupted mid-commit and "
                        "the dataset may be PARTIAL. Pass "
                        "allow_uncommitted=True to read it anyway.")
                import logging
                logging.getLogger(__name__).warning(
                    "%r has no _SUCCESS marker%s: serving a dataset "
                    "this library did not commit. COMPLETENESS CANNOT "
                    "BE VERIFIED — foreign writers (pyarrow/pandas) "
                    "don't produce the marker, but a writer that died "
                    "without leaving its _tmp.* staging remnant looks "
                    "identical. If these rows feed training, confirm "
                    "the row count or rewrite via write_parquet (touch "
                    "_SUCCESS to silence this warning).", path,
                    " and a _tmp.* staging remnant" if staging else "")
        else:
            files = [path]
        if not files:
            raise FileNotFoundError(
                f"no .parquet files under {path!r}")

        schema = None

        def make(f: str) -> Source:
            nonlocal schema
            pf = pq.ParquetFile(f)
            if schema is None:
                schema = pf.schema_arrow
            num_rows = pf.metadata.num_rows

            def _load(f=f) -> pa.RecordBatch:
                table = pq.read_table(f).combine_chunks()
                if table.num_rows == 0:
                    return pa.RecordBatch.from_pylist(
                        [], schema=table.schema)
                return table.to_batches()[0]

            return Source(_load, num_rows)

        out = DataFrame([make(f) for f in files], engine=engine)
        # schema from the footer already parsed for num_rows — the
        # default zero-row probe would read and decode a whole part
        # file to answer .columns
        out._schema = schema
        return out

    def write_parquet(self, path: str,
                      row_group_rows: Optional[int] = None) -> str:
        """Materialize the plan and write one parquet part file per
        partition under ``path`` (Spark's ``df.write.parquet`` shape).
        ``row_group_rows`` caps rows per parquet row group (default:
        pyarrow's) — smaller groups let range readers
        (``repartition(cacheDir=)``) fetch only what they need.

        Part writing is a PLAN STAGE: each partition's task writes its
        own part into a staging subdirectory and returns only a tiny
        (file name, row count) summary — on :class:`SparkEngine` the
        parts are written ON THE EXECUTORS (Spark's committer model:
        ``path`` must be storage every executor reaches — NFS/GCS/
        fuse), and the driver never sees the data, only summaries. The
        driver then commits: renames staged parts into place in
        partition order and writes ``_SUCCESS``. A crash mid-stream
        leaves no part files; a kill mid-commit leaves parts without
        ``_SUCCESS``, which :meth:`read_parquet` refuses. Refuses a
        directory already holding part files. Returns ``path``."""
        import glob
        import shutil

        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        if glob.glob(os.path.join(path, "*.parquet")):
            raise FileExistsError(
                f"{path!r} already holds parquet part files; write to "
                "a fresh directory (overwrite is never implicit)")
        stale = glob.glob(os.path.join(path, "_tmp.*"))
        if stale:
            # staging leftovers: a concurrent writer, or a writer killed
            # mid-stream. Refusing (not sweeping) is the safe call — a
            # sweep would delete a LIVE concurrent writer's staged parts
            raise FileExistsError(
                f"{path!r} holds staging leftovers ({stale[0]}): "
                "another write_parquet is in progress, or a previous "
                "one was killed mid-stream — delete the _tmp.* "
                "directory if no writer is running")
        staging = os.path.join(path, f"_tmp.{os.getpid()}")
        # bare makedirs: a second same-process writer racing into the
        # same path must fail HERE (FileExistsError), not interleave
        # commits with this writer (tasks re-create it with exist_ok
        # because remote executors start without it)
        os.makedirs(staging)
        summary_schema = pa.schema([("part", pa.string()),
                                    ("rows", pa.int64())])

        def _write_part(batch: pa.RecordBatch, index: int
                        ) -> pa.RecordBatch:
            # runs INSIDE the task; tmp + os.replace makes retried /
            # duplicate task attempts idempotent (last writer wins on
            # an identical part name)
            if batch.num_rows == 0:
                # emptied partitions may carry imprecise computed-column
                # types (see collect()); they contribute no rows
                return pa.RecordBatch.from_pylist(
                    [], schema=summary_schema)
            os.makedirs(staging, exist_ok=True)
            import uuid
            # unique per attempt: repeated logical indices (partition
            # repeats) and task retries each stage their own file; only
            # names returned in summaries commit, orphans are swept
            # with the staging dir
            fname = f"part-{index:05d}-{uuid.uuid4().hex[:8]}.parquet"
            tmp = os.path.join(
                staging,
                f"{fname}.tmp.{os.getpid()}.{threading.get_ident()}")
            kw = ({"row_group_size": int(row_group_rows)}
                  if row_group_rows else {})
            pq.write_table(pa.Table.from_batches([batch]), tmp, **kw)
            os.replace(tmp, os.path.join(staging, fname))
            return pa.RecordBatch.from_pylist(
                [{"part": fname, "rows": batch.num_rows}],
                schema=summary_schema)

        committed = 0
        try:
            entries = []
            for b in self.map_batches(_write_part, name="write_parquet",
                                      row_preserving=False,
                                      with_index=True,
                                      effectful=True).stream():
                entries.extend(b.to_pylist())
            if not entries:
                # all-empty frame: one empty part so the dataset (and
                # its schema) still round-trips through read_parquet
                f = os.path.join(staging, "part-empty.parquet")
                pq.write_table(self.schema.empty_table(), f)
                entries = [{"part": "part-empty.parquet", "rows": 0}]
            # commit in stream (= partition) order: read_parquet sorts
            # part files lexicographically, so sequential names keep
            # row order stable even when logical indices are sparse
            for seq, e in enumerate(entries):
                os.replace(os.path.join(staging, e["part"]),
                           os.path.join(path, f"part-{seq:05d}.parquet"))
                committed += 1
            # commit marker (Spark's _SUCCESS): the rename loop itself
            # is not atomic, so a kill mid-commit leaves part files but
            # no marker — read_parquet refuses to read without it
            with open(os.path.join(path, "_SUCCESS"), "w"):
                pass
        except BaseException:
            # Once ANY part moved into `path`, the staging dir IS the
            # interrupted-commit evidence read_parquet keys on —
            # sweeping it would downgrade a PARTIAL dataset to
            # "foreign writer, warn-and-serve". Before the first
            # rename, `path` holds no parts, so sweeping is safe.
            if not committed:
                shutil.rmtree(staging, ignore_errors=True)
            raise
        shutil.rmtree(staging, ignore_errors=True)
        return path

    # -- plan building ------------------------------------------------------

    def map_batches(self, fn: Callable[..., pa.RecordBatch],
                    kind: str = "host", name: str = "map_batches",
                    row_preserving: bool = True,
                    with_index: bool = False,
                    batch_hint: Optional[int] = None,
                    effectful: bool = False) -> "DataFrame":
        return DataFrame(
            self._sources,
            self._plan + [Stage(fn, kind, name, row_preserving,
                                with_index, batch_hint, effectful)],
            self._engine)

    def with_column(self, name: str,
                    fn: Callable[[pa.RecordBatch], pa.Array],
                    kind: str = "host") -> "DataFrame":
        """Add — or REPLACE, pyspark ``withColumn`` semantics, position
        preserved — a column computed per batch. ``fn`` may return an
        Arrow array or a numpy array (auto-converted to a tensor
        column)."""
        from sparkdl_tpu.data.tensors import append_tensor_column

        if not callable(fn):
            raise TypeError(
                f"with_column({name!r}) needs a per-batch function "
                f"(batch -> column), got {type(fn).__name__}; a literal "
                "column can't be appended lazily — partitions stream, "
                "so compute it from each batch (e.g. from a key column)")

        def _stage(batch: pa.RecordBatch) -> pa.RecordBatch:
            col = fn(batch)
            if isinstance(col, np.ndarray):
                return append_tensor_column(batch, name, col,
                                            replace=True)
            if isinstance(col, pa.ChunkedArray):
                col = col.combine_chunks()
            # all-indices: get_field_index reads DUPLICATED names as -1
            idxs = batch.schema.get_all_field_indices(name)
            if len(idxs) > 1:
                raise ValueError(
                    f"cannot replace column {name!r}: {len(idxs)} "
                    "columns share that name (e.g. after a join); "
                    "rename/drop first")
            if idxs:
                return batch.set_column(idxs[0], name, col)
            return batch.append_column(name, col)

        return self.map_batches(_stage, kind=kind, name=f"with_column({name})")

    def select(self, *cols: str) -> "DataFrame":
        cols = list(cols)

        def _stage(batch: pa.RecordBatch) -> pa.RecordBatch:
            return batch.select(cols)

        return self.map_batches(_stage, name=f"select({','.join(cols)})")

    def drop(self, *cols: str) -> "DataFrame":
        to_drop = set(cols)

        def _stage(batch: pa.RecordBatch) -> pa.RecordBatch:
            keep = [n for n in batch.schema.names if n not in to_drop]
            return batch.select(keep)

        return self.map_batches(_stage, name=f"drop({','.join(cols)})")

    def rename(self, mapping: dict) -> "DataFrame":
        # Duplicate-creating renames fail LOUDLY (Spark tolerates the
        # duplicate and errors lazily on the first ambiguous
        # resolution; our by-name lookups would serve the FIRST column
        # silently). Only names whose count INCREASES are the mapping's
        # fault — a frame already carrying duplicates may still rename
        # its other columns. Validation runs eagerly when the schema is
        # free (cached, or a leaf schema_hint means the probe loads
        # nothing); otherwise per batch at execution — computing the
        # schema here would load a whole partition just to check names.
        import collections

        def _validate(names) -> None:
            before = collections.Counter(names)
            after = collections.Counter(mapping.get(n, n)
                                        for n in names)
            dup = sorted(n for n, c in after.items()
                         if c > 1 and c > before[n])
            if dup:
                raise ValueError(
                    f"rename would duplicate column name(s) {dup}; "
                    "drop the existing column first")

        if self.schema_probe_free:
            _validate(list(self.schema.names))
            validate_per_batch = None
        else:
            validate_per_batch = _validate

        def _stage(batch: pa.RecordBatch) -> pa.RecordBatch:
            if validate_per_batch is not None:
                validate_per_batch(batch.schema.names)
            return batch.rename_columns(
                [mapping.get(n, n) for n in batch.schema.names])

        return self.map_batches(_stage, name="rename")

    def filter(self, predicate: Callable[[pa.RecordBatch], "pa.Array | np.ndarray"]
               ) -> "DataFrame":
        def _stage(batch: pa.RecordBatch) -> pa.RecordBatch:
            mask = predicate(batch)
            if isinstance(mask, np.ndarray):
                mask = pa.array(mask)
            return batch.filter(mask)

        return self.map_batches(_stage, name="filter", row_preserving=False)

    def repartition(self, num_partitions: int,
                    cacheDir: Optional[str] = None) -> "DataFrame":
        """Change the partition count, preserving row order (Spark's
        shuffle repartition — SURVEY §1 L0).

        Without ``cacheDir``: materializes the whole frame on the
        driver, then re-slices — fine for frames that fit in RAM.

        With ``cacheDir``: OUT-OF-CORE (VERDICT r4 #6). The frame
        streams through :meth:`write_parquet` into a spill under
        ``cacheDir`` (parts written partition-at-a-time, bounded
        memory), then the result is ``num_partitions`` lazy sources
        each reading only its own contiguous row range from the spill
        (row counts come from parquet footers, so planning reads no
        data). Peak memory is one input partition while spilling and
        ~2 spill files per output partition while reading — never the
        whole frame. The spill persists for the returned frame's
        lifetime; it lives under a unique subdirectory of ``cacheDir``
        and can be reclaimed by deleting it once the frame is done."""
        if int(num_partitions) <= 0:
            raise ValueError(  # Spark raises too; clamping hides typos
                f"num_partitions must be positive, got {num_partitions}")
        if cacheDir is None:
            return DataFrame.from_table(self.collect(), num_partitions,
                                        self._engine)
        import uuid

        spill = os.path.join(cacheDir,
                             f"repartition_spill_{uuid.uuid4().hex[:12]}")
        # small row groups so range reads fetch only what they need —
        # whole-file loads would re-decode each multi-GB part once per
        # overlapping output partition (review r5 finding)
        self.write_parquet(spill, row_group_rows=4096)
        return DataFrame._from_parquet_ranges(spill, int(num_partitions),
                                              self._engine)

    @staticmethod
    def _from_parquet_ranges(path: str, num_partitions: int,
                             engine=None) -> "DataFrame":
        """``num_partitions`` lazy sources over a parquet directory,
        each reading ONLY the row groups its contiguous row range
        overlaps (counts from footers; no data read at plan time).
        Peak memory per load ≈ the range plus one boundary row group."""
        import glob as _glob

        import pyarrow.parquet as pq

        files = sorted(_glob.glob(os.path.join(path, "*.parquet")))
        if not files:
            raise FileNotFoundError(
                f"no parquet part files under {path!r}")
        groups = []  # (file, row_group_index, rows)
        for f in files:
            md = pq.ParquetFile(f).metadata
            for g in range(md.num_row_groups):
                groups.append((f, g, md.row_group(g).num_rows))
        offsets = np.concatenate(
            [[0], np.cumsum([g[2] for g in groups])]) if groups \
            else np.array([0])
        total = int(offsets[-1])
        n_out = max(1, min(int(num_partitions), total) if total else 1)
        bounds = np.linspace(0, total, n_out + 1).astype(int)

        def _make_load(lo: int, hi: int):
            def _load() -> pa.RecordBatch:
                frags = []
                pf = None
                open_name = None
                # overlapping row-group window straight from offsets
                i0 = max(0, int(np.searchsorted(offsets, lo,
                                                "right")) - 1)
                i1 = int(np.searchsorted(offsets, hi, "left"))
                for i in range(i0, min(i1, len(groups))):
                    f, g, _rows = groups[i]
                    s_lo, s_hi = int(offsets[i]), int(offsets[i + 1])
                    if s_hi <= lo or s_lo >= hi:
                        continue
                    if f != open_name:
                        pf = pq.ParquetFile(f)
                        open_name = f
                    tbl = pf.read_row_group(g)
                    a = max(lo, s_lo) - s_lo
                    z = min(hi, s_hi) - s_lo
                    frags.extend(tbl.slice(a, z - a).combine_chunks()
                                 .to_batches())
                frags = [b for b in frags if b.num_rows]
                if not frags:
                    return _empty_batch(pq.read_schema(files[0]))
                # _concat_batches raises loudly on >2GiB columns that
                # refuse to combine — returning a subset would silently
                # drop rows on exactly the larger-than-RAM path this
                # exists for
                from sparkdl_tpu.data.engine import _concat_batches
                return _concat_batches(frags)
            return _load

        sources = [Source(_make_load(int(lo), int(hi)), int(hi - lo))
                   for lo, hi in zip(bounds[:-1], bounds[1:])]
        out = DataFrame(sources, engine=engine)
        # footer-only read: the default probe would load a whole row
        # range (the read_parquet precedent)
        out._schema = pq.read_schema(files[0])
        return out

    def coalesce(self, num_partitions: int) -> "DataFrame":
        """Merge ADJACENT partitions down to ``num_partitions`` without
        a global materialization (Spark ``coalesce(shuffle=False)``):
        each output partition runs its group of input partitions
        through the full plan ONE AT A TIME — through the engine's
        retrying, device-locked partition runner, so device stages stay
        serialized — and concatenates. Memory per in-flight output
        partition is one group's rows (≈ total/num_partitions), and the
        engine bounds in-flight partitions as usual; coalescing to very
        FEW partitions therefore approaches full materialization — for
        a larger-than-RAM re-layout use :meth:`write_parquet` or
        :meth:`cache_to_disk` instead. Row order is preserved, and
        ``with_index`` plan stages keep each input partition's own
        logical identity, so deterministic stages like ``sample`` draw
        exactly what they draw un-coalesced."""
        if int(num_partitions) <= 0:
            raise ValueError(  # Spark raises too; clamping hides typos
                f"num_partitions must be positive, got {num_partitions}")
        n_out = min(int(num_partitions), len(self._sources))
        if n_out == len(self._sources):
            return self
        preserving = all(st.row_preserving for st in self._plan)
        bounds = np.linspace(0, len(self._sources), n_out + 1).astype(int)
        schema = self.schema  # capture the VALUE, not self (pickling)
        sources = []
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            group = _CoalescedGroup(self._engine, self._plan,
                                    self._sources[lo:hi], int(lo),
                                    schema)
            rows = (sum(s.num_rows for s in self._sources[lo:hi])
                    if preserving and all(s.num_rows is not None
                                          for s in self._sources[lo:hi])
                    else None)
            sources.append(Source(group.load, rows))
        out = DataFrame(sources, engine=self._engine)
        # pre-seeded: the coalesced frame's plan is empty and its load
        # IS the baked plan, so the default zero-row probe would decode
        # a whole GROUP just to answer .columns (cache_to_disk's trap)
        out._schema = schema
        return out

    def _materialize_prefix(self, n: int) -> "DataFrame":
        """First ``n`` FINAL rows as a 1-partition frame, streaming
        partitions only until the cutoff is met and slicing whole Arrow
        batches (no per-row Python — image/tensor columns stay
        columnar)."""
        batches: List[pa.RecordBatch] = []
        remaining = n
        if remaining > 0:
            for batch in self.stream():
                if batch.num_rows > remaining:
                    batch = batch.slice(0, remaining)
                batches.append(batch)
                remaining -= batch.num_rows
                if remaining <= 0:
                    break
        table = (pa.Table.from_batches(batches, schema=self.schema)
                 if batches else
                 pa.Table.from_pylist([], schema=self.schema))
        return DataFrame.from_table(table, 1, self._engine)

    def limit(self, n: int) -> "DataFrame":
        """First ``n`` rows (across partitions, in order), lazily:
        partitions past the cutoff are never loaded."""
        if n < 0:
            raise ValueError(f"limit must be >= 0, got {n}")
        if any(not st.row_preserving for st in self._plan):
            # a filter in the plan changes row counts — the cutoff must
            # apply to FINAL rows, so materialize just enough
            return self._materialize_prefix(n)
        out_sources: List[Source] = []
        remaining = n
        for s in self._sources:
            if remaining <= 0:
                break
            if s.num_rows is None:
                # Unknown partition size (union's deferred sides): a
                # lazy prefix cannot know whether this source satisfies
                # the cutoff — slicing it and stopping here silently
                # under-returns when it holds fewer than ``remaining``
                # rows. Materialize just enough instead.
                return self._materialize_prefix(n)
            if s.num_rows <= remaining:
                out_sources.append(s)
                remaining -= s.num_rows
            else:
                take = remaining

                def _load(s=s, take=take) -> pa.RecordBatch:
                    return s.load().slice(0, take)

                # keep the partition's logical identity for with_index
                # stages (the un-limited frame's draws must be a prefix)
                out_sources.append(dataclasses.replace(
                    s, load=_load, num_rows=take))
                remaining = 0
        if not out_sources:  # keep the schema even with zero rows
            return DataFrame.from_table(
                pa.Table.from_pylist([], schema=self.schema), 1,
                self._engine)
        return DataFrame(out_sources, self._plan, self._engine)

    def with_partition_order(self, indices: Sequence[int]) -> "DataFrame":
        """A frame over the given subset/permutation of this frame's
        partitions, same plan — the public seam for per-epoch partition
        shuffles (streaming training) and host sharding (each index
        selects one existing partition; repeats allowed)."""
        n = len(self._sources)
        indices = [int(i) for i in indices]  # one-shot iterables: read once
        bad = [i for i in indices if not (0 <= i < n)]
        if bad:
            raise IndexError(
                f"partition index {bad[0]} out of range [0, {n})")

        def keep_identity(i: int) -> Source:
            src = self._sources[i]
            if src.logical_index is not None:
                return src  # already pinned by an earlier reorder
            return dataclasses.replace(src, logical_index=i)

        return DataFrame([keep_identity(i) for i in indices],
                         self._plan, self._engine)

    def union(self, other: "DataFrame") -> "DataFrame":
        """Concatenate two frames' rows (self's first). Stays fully lazy
        when both share the same plan; otherwise each side materializes
        lazily (once, at first execution) through its own engine path so
        device-stage serialization is preserved."""
        if self.schema != other.schema:
            raise ValueError(
                f"union schema mismatch: {self.schema.names} vs "
                f"{other.schema.names}")
        if self._plan == other._plan:
            out = DataFrame(self._sources + other._sources, self._plan,
                            self._engine)
            out._schema = self._schema  # just computed by the check
            return out

        def deferred(df: "DataFrame") -> List[Source]:
            side = _DeferredSide(df._engine, df._plan, df._sources)
            preserving = all(st.row_preserving for st in df._plan)
            return [Source(functools.partial(side.get, i),
                           s.num_rows if preserving else None)
                    for i, s in enumerate(df._sources)]

        out = DataFrame(deferred(self) + deferred(other),
                        engine=self._engine)
        out._schema = self._schema  # deferred loads END in this plan
        return out

    def join(self, other: "DataFrame", on, how: str = "inner", *,
             broadcast_limit_rows: int = 2_000_000,
             broadcast_limit_bytes: int = 256 << 20) -> "DataFrame":
        """Broadcast hash join: ``other`` (the small side — e.g. a label
        table) materializes ONCE and ships into a per-batch probe;
        this frame streams. The Spark-shaped affordance behind every
        "attach labels to images" flow (reference README's
        transfer-learning example joined labels onto readImages output).

        ``on``: key column name or list of names present on both sides;
        ``how``: ``inner`` (drop unmatched left rows) or ``left`` (keep
        them, right columns null). Keys must be UNIQUE on the right
        side — duplicate right keys raise (this is a broadcast lookup,
        not a general shuffle join).

        The right side must fit the broadcast contract: at most
        ``broadcast_limit_rows`` rows / ``broadcast_limit_bytes``
        materialized bytes (Spark's autoBroadcastJoinThreshold shape,
        sized for driver RAM rather than shuffle traffic). Joining two
        big frames raises a named error instead of an OOM; raise the
        limits explicitly if the right side genuinely fits in memory."""
        keys = [on] if isinstance(on, str) else list(on)
        if not keys:
            raise ValueError("join needs at least one key column")
        if how not in ("inner", "left"):
            raise ValueError(f"how must be 'inner' or 'left', got {how!r}")
        # single streamed pass over the right side: both guards fire as
        # soon as a limit is crossed, BEFORE the full table is held (and
        # the build side's plan executes once, not count()+collect())
        r_batches, n_right, nbytes_right = [], 0, 0
        for rb in other.stream():
            if rb.num_rows == 0:
                # emptied partitions may carry imprecise computed-column
                # types (see collect()) — and contribute nothing
                continue
            n_right += rb.num_rows
            nbytes_right += rb.nbytes
            if n_right > broadcast_limit_rows:
                raise ValueError(
                    f"broadcast join: right side exceeds "
                    f"broadcast_limit_rows={broadcast_limit_rows:,} "
                    "(the right side materializes in full on every "
                    "process). Swap the sides, pre-aggregate, or pass a "
                    "higher broadcast_limit_rows if it truly fits in "
                    "memory.")
            if nbytes_right > broadcast_limit_bytes:
                raise ValueError(
                    f"broadcast join: right side exceeds "
                    f"broadcast_limit_bytes={broadcast_limit_bytes:,} "
                    f"({nbytes_right:,} bytes so far; the right side "
                    "materializes in full on every process). Swap the "
                    "sides, drop payload columns, or pass a higher "
                    "broadcast_limit_bytes if it truly fits.")
            r_batches.append(rb)
        right = (pa.Table.from_batches(r_batches) if r_batches
                 else other.schema.empty_table())
        for k in keys:
            column_index(right, k)   # raise early on a bad key
            column_index(self.schema, k)
        overlap = (set(self.schema.names) & set(right.schema.names)) \
            - set(keys)
        if overlap:
            raise ValueError(
                f"non-key columns {sorted(overlap)} exist on both "
                "sides; rename or drop one side first")

        import pyarrow.compute as pc

        def key_array(table_or_batch) -> pa.Array:
            """Key column(s) → one hashable array, all in C++ — the
            probe is a per-batch hot stage and must not drop to
            per-row Python. Multi-key: columns cast to string and
            joined with a separator (a composite hash key)."""
            arrs = []
            for k in keys:
                col = table_or_batch.column(
                    column_index(table_or_batch, k))
                if isinstance(col, pa.ChunkedArray):
                    col = col.combine_chunks()
                arrs.append(col)
            if len(arrs) == 1:
                return arrs[0]
            # escape the separator inside each field before joining, or
            # values containing \x1f would make distinct key tuples
            # collide (('x\x1fy','z') vs ('x','y\x1fz')) — wrong
            # matches / spurious duplicate-key errors
            parts = []
            for a in arrs:
                s = pc.cast(a, pa.string())
                s = pc.replace_substring(s, "\\", "\\\\")
                s = pc.replace_substring(s, "\x1f", "\\u")
                parts.append(s)
            return pc.binary_join_element_wise(*parts, "\x1f")

        right_keys = key_array(right)
        if right_keys.null_count:
            raise ValueError("right-side join keys contain nulls")
        if pc.count_distinct(right_keys).as_py() != len(right_keys):
            dup = [k for k, c in
                   zip(*np.unique(np.asarray(right_keys.to_pylist(),
                                             dtype=object),
                                  return_counts=True)) if c > 1][0]
            raise ValueError(
                f"duplicate join key {dup!r} on the right side; "
                "broadcast join needs unique right keys")
        payload = right.drop_columns(keys)

        def _stage(batch: pa.RecordBatch) -> pa.RecordBatch:
            idx = pc.index_in(key_array(batch), value_set=right_keys)
            if how == "inner":
                keep = idx.is_valid()
                batch = batch.filter(keep)
                take = idx.drop_null()
            else:
                take = idx  # null index → null payload row
            picked = payload.take(take)
            for col_i, field in enumerate(picked.schema):
                batch = batch.append_column(
                    field, picked.column(col_i).combine_chunks())
            return batch

        return self.map_batches(
            _stage, name=f"join({','.join(keys)})",
            row_preserving=(how == "left"))

    def sample(self, fraction: float, seed: int = 42) -> "DataFrame":
        """Bernoulli row sample (per-row coin flip, like Spark's).
        Deterministic per (seed, partition): re-materializations return
        the same rows, and concurrent partitions each use their own
        generator."""
        if not (0.0 <= fraction <= 1.0):
            raise ValueError(f"fraction must be in [0, 1], got {fraction}")

        def _stage(batch: pa.RecordBatch, index: int) -> pa.RecordBatch:
            rng = np.random.default_rng((seed, index))
            keep = rng.random(batch.num_rows) < fraction
            return batch.filter(pa.array(keep))

        return self.map_batches(_stage, name=f"sample({fraction})",
                                row_preserving=False, with_index=True)

    def show(self, n: int = 20, truncate: int = 40) -> None:
        """Print the first ``n`` rows as a simple table (Spark
        ``df.show`` affordance)."""
        rows = self.take(n)
        cols = self.columns
        def fmt(v):
            s = repr(v)
            return s if len(s) <= truncate else s[:truncate - 1] + "…"
        widths = {c: len(c) for c in cols}
        rendered = [{c: fmt(r.get(c)) for c in cols} for r in rows]
        for r in rendered:
            for c in cols:
                widths[c] = max(widths[c], len(r[c]))
        line = "+" + "+".join("-" * (widths[c] + 2) for c in cols) + "+"
        print(line)
        print("|" + "|".join(f" {c.ljust(widths[c])} " for c in cols)
              + "|")
        print(line)
        for r in rendered:
            print("|" + "|".join(f" {r[c].ljust(widths[c])} "
                                 for c in cols) + "|")
        print(line)

    def cache(self) -> "DataFrame":
        """Materialize the plan ONCE and return a frame over the
        in-memory result (Spark's ``df.cache()`` affordance, eager).
        Repeated materializations of the returned frame — CV folds,
        multi-trial fits, per-epoch passes — re-slice the table instead
        of re-running a decode-bearing plan."""
        return DataFrame.from_table(self.collect(),
                                    max(1, len(self._sources)),
                                    self._engine)

    _spill_manifest_lock = threading.Lock()

    def cache_to_disk(self, directory: str,
                      fingerprint: str = "") -> "DataFrame":
        """A frame whose partitions spill to Arrow IPC files on first
        load and re-read from disk afterwards — the multi-pass analogue
        of :meth:`cache` for data too big (or too numerous in epochs) to
        pin in memory. Each partition runs this frame's FULL plan once,
        writes the result atomically (tmp + rename), and every later
        materialization streams the file back; partition identity
        (``logical_index``) is preserved so per-epoch partition shuffles
        (``with_partition_order``) compose. Intended for host-stage
        plans (decode/resize); a device stage inside the spilled plan
        would run outside the engine's device lock on first load, and
        the spilled stages run inside ``Source.load`` so StageMetrics
        does not time them (the trade for running them at most once).
        Each executing machine spills to ITS OWN ``directory`` — on a
        distributed engine the cache is per-machine, not shared.

        A populated ``directory`` is only reused when its manifest
        matches this frame's SHAPE (schema + partition count) and the
        caller-supplied ``fingerprint``. Shape alone cannot distinguish
        two datasets with identical schema — callers reusing a cache
        directory across runs should pass a content fingerprint (e.g. a
        hash of source paths); mismatches raise rather than silently
        returning another dataset's rows."""
        import json

        os.makedirs(directory, exist_ok=True)
        plan = list(self._plan)
        preserving = all(st.row_preserving for st in plan)
        manifest_path = os.path.join(directory, "_manifest.json")
        manifest = {"schema": self.schema.to_string(),
                    "num_partitions": len(self._sources),
                    "fingerprint": str(fingerprint)}
        # in-process lock + atomic rename: concurrent callers sharing a
        # spill dir (fitMultiple trials) must not race the
        # check-then-act below into spurious "not empty" errors
        with DataFrame._spill_manifest_lock:
            if os.path.exists(manifest_path):
                with open(manifest_path) as f:
                    existing = json.load(f)
                # manifests written before the fingerprint field count
                # as the default fingerprint, not as a mismatch
                existing.setdefault("fingerprint", "")
                if existing != manifest:
                    raise ValueError(
                        f"cache directory {directory!r} holds a spill "
                        "of a DIFFERENT frame (schema, partition count "
                        "or fingerprint mismatch); use a fresh "
                        "directory")
            elif [n for n in os.listdir(directory)
                  if not n.startswith("_manifest.json.tmp")]:
                raise ValueError(
                    f"cache directory {directory!r} is not empty and "
                    "has no spill manifest; use a fresh directory")
            else:
                tmp = (f"{manifest_path}.tmp.{os.getpid()}"
                       f".{threading.get_ident()}")
                with open(tmp, "w") as f:
                    json.dump(manifest, f)
                os.replace(tmp, manifest_path)

        def make(i: int, src: Source) -> Source:
            logical = (src.logical_index
                       if src.logical_index is not None else i)
            path = os.path.join(directory, f"part_{logical:05d}.arrow")

            def _load(src=src, logical=logical, path=path
                      ) -> pa.RecordBatch:
                if os.path.exists(path):
                    with pa.memory_map(path) as source:
                        table = pa.ipc.open_file(source).read_all()
                    return table.combine_chunks().to_batches()[0] \
                        if table.num_rows else \
                        pa.RecordBatch.from_pylist([],
                                                   schema=table.schema)
                from sparkdl_tpu.data.spark_binding import apply_plan
                batch = apply_plan(plan, src.load(), logical)
                # tmp unique per pid AND thread: the engine's
                # early-stop cancel() doesn't stop already-running
                # loads, so a re-submitted partition can overlap one —
                # a shared tmp would interleave writers. The closure
                # may also run on a remote executor where the calling
                # process's makedirs never happened.
                os.makedirs(directory, exist_ok=True)
                tmp = (f"{path}.tmp.{os.getpid()}"
                       f".{threading.get_ident()}")
                with pa.OSFile(tmp, "wb") as sink:
                    with pa.ipc.new_file(sink, batch.schema) as w:
                        w.write_batch(batch)
                os.replace(tmp, path)
                return batch

            # effectful: the first load WRITES the spill file — the
            # engine must drain straggler loads on error so none can
            # re-create a file after the tuning cleanup's rmtree
            return Source(_load,
                          src.num_rows if preserving else None,
                          logical_index=src.logical_index,
                          effectful=True)

        out = DataFrame([make(i, s) for i, s in enumerate(self._sources)],
                        engine=self._engine)
        # schema from the UNDERLYING frame's zero-row probe: the cached
        # frame's plan is empty and its load IS the spilled plan, so
        # the default probe would decode+spill a whole partition just
        # to answer .columns / union schema checks
        out._schema = self.schema
        return out

    def snapshot(self, root: str, fingerprint: str = "",
                 decode_key: Optional[str] = None) -> "DataFrame":
        """A frame backed by the CONTENT-ADDRESSED snapshot store
        (``sparkdl_tpu/inputsvc/snapshot.py``; docs/DATA_SERVICE.md) —
        the multi-run, multi-tenant evolution of :meth:`cache_to_disk`.
        The store key hashes ``fingerprint`` (corpus identity — e.g. a
        hash of source paths) with ``decode_key`` (the decode
        configuration; defaults to the plan's stage-name signature)
        and the snapshot format version: a corpus change, a config
        change, or a format bump each lands in a fresh key directory
        and decodes cold, so a warm hit can NEVER be stale. Chunks are
        self-validating (per-chunk blake2b digests): corruption or
        truncation re-decodes that partition cleanly instead of
        crashing or serving bad rows. The second epoch — or the second
        tenant sharing ``root`` — streams with decode busy-seconds
        ≈ 0 (the ``inputsvc.snapshot_*`` counters tell the story)."""
        from sparkdl_tpu.inputsvc.snapshot import snapshot_sources
        out = DataFrame(
            snapshot_sources(self._sources, list(self._plan),
                             self.schema, root, fingerprint,
                             decode_key),
            engine=self._engine)
        # schema from the UNDERLYING frame (the cache_to_disk
        # reasoning): the snapshot frame's plan is empty and its load
        # IS the decode, so the default probe would decode+write a
        # whole partition just to answer .columns
        out._schema = self.schema
        return out

    def filter_rows(self, mask: np.ndarray) -> "DataFrame":
        """Keep rows where the GLOBAL boolean mask is true (mask indexed in
        collected row order). Used by CrossValidator k-fold splits."""
        table = self.collect()
        if len(mask) != table.num_rows:
            raise ValueError(f"mask length {len(mask)} != rows "
                             f"{table.num_rows}")
        kept = table.filter(pa.array(np.asarray(mask, dtype=bool)))
        return DataFrame.from_table(kept, max(1, len(self._sources)),
                                    self._engine)

    # -- introspection ------------------------------------------------------

    @property
    def num_partitions(self) -> int:
        return len(self._sources)

    @property
    def schema(self) -> pa.Schema:
        """Schema after the plan, computed once on a zero-row prototype
        (stages must tolerate empty batches) and cached — ``limit``/
        ``union``/``show`` all consult it, and a decode-bearing plan
        must not re-load partition 0 per access. When the first source
        publishes a ``schema_hint`` (statically-known leaf schemas:
        in-memory tables, file listings) the prototype is built from it
        WITHOUT loading the partition; otherwise the source loads once
        and is sliced to zero rows."""
        if self._schema is None:
            if not self._sources:
                return pa.schema([])
            src = self._sources[0]
            idx = src.logical_index if src.logical_index is not None else 0
            proto = (_empty_batch(src.schema_hint)
                     if src.schema_hint is not None
                     else src.load().slice(0, 0))
            for stage in self._plan:
                proto = (stage.fn(proto, idx) if stage.with_index
                         else stage.fn(proto))
            self._schema = proto.schema
        return self._schema

    @property
    def schema_probe_free(self) -> bool:
        """Whether reading :attr:`schema` costs no partition load:
        already cached, or the first source publishes a ``schema_hint``
        (the probe then runs the plan on an empty prototype only).
        Free-by-contract callers — ``rename`` validation, sizing
        estimates — consult this instead of silently decoding a
        partition at plan time."""
        return (self._schema is not None or not self._sources
                or self._sources[0].schema_hint is not None)

    @property
    def columns(self) -> List[str]:
        return list(self.schema.names)

    # -- materialization ----------------------------------------------------

    def stream(self) -> Iterator[pa.RecordBatch]:
        """Ordered iterator of fully-transformed partition batches."""
        return self._engine.execute(self._sources, self._plan)

    def collect(self, on_batch=None) -> pa.Table:
        """Materialize the frame as one Arrow table.

        ``on_batch``: optional observer called with each streamed batch
        as it arrives — the seam for byte/row watchdogs (e.g.
        ``LogisticRegression``'s mid-collect budget warning) so callers
        that need to watch the stream don't re-implement collect's
        empty-batch rules."""
        batches = []
        for b in self.stream():
            if on_batch is not None:
                on_batch(b)
            batches.append(b)
        if not batches:
            return pa.table({})
        non_empty = [b for b in batches if b.num_rows]
        if non_empty and len(non_empty) != len(batches):
            # A zero-row batch contributes no rows but MAY carry
            # imprecise column types: a computed column (e.g. a decoded
            # image tensor) cannot infer its row shape from an empty
            # input, so an emptied partition's schema can disagree with
            # the populated ones (plan-stage filters — CV folds,
            # sample — routinely empty whole partitions). Drop them
            # rather than fail the concat.
            batches = non_empty
        elif not non_empty:
            # ALL partitions emptied: the same imprecise-type hazard
            # means sibling empty batches can disagree with each other
            # — keep one as the schema carrier instead of failing a
            # meaningless 0-row concat
            batches = batches[:1]
        return pa.Table.from_batches(batches)

    def collect_rows(self) -> List[Row]:
        return self.collect().to_pylist()

    def to_pandas(self):
        return self.collect().to_pandas()

    def count(self) -> int:
        known = self.known_count()
        if known is not None:
            return known
        return sum(b.num_rows for b in self.stream())

    def known_count(self) -> Optional[int]:
        """Row count WITHOUT executing the plan, or None when it would
        require execution (a non-row-preserving stage, or sources
        without counts). Lets sizing decisions — e.g.
        ``LogisticRegression``'s memory-budget auto-switch — stay free
        instead of silently running an expensive upstream plan twice."""
        if all(st.row_preserving for st in self._plan) and \
                all(s.num_rows is not None for s in self._sources):
            return sum(s.num_rows for s in self._sources)
        return None

    def take(self, n: int) -> List[Row]:
        out: List[Row] = []
        for batch in self.stream():
            out.extend(batch.to_pylist())
            if len(out) >= n:
                break
        return out[:n]

    def first(self) -> Optional[Row]:
        rows = self.take(1)
        return rows[0] if rows else None

    def tensor(self, col: str) -> np.ndarray:
        """Collect one tensor column as a stacked ndarray [N, *shape]."""
        from sparkdl_tpu.data.tensors import arrow_to_tensor
        table = self.collect()
        idx = column_index(table, col)
        return arrow_to_tensor(table.column(idx), table.schema.field(idx))

    def __repr__(self) -> str:
        names = ",".join(self.columns) if self._sources else ""
        return (f"DataFrame[{names}] "
                f"({len(self._sources)} partitions, {len(self._plan)} stages)")
