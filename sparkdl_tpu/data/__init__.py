"""Columnar data substrate: partitioned Arrow DataFrames + local engine.

The reference delegated partitioning/scheduling to Apache Spark (its L0)
and block execution to TensorFrames (L1). This package is the TPU build's
engine seam: an Arrow-record-batch DataFrame with a lazy per-partition
transform plan, executed by a thread-pool :class:`LocalEngine` whose
host stages run in parallel on CPU threads and whose device stages feed
the TPU serially. A Spark binding (mapInArrow) can be dropped in behind
the same DataFrame API where pyspark exists.
"""

from sparkdl_tpu.data.frame import DataFrame, Row  # noqa: F401
from sparkdl_tpu.data.engine import LocalEngine, default_engine  # noqa: F401
from sparkdl_tpu.data.tensors import (  # noqa: F401
    arrow_to_tensor,
    tensor_field,
    tensor_shape_of,
    tensor_to_arrow,
)

__all__ = [
    "DataFrame",
    "Row",
    "LocalEngine",
    "default_engine",
    "tensor_to_arrow",
    "arrow_to_tensor",
    "tensor_field",
    "tensor_shape_of",
]
