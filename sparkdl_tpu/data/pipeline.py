"""Parallel host pipeline: overlapped multi-worker decode/ship with a
shared-memory Arrow hand-off and an ordered bounded re-merge.

BENCH r05's standing note says it plainly: on a 1-core host decode and
ship-side host work serialize on one Python stream
(``1/decode + 1/ship ~= 1/pipeline``) while the compute ceiling sits
~6x higher. ROADMAP item 3 names the fix — the tf.data shape
(PAPERS.md, arxiv 2101.12127): run the host-side transformation
(source load + decode stages) on N workers concurrently with bounded
read-ahead and ORDERED delivery, so decode overlaps ship/dispatch
instead of taking turns with it.

This module is that worker pool. :class:`LocalEngine` selects it per
``execute()`` when its ``pipeline_workers`` knob (ctor arg or
``SPARKDL_TPU_PIPELINE_WORKERS``, typo-degrades to serial) resolves to
>= 2:

* **process pool** (the default for CPU-heavy Python decode, which the
  GIL would otherwise serialize): each partition's source load + host
  stage prefix runs in a worker process; the finished Arrow fragment
  is handed back through a POSIX shared-memory segment carrying the
  Arrow IPC stream — the consumer copies the segment ONCE into
  process-owned bytes (a single bounded memcpy, counted in
  ``pipeline.handoff_bytes``) and maps the record batch zero-copy over
  them, so fragment rows flow into the engine's existing zero-copy
  re-chunk / ``PadStaging`` ship path without any further per-row
  work. Fragments under :data:`SHM_MIN_BYTES` skip the segment and
  ride the result pipe directly (the segment costs two syscalls; tiny
  metadata batches don't earn them).
* **thread pool fallback** where the process pool cannot apply — a
  plan or source that does not survive the cloudpickle round-trip (the
  sparkdl-lint H3 shipping discipline: locks/pools must drop on the
  wire), or a platform without a usable start method. Counted in
  ``pipeline.fallbacks``, never silent. Thread workers overlap only
  where stages release the GIL (the native libjpeg decode shim does;
  pure-PIL decode does not — exactly the case the process pool
  exists for).
* **ordered bounded re-merge**: workers complete in any order; results
  park in a reorder window bounded by the ``read_ahead`` knob
  (``SPARKDL_TPU_PIPELINE_READ_AHEAD``) and are yielded strictly in
  partition order — row identity and order are EXACT through the
  pooled path, including under mid-stream ``LiveBatchHint`` changes
  (the re-chunk cut downstream re-reads its hint between blocks
  exactly as in the serial path; pinned in tests/test_pipeline.py).

Degrades (each counted, none silent): requested workers < 2, a
config typo, or a 1-core host in auto mode run SERIAL — the existing
single-stream path, byte-for-byte. An explicit ``pipeline_mode``
("process"/"thread") trusts the caller and skips the core check (the
CI correctness drills run pooled on 1-core hosts on purpose).

Failure semantics match the engine's: a worker raising surfaces ONE
typed error to the consumer (process-mode exceptions are cloudpickled
back and re-raised; a worker that cannot even report yields
:class:`PipelineWorkerError`); transient failures re-run through the
engine's shared :class:`~sparkdl_tpu.resilience.policy.RetryPolicy`
(parent-side re-submit — the budget only bounds amplification if every
retry shares the bucket); on error or early abandonment in-flight
siblings are cancelled, EFFECTFUL plans/sources drain before control
returns (the engine's quiesce discipline), and any completed-but-
unconsumed shared-memory segment is released so an abandoned stream
cannot leak ``/dev/shm``.

Observability: every in-flight partition feeds the stall watchdog
(source ``pipeline.decode:<index>`` — a wedged worker fires a stall
NAMING the partition and recovers when it completes); merged fragments
land on the tracer's ``engine`` lane as ``pipeline.fragment`` spans;
the registry carries ``pipeline.*`` gauges/counters
(docs/OBSERVABILITY.md); and :func:`state` renders the live
worker/read-ahead/mode picture for ``/statusz``, flight bundles, and
bench's ``pipeline_overlap`` block. Worker-process host busy time is
reported back per task and folded into ``engine.busy_seconds`` by the
consumer, so the utilization ledger's decode lane keeps its ONE feed —
and gains a per-worker ceiling basis: with N pooled workers the lane's
ceiling is N busy-seconds per wall second (``decode_basis:
"busy/pooled-workers"``, obs/ledger.py).
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
import uuid
from collections import OrderedDict
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import pyarrow as pa

from sparkdl_tpu.obs import default_registry, span
from sparkdl_tpu.obs.watchdog import watchdog
from sparkdl_tpu.resilience.errors import TransientError

logger = logging.getLogger(__name__)

#: worker-count env knob; 0/unset/typo = serial (the existing path)
ENV_WORKERS = "SPARKDL_TPU_PIPELINE_WORKERS"
#: reorder-window env knob; default 2x workers (enough look-ahead to
#: keep every worker busy while the consumer drains in order)
ENV_READ_AHEAD = "SPARKDL_TPU_PIPELINE_READ_AHEAD"
#: pool-mode env knob: auto (process, thread fallback) | process | thread
ENV_MODE = "SPARKDL_TPU_PIPELINE_MODE"
#: multiprocessing start-method override (auto: spawn where the main
#: module supports re-import, else fork)
ENV_MPCTX = "SPARKDL_TPU_PIPELINE_MPCTX"

_MODES = ("auto", "process", "thread")

#: fragments smaller than this ride the result pipe instead of a
#: shared-memory segment (two syscalls + an mmap don't pay for tiny
#: metadata batches; decoded image fragments clear this easily)
SHM_MIN_BYTES = 64 * 1024


def _count(what: str, amount: float = 1.0) -> None:
    default_registry().counter(f"pipeline.{what}").add(amount)


def resolve_workers(explicit: Optional[int]) -> int:
    """The requested worker count: an explicit ctor value wins, then
    :data:`ENV_WORKERS`. A typo or negative value degrades to 0
    (serial) with one warning + ``pipeline.config_errors`` — the
    ledger/env-parsing precedent: a config typo must never make the
    engine unusable."""
    if explicit is not None:
        return max(0, int(explicit))
    raw = os.environ.get(ENV_WORKERS, "")
    if not raw:
        return 0
    try:
        val = int(raw)
        if val < 0:
            raise ValueError(val)
        return val
    except ValueError:
        logger.warning("%s=%r is not a non-negative int; running the "
                       "serial host path", ENV_WORKERS, raw)
        _count("config_errors")
        return 0


def resolve_read_ahead(explicit: Optional[int], workers: int) -> int:
    """The reorder-window depth (in-flight partitions ahead of the
    merge point): explicit wins, then :data:`ENV_READ_AHEAD`, then
    2x workers — the same typo-degrade contract as
    :func:`resolve_workers`."""
    default = max(2, 2 * max(1, workers))
    if explicit is not None:
        return max(1, int(explicit))
    raw = os.environ.get(ENV_READ_AHEAD, "")
    if not raw:
        return default
    try:
        val = int(raw)
        if val < 1:
            raise ValueError(val)
        return val
    except ValueError:
        logger.warning("%s=%r is not a positive int; using the default "
                       "%d", ENV_READ_AHEAD, raw, default)
        _count("config_errors")
        return default


def resolve_mode(explicit: Optional[str]) -> str:
    """Pool mode: explicit wins, then :data:`ENV_MODE`, then auto."""
    raw = explicit or os.environ.get(ENV_MODE, "") or "auto"
    raw = raw.lower()
    if raw not in _MODES:
        logger.warning("pipeline mode %r is not one of %s; using "
                       "'auto'", raw, _MODES)
        _count("config_errors")
        return "auto"
    return raw


_warned_once: set = set()
_warn_lock = threading.Lock()


def _warn_once(key: str, msg: str, *args) -> None:
    with _warn_lock:
        fire = key not in _warned_once
        _warned_once.add(key)
    if fire:
        # inside a telemetry-armed worker process the degrade event
        # ships to the parent (which dedupes ACROSS workers and logs
        # once); everywhere else this is one module-global None check
        from sparkdl_tpu.obs import remote
        if remote.capture_degrade(f"pipeline:{key}",
                                  msg % args if args else msg):
            return
        logger.warning(msg, *args)


def effective_workers(requested: int, mode: str,
                      record: bool = True) -> int:
    """The worker count a pooled stream actually runs: 0 (serial) when
    fewer than 2 are requested, and — in auto mode only — on a 1-core
    host, where overlapping decode with itself buys nothing and the
    pool's hand-off overhead would eat the 5%-of-serial degrade budget.
    An explicit process/thread mode trusts the caller (correctness
    drills run pooled on 1-core CI hosts on purpose). Degrades count
    ``pipeline.degrade_events`` — but only when a stream is actually
    being resolved: informational callers (bench labeling a result,
    the sweep labeling a grid row) pass ``record=False`` so the
    documented "every downgrade counted" contract stays a count of
    downgrades, not of questions."""
    req = max(0, int(requested))
    if req < 2:
        return 0
    if mode == "auto" and (os.cpu_count() or 1) < 2:
        if record:
            _warn_once("1core",
                       "pipeline: %d workers requested on a 1-core "
                       "host; running the serial host path (explicit "
                       "pipeline_mode forces the pool)", req)
            _count("degrade_events")
        return 0
    return req


def _spawn_safe() -> bool:
    """Whether the ``spawn`` start method can re-import ``__main__``
    here: real script files and ``python -m`` runs qualify; ``python -``
    heredocs and REPLs do not (spawn would die trying to re-run
    ``<stdin>``)."""
    main = sys.modules.get("__main__")
    if main is None:
        return False
    if getattr(main, "__spec__", None) is not None:
        return True
    path = getattr(main, "__file__", None)
    return bool(path) and os.path.exists(str(path))


def _mp_context():
    """The start method for worker processes: the env override when
    valid, else ``spawn`` where the main module survives re-import
    (fresh children — no inherited jax/OpenMP thread state), else
    ``fork`` (the only method that works under ``python -`` heredocs;
    children must stay off jax, which these workers do — they run
    Arrow/PIL/native decode only). None = no process pool here."""
    import multiprocessing as mp
    avail = mp.get_all_start_methods()
    raw = os.environ.get(ENV_MPCTX, "")
    if raw:
        if raw in avail:
            return mp.get_context(raw)
        logger.warning("%s=%r is not one of %s; auto-selecting",
                       ENV_MPCTX, raw, avail)
        _count("config_errors")
    if "spawn" in avail and _spawn_safe():
        return mp.get_context("spawn")
    if "fork" in avail:
        return mp.get_context("fork")
    return None


class PipelineWorkerError(RuntimeError):
    """A pooled worker failed in a way that could not be reported as
    its original typed exception (the exception itself did not survive
    the wire). Carries the worker-side repr so the failure still names
    itself."""


class PipelineHandoffError(TransientError):
    """The shared-memory hand-off of a finished fragment failed on the
    consumer side (segment missing/unreadable) — distinct from the
    worker failing, and TYPED transient (resilience/errors.py) so the
    parent-side retry actually fires: a re-run re-creates the
    segment."""


# ---------------------------------------------------------------------------
# worker side (runs in the pool process; must not touch jax)
# ---------------------------------------------------------------------------

#: per-worker-process plan cache, keyed by stream token — tasks carry
#: the cloudpickled plan redundantly (any task can land on any worker)
#: but each worker deserializes a stream's plan once. Bounded at a few
#: entries with oldest-out eviction so CONCURRENT streams sharing the
#: pool don't thrash each other's entry (a clear-on-miss single slot
#: would re-deserialize per task exactly when two streams interleave)
#: while a parade of finished streams still can't pin dead plans.
_PLAN_CACHE: "OrderedDict[str, list]" = OrderedDict()
_PLAN_CACHE_MAX = 4


def _encode_batch(batch: pa.RecordBatch) -> pa.Buffer:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, batch.schema) as writer:
        writer.write_batch(batch)
    return sink.getvalue()


def _decode_batch(data) -> pa.RecordBatch:
    """Arrow IPC stream bytes -> the fragment batch, zero-copy over
    ``data`` (the py_buffer keeps the owning bytes alive for as long
    as any downstream slice of the batch does)."""
    reader = pa.ipc.open_stream(pa.py_buffer(data))
    batch = reader.read_next_batch()
    return batch


def _with_frame(result: tuple, agent) -> tuple:
    """Append the telemetry frame to a task result tuple — ONLY when a
    worker agent is armed, so the disarmed hand-off carries zero extra
    bytes and keeps its exact pre-telemetry tuple shapes (the parent
    demuxes by the per-kind base length, ``_split_frame``)."""
    if agent is None:
        return result
    try:
        return result + (agent.cut_frame(),)
    except Exception:
        # telemetry must never fail the fragment it rides with
        logger.exception("pipeline worker: telemetry frame cut failed; "
                         "fragment ships without it")
        return result


def _pooled_partition_task(token: str, plan_blob: bytes,
                           src_blob: bytes, index: int,
                           shm_min: int,
                           tel: Optional[dict] = None) -> tuple:
    """One partition's source load + host-stage prefix, in a worker
    process. Returns a plain-picklable result tuple (never raises —
    exceptions ship back cloudpickled so their type survives):

    ``("shm", name, nbytes, busy_s, timings, rows)`` — fragment in a
    shared-memory segment the CONSUMER owns from here on (this side
    unregisters it from its resource tracker before returning);
    ``("buf", payload_bytes, busy_s, timings, rows)`` — small fragment
    riding the result pipe;
    ``("err", exc_blob_or_None, repr, type_name)`` — the failure,
    typed where cloudpickle can carry it.

    ``tel`` is the parent's telemetry config
    (:func:`sparkdl_tpu.obs.remote.telemetry_config`): when set, this
    process's :class:`~sparkdl_tpu.obs.remote.TelemetryAgent` arms
    (once — pool workers persist) and every result tuple gains ONE
    trailing frame element carrying the worker's spans, counter
    deltas, watchdog verdict, degrade events, and fault state back to
    the parent aggregator. ``None`` (disarmed) leaves the tuples
    byte-identical to the pre-telemetry shapes.
    """
    import cloudpickle
    agent = None
    try:
        if tel is not None:
            try:
                from sparkdl_tpu.obs import remote as _remote
                agent = _remote.worker_agent(tel)
            except Exception:
                # the fragment matters more than its telemetry
                logger.exception("pipeline worker: telemetry agent "
                                 "arming failed; task runs unobserved")
                agent = None
        plan = _PLAN_CACHE.get(token)
        if plan is None:
            plan = cloudpickle.loads(plan_blob)
            _PLAN_CACHE[token] = plan
            while len(_PLAN_CACHE) > _PLAN_CACHE_MAX:
                _PLAN_CACHE.popitem(last=False)
        else:
            _PLAN_CACHE.move_to_end(token)
        source = cloudpickle.loads(src_blob)
        logical = getattr(source, "logical_index", None)
        if logical is not None:
            index = logical
        # the engine's fault-injection sites apply to pooled partitions
        # too (env-armed config reaches the worker — and the telemetry
        # plane ships programmatic specs, so with an armed agent the
        # per-site counters recorded here reach the parent as
        # worker.<i>.faults.* instead of dying with the process)
        from sparkdl_tpu.resilience.faults import maybe_fail
        try:
            maybe_fail("pipeline.worker_death")
        except BaseException:
            # the ROADMAP-named worker-death drill: a REAL corpse (the
            # parent sees BrokenProcessPool, exactly like an OOM
            # kill), not a reportable error shipped back politely
            os._exit(1)
        from sparkdl_tpu.obs.watchdog import watchdog as _watchdog
        wd = _watchdog()
        busy = 0.0
        timings: List[Tuple[str, float, int]] = []
        with wd.watch("pipeline.worker_decode"), \
                span("worker.decode", lane="worker", partition=index):
            maybe_fail("pipeline.worker_decode")
            maybe_fail("engine.source_load")
            t0 = time.perf_counter()
            with span("worker.source_load", lane="worker",
                      partition=index):
                batch = source.load()
            busy += time.perf_counter() - t0
            for stage in plan:
                wd.pulse("pipeline.worker_decode")
                maybe_fail("engine.stage_apply")
                rows_in = batch.num_rows
                t0 = time.perf_counter()
                with span(f"worker.stage:{stage.name}", lane="worker",
                          partition=index, rows=rows_in):
                    batch = (stage.fn(batch, index) if stage.with_index
                             else stage.fn(batch))
                dt = time.perf_counter() - t0
                busy += dt
                timings.append((stage.name, dt, rows_in))
        payload = _encode_batch(batch)
        rows = batch.num_rows
        if agent is not None:
            # worker-side row accounting for report --workers / the
            # flight bundle's workers[] counter snapshot; parent-side
            # mirror lands as worker.<i>.pipeline.worker_rows
            _count("worker_rows", rows)
        if payload.size >= shm_min:
            try:
                from multiprocessing import shared_memory
                shm = shared_memory.SharedMemory(create=True,
                                                 size=payload.size)
            except Exception as e:
                # platforms without /dev/shm (or a full one) fall back
                # to the pipe — the fragment still arrives
                logger.warning("pipeline: shared-memory segment "
                               "unavailable (%s); fragment rides the "
                               "result pipe", e)
                shm = None
            if shm is not None:
                # cast to the flat byte view shm.buf exposes (the
                # Arrow buffer's own memoryview is not always 'B')
                shm.buf[:payload.size] = memoryview(payload).cast("B")
                name = shm.name
                try:
                    # ownership moves to the consumer: without this the
                    # worker's resource tracker unlinks the segment when
                    # the pool retires the process
                    from multiprocessing import resource_tracker
                    resource_tracker.unregister(shm._name,
                                                "shared_memory")
                except Exception as e:
                    # best-effort: double-unlink at exit is a warning,
                    # not a leak (the consumer unlinks first)
                    logger.debug("pipeline: resource-tracker "
                                 "unregister failed: %s", e)
                shm.close()
                return _with_frame(
                    ("shm", name, payload.size, busy, timings, rows),
                    agent)
        return _with_frame(
            ("buf", payload.to_pybytes(), busy, timings, rows), agent)
    except BaseException as exc:  # ships back typed; never raises
        blob = None
        try:
            exc.__traceback__ = None  # tracebacks don't pickle
            blob = cloudpickle.dumps(exc)
        except Exception:
            blob = None
        return _with_frame(
            ("err", blob, repr(exc), type(exc).__name__), agent)


# ---------------------------------------------------------------------------
# consumer side
# ---------------------------------------------------------------------------

#: base tuple length per result kind — the frame demux key: a result
#: longer than its base length carries EXACTLY one trailing telemetry
#: frame (armed streams only; disarmed tuples are the base shapes)
_RESULT_BASE_LEN = {"shm": 6, "buf": 5, "err": 4}


def _split_frame(result: tuple) -> Tuple[tuple, Optional[dict]]:
    """``(base_result, frame_or_None)`` — the parent half of the
    transport seam (:mod:`sparkdl_tpu.obs.remote`)."""
    if not isinstance(result, tuple) or not result:
        return result, None
    base = _RESULT_BASE_LEN.get(result[0])
    if base is None or len(result) <= base:
        return result, None
    return result[:base], result[base]


def _ingest_frame(frame: Optional[dict]) -> None:
    if frame is None:
        return
    try:
        from sparkdl_tpu.obs import remote
        remote.aggregator().ingest(frame)
    except Exception:
        # ingest() guards itself (worker.ingest_errors); this catches
        # an unimportable aggregator, which must not fail the fragment
        default_registry().counter("worker.ingest_errors").add()
        logger.exception("pipeline: telemetry frame ingest failed")


def _release_result(result: tuple) -> None:
    """Free a completed-but-unconsumed task result (early-stop or
    error abandonment): the shared-memory segment must be unlinked or
    an abandoned stream leaks ``/dev/shm``."""
    result, frame = _split_frame(result)
    _ingest_frame(frame)  # an abandoned fragment's telemetry survives
    if not isinstance(result, tuple) or not result or result[0] != "shm":
        return
    try:
        from multiprocessing import shared_memory
        shm = shared_memory.SharedMemory(name=result[1])
        shm.close()
        shm.unlink()
        _count("fragments_discarded")
    except FileNotFoundError:
        # already released (a racing consumer unlinked first) — the
        # goal state, but say so for a postmortem reading debug logs
        logger.debug("pipeline: abandoned fragment %r already "
                     "released", result[1])
    except Exception as e:
        _count("handoff_errors")
        logger.warning("pipeline: releasing an abandoned fragment "
                       "failed: %s", e)


def _raise_worker_error(result: tuple) -> None:
    _kind, blob, rep, type_name = result
    if blob is not None:
        import cloudpickle
        try:
            exc = cloudpickle.loads(blob)
        except Exception:
            exc = None
        if isinstance(exc, BaseException):
            raise exc
    raise PipelineWorkerError(
        f"pooled worker failed with {type_name}: {rep}")


def _consume_result(result: tuple) -> Tuple[pa.RecordBatch, float,
                                            List[tuple]]:
    """A task result tuple -> (batch, busy_seconds, stage timings).
    Shared-memory fragments are copied ONCE into process-owned bytes
    and the segment is released immediately; the batch then aliases
    the owned bytes zero-copy for the rest of its life. An armed
    stream's trailing telemetry frame is split off and ingested FIRST
    — an "err" result's frame still reaches the aggregator (the
    injected-fault drill is attributed even though the fragment
    raises)."""
    result, frame = _split_frame(result)
    _ingest_frame(frame)
    kind = result[0]
    if kind == "err":
        _raise_worker_error(result)
    if kind == "shm":
        _, name, nbytes, busy, timings, _rows = result
        from multiprocessing import shared_memory
        try:
            shm = shared_memory.SharedMemory(name=name)
        except FileNotFoundError:
            _count("handoff_errors")
            raise PipelineHandoffError(
                f"shared-memory segment {name!r} vanished before the "
                "fragment was consumed") from None
        try:
            data = bytes(shm.buf[:nbytes])  # the ONE bounded memcpy
        finally:
            shm.close()
            try:
                shm.unlink()
            except FileNotFoundError:
                logger.debug("pipeline: segment %r already unlinked",
                             name)
        _count("shm_segments")
    else:
        _, data, busy, timings, _rows = result
    _count("handoff_bytes", len(data))
    return _decode_batch(data), busy, list(timings)


# the live pooled-worker gauge the utilization ledger divides the
# decode lane by (per-worker ceiling basis): max over active streams,
# 0 when nothing pooled is running. _workers_peak additionally holds
# the max since the last consume_workers_peak() call so a ledger
# window that straddles a stream's END still divides by the workers
# that actually earned its busy seconds (an instantaneous gauge read
# at tick time would see 0 and misread a 4-worker window's 4
# busy-seconds/wall as a saturated serial lane).
_active_streams: Dict[int, Tuple[int, float]] = {}  # sid -> (workers, t0)
_active_lock = threading.Lock()
_stream_seq = 0
_workers_peak = 0
_workers_alltime = 0


def _enter_stream(workers: int) -> int:
    global _stream_seq, _workers_peak, _workers_alltime
    with _active_lock:
        _stream_seq += 1
        sid = _stream_seq
        _active_streams[sid] = (workers, time.perf_counter())
        live = max(w for w, _ in _active_streams.values())
        _workers_peak = max(_workers_peak, live)
        _workers_alltime = max(_workers_alltime, live)
    default_registry().gauge("pipeline.workers").set(live)
    return sid


def consume_workers_peak() -> int:
    """Max pooled workers live since the previous call (the ledger's
    per-window read, obs/ledger.py): covers streams that started AND
    finished inside the window. Resets the peak to the current live
    count, so each window consumes exactly its own history."""
    global _workers_peak
    with _active_lock:
        live = max((w for w, _ in _active_streams.values()), default=0)
        peak = max(_workers_peak, live)
        _workers_peak = live
        return peak


def alltime_workers_peak() -> int:
    """Process-lifetime pooled-worker high-water mark — the ledger's
    CUMULATIVE-verdict decode ceiling (a process that ever ran pooled
    banked pooled busy-seconds in the cumulative totals; dividing
    them by the serial ceiling would fabricate a saturated decode
    verdict)."""
    with _active_lock:
        live = max((w for w, _ in _active_streams.values()), default=0)
        return max(_workers_alltime, live)


def _exit_stream(sid: int) -> None:
    with _active_lock:
        entry = _active_streams.pop(sid, None)
        live = max((w for w, _ in _active_streams.values()), default=0)
    default_registry().gauge("pipeline.workers").set(live)
    if entry is not None:
        # pooled-stream ACTIVE wall seconds: PipelineTarget's
        # throughput denominator (rows per active second — idle gaps
        # between executes must not deflate a trial's evaluation)
        _count("stream_seconds", time.perf_counter() - entry[1])


# the last-resolved configuration, for /statusz, flight bundles, and
# bench's pipeline_overlap block (one shape everywhere)
_last_state: Dict[str, Any] = {}
_state_lock = threading.Lock()


def _record_state(**kv) -> None:
    with _state_lock:
        _last_state.update(kv)


def state() -> Dict[str, Any]:
    """The scrape-able pipeline state (``/statusz``, flight bundles):
    the last stream's resolved mode/workers/read-ahead plus the live
    ``pipeline.*`` counters."""
    snap = default_registry().snapshot()
    with _state_lock:
        out = dict(_last_state)
    with _active_lock:
        out["streams_active"] = len(_active_streams)
    out["counters"] = {k: v for k, v in snap.items()
                       if k.startswith("pipeline.")}
    return out


def _retire_worker_telemetry(handle) -> None:
    """Before a CLEAN process-pool shutdown, tell the telemetry
    aggregator these worker pids are retiring — otherwise a LATER pool
    break probes the reaped pids and misattributes the clean exits as
    deaths. Thread pools (no ``_processes``) are a no-op."""
    if handle is None:
        return
    procs = getattr(handle.pool, "_processes", None)
    if not procs:
        return
    try:
        from sparkdl_tpu.obs import remote
        remote.aggregator().note_pool_retired(list(procs.keys()))
    # sparkdl-lint: allow[H12] -- best-effort lifecycle bookkeeping: the shutdown itself proceeds either way, and an unretired slot only risks a later over-count that note_pool_broken's ERROR log surfaces
    except Exception:
        logger.exception("pipeline: worker retirement bookkeeping "
                         "failed")


class _PoolHandle:
    """One pool GENERATION. Streams pin the handle for their whole
    life (``refs``), so a live resize — the autotuner moving
    ``pipeline_workers`` while a stream is mid-flight — builds a NEW
    generation for new streams instead of shutting down (and
    cancelling the queued tasks of) the one a concurrent stream is
    still draining. A retired generation shuts down when its last
    holder releases it."""

    __slots__ = ("pool", "workers", "refs", "retired")

    def __init__(self, pool, workers: int):
        self.pool = pool
        self.workers = workers
        self.refs = 0
        self.retired = False


class HostPipeline:
    """The engine-owned worker pool + ordered re-merge
    (module docstring). One instance per :class:`LocalEngine`, built
    lazily on the first pooled ``execute()``; the pool persists across
    executes and is re-sized when the ``pipeline_workers`` knob moves
    (the autotune apply point — knob writes land between streams, the
    engine re-reads per execute; in-flight streams keep their pinned
    :class:`_PoolHandle` generation)."""

    # sparkdl-lint H3 contract: pool (re)builds can race from
    # concurrent execute() calls — pool handles and the mode
    # bookkeeping hold self._lock
    _lock_guards = ("_proc_handle", "_thread_handle", "_proc_broken")

    def __init__(self, mode: Optional[str] = None,
                 shm_min_bytes: int = SHM_MIN_BYTES):
        self.mode = resolve_mode(mode)
        self.shm_min_bytes = int(shm_min_bytes)
        self._lock = threading.Lock()
        self._proc_handle: Optional[_PoolHandle] = None
        self._proc_broken = False
        self._thread_handle: Optional[_PoolHandle] = None

    # locks and pools never ship (H3): a pipeline reachable through a
    # pickled engine arrives config-only, pools rebuilt on first use
    def __getstate__(self):
        state = self.__dict__.copy()
        del state["_lock"]
        state["_proc_handle"] = None
        state["_proc_broken"] = False
        state["_thread_handle"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()

    # -- pools ---------------------------------------------------------------

    def _retire_locked(self, handle: Optional[_PoolHandle]
                       ) -> Optional[_PoolHandle]:
        """Mark ``handle`` retired (caller holds the lock); returns it
        when no stream still pins it — i.e. when the CALLER must shut
        it down (outside the lock)."""
        # deferred: the data layer must not pull the jax-importing
        # runtime package in at module load; retires are rare (pool
        # resize / close), so the import cost lands off the hot path
        from sparkdl_tpu.runtime.sanitize import assert_lock_owned
        assert_lock_owned(self._lock, "HostPipeline._retire_locked")
        if handle is None:
            return None
        handle.retired = True
        return handle if handle.refs <= 0 else None

    def _acquire_process(self, workers: int) -> Optional[_PoolHandle]:
        """Pin the process-pool generation at ``workers`` size for one
        stream (rebuilding when the knob moved); None when no usable
        start method exists or a previous pool broke (worker killed —
        the stream that saw it raised typed; later streams fall back
        to threads, counted by the caller)."""
        from concurrent.futures import ProcessPoolExecutor
        with self._lock:
            if self._proc_broken:
                return None
            h = self._proc_handle
            if h is not None and h.workers == workers:
                h.refs += 1
                return h
        ctx = _mp_context()
        if ctx is None:
            return None
        new = _PoolHandle(
            ProcessPoolExecutor(max_workers=workers, mp_context=ctx),
            workers)
        new.refs = 1
        shut = None
        with self._lock:
            h = self._proc_handle
            if self._proc_broken:
                shut, new = new, None      # broke while building
            elif h is not None and h.workers == workers:
                h.refs += 1                # lost a racing same-size build
                shut, new = new, h
            else:
                self._proc_handle = new
                shut = self._retire_locked(h)
        if shut is not None:
            _retire_worker_telemetry(shut)
            shut.pool.shutdown(wait=False, cancel_futures=True)
        return new

    def _acquire_thread(self, workers: int) -> _PoolHandle:
        """The thread-pool analogue of :meth:`_acquire_process`
        (always succeeds — threads need no start method)."""
        shut = None
        with self._lock:
            h = self._thread_handle
            if h is not None and h.workers == workers:
                h.refs += 1
                return h
            new = _PoolHandle(
                ThreadPoolExecutor(
                    max_workers=workers,
                    thread_name_prefix="sparkdl-pipeline"),
                workers)
            new.refs = 1
            self._thread_handle = new
            shut = self._retire_locked(h)
        if shut is not None:
            shut.pool.shutdown(wait=False, cancel_futures=True)
        return new

    def _release(self, handle: Optional[_PoolHandle]) -> None:
        """A stream is done with its pinned generation; a retired one
        shuts down when the last holder leaves (queued abandoned tasks
        cancel; running ones finish and their done-callbacks release
        any shm segments)."""
        if handle is None:
            return
        with self._lock:
            handle.refs -= 1
            shut = handle.retired and handle.refs <= 0
        if shut:
            _retire_worker_telemetry(handle)
            handle.pool.shutdown(wait=False, cancel_futures=True)

    def _mark_broken(self) -> None:
        with self._lock:
            already = self._proc_broken
            self._proc_broken = True
            shut = self._retire_locked(self._proc_handle)
            self._proc_handle = None
        if shut is not None:
            shut.pool.shutdown(wait=False, cancel_futures=True)
        if not already:
            # attribute the corpse: probe the telemetry plane's known
            # worker pids, mark the dead one, count
            # pipeline.worker_deaths, dump a flight bundle naming it
            try:
                from sparkdl_tpu.obs import remote
                remote.aggregator().note_pool_broken(
                    "process pool broke (a worker process died)")
            # sparkdl-lint: allow[H12] -- best-effort death attribution: the broken pool itself is already counted (pipeline.fallbacks) and raises typed (PipelineWorkerError) upstream
            except Exception:
                logger.exception("pipeline: worker-death attribution "
                                 "failed")

    def shutdown(self) -> None:
        with self._lock:
            handles = (self._proc_handle, self._thread_handle)
            self._proc_handle = None
            self._thread_handle = None
            for h in handles:
                if h is not None:
                    h.retired = True
        for h in handles:
            if h is not None:
                _retire_worker_telemetry(h)
                h.pool.shutdown(wait=False, cancel_futures=True)

    # -- mode resolution -----------------------------------------------------

    def _pickle_payload(self, sources: Sequence, plan: Sequence
                        ) -> Optional[Tuple[bytes, List[bytes]]]:
        """(plan blob, per-source blobs) when the H3 shipping
        discipline holds for this stream, else None — the thread
        fallback's trigger."""
        import cloudpickle
        try:
            plan_blob = cloudpickle.dumps(list(plan))
            src_blobs = [cloudpickle.dumps(s) for s in sources]
            return plan_blob, src_blobs
        except Exception as e:
            _warn_once(f"pickle:{type(e).__name__}",
                       "pipeline: plan/source does not survive the "
                       "cloudpickle round-trip (%s: %s); process pool "
                       "falls back to threads", type(e).__name__, e)
            _count("fallbacks")
            return None

    # -- the pooled stream ---------------------------------------------------

    def stream(self, sources: Sequence, plan: Sequence, engine,
               workers: int) -> Iterator[Tuple[int, pa.RecordBatch]]:
        """Yield ``(logical_index, fragment)`` in partition order with
        ``workers`` pool workers and the engine's live ``read_ahead``
        window. The generator owns its in-flight bookkeeping: early
        abandonment cancels, effectful plans/sources drain (quiesce),
        abandoned shared-memory fragments release."""
        plan = list(plan)
        mode = self.mode
        payload = None
        handle = None
        if mode in ("auto", "process"):
            payload = self._pickle_payload(sources, plan)
            if payload is not None:
                handle = self._acquire_process(workers)
            if handle is None:
                if payload is not None:
                    # pool unavailable (no start method / broken pool)
                    _warn_once("noproc",
                               "pipeline: no usable process pool on "
                               "this platform; falling back to the "
                               "thread pool")
                    _count("fallbacks")
                mode = "thread"
            else:
                mode = "process"
        read_ahead = max(1, int(getattr(engine, "pipeline_read_ahead",
                                        0) or 1))
        _record_state(mode=mode, workers=workers,
                      read_ahead=read_ahead,
                      shm_min_bytes=self.shm_min_bytes)
        default_registry().gauge("pipeline.read_ahead").set(read_ahead)
        if mode == "process":
            return self._stream_process(sources, plan, engine, workers,
                                        payload, handle)
        return self._stream_thread(sources, plan, engine, workers)

    def _stream_thread(self, sources, plan, engine, workers):
        """Thread-mode pooled stream: tasks run the engine's own
        retrying ``_run_partition`` (spans, busy-seconds feed, stage
        metrics all land exactly as in the serial path)."""
        handle = self._acquire_thread(workers)

        def submit(pos: int) -> Future:
            return handle.pool.submit(engine._run_partition,
                                      sources[pos], plan, pos)

        return self._merge(sources, plan, engine, workers, submit,
                           consume=None, resubmit=None, mode="thread",
                           handle=handle)

    def _stream_process(self, sources, plan, engine, workers, payload,
                        handle: _PoolHandle):
        plan_blob, src_blobs = payload
        token = uuid.uuid4().hex
        # resolved ONCE per stream: None (disarmed) costs nothing and
        # ships nothing; armed, every task carries the config so any
        # worker the task lands on arms its agent
        from sparkdl_tpu.obs import remote
        tel = remote.telemetry_config()

        def submit(pos: int) -> Future:
            from concurrent.futures.process import BrokenProcessPool
            try:
                return handle.pool.submit(_pooled_partition_task,
                                          token, plan_blob,
                                          src_blobs[pos], pos,
                                          self.shm_min_bytes, tel)
            except BrokenProcessPool as exc:
                self._mark_broken()
                _count("fallbacks")
                raise PipelineWorkerError(
                    "process pool broke mid-stream (a worker process "
                    "died); subsequent pooled streams fall back to "
                    "the thread pool") from exc

        def consume(pos: int, result: tuple) -> pa.RecordBatch:
            batch, busy, timings = _consume_result(result)
            # the worker's host busy time lands in the ONE decode-lane
            # feed (obs/ledger.py) — counted here because the worker's
            # own registry dies with its process
            default_registry().counter("engine.busy_seconds").add(busy)
            if engine.stage_metrics is not None:
                for name, seconds, rows in timings:
                    engine.stage_metrics.add(name, seconds, rows)
            return batch

        return self._merge(sources, plan, engine, workers, submit,
                           consume=consume, resubmit=submit,
                           mode="process", handle=handle)

    def _merge(self, sources, plan, engine, workers, submit, consume,
               resubmit, mode: str, handle: Optional[_PoolHandle]):
        """The ordered bounded re-merge (one generator, both modes).
        ``consume`` post-processes a raw future result into a batch
        (process mode: shm hand-off + accounting; thread mode: the
        result IS the batch). ``resubmit`` enables parent-side retry
        through the engine's shared RetryPolicy (process mode only —
        thread-mode tasks already retry inside ``_run_partition``).
        ``handle`` is the stream's pinned pool generation, released
        when the generator finishes/abandons."""
        drain = (any(getattr(st, "effectful", False) for st in plan)
                 or any(getattr(src, "effectful", False)
                        for src in sources))
        wd = watchdog()
        inflight = default_registry().gauge("pipeline.inflight")
        inflight_peak = default_registry().gauge(
            "pipeline.inflight_peak")

        def _logical(pos: int) -> int:
            logical = getattr(sources[pos], "logical_index", None)
            return pos if logical is None else logical

        def _wd_source(pos: int) -> str:
            return f"pipeline.decode:{_logical(pos)}"

        def _result(pos: int, fut: Future):
            try:
                raw = fut.result()
            except BaseException as exc:
                from concurrent.futures.process import BrokenProcessPool
                if isinstance(exc, BrokenProcessPool):
                    # a worker died (OOM/kill) and took the pool with
                    # it: this stream fails typed; later streams fall
                    # back to the thread pool (counted) instead of
                    # resubmitting into a corpse
                    self._mark_broken()
                    _count("fallbacks")
                    raise PipelineWorkerError(
                        "process pool broke mid-stream (a worker "
                        "process died); subsequent pooled streams "
                        "fall back to the thread pool") from exc
                raise
            if consume is None:
                return raw
            try:
                return consume(pos, raw)
            except BaseException as exc:
                if resubmit is None:
                    raise
                # parent-side re-runs through the SHARED RetryPolicy
                # (grant-by-grant, because attempt 1 — the pooled
                # task that just failed — already happened): the
                # budget only bounds sustained amplification if
                # pooled retries drain the same bucket as serial ones
                policy = engine.retry_policy
                on_retry = engine._log_retry(
                    f"pooled partition {_logical(pos)}")
                key = f"pipeline:{_logical(pos)}"
                policy.deposit()
                attempt = 1
                while True:
                    delay = policy.grant(attempt, exc, key=key)
                    if delay is None:
                        raise exc
                    on_retry(attempt, exc, delay)
                    time.sleep(delay)
                    try:
                        return consume(pos, resubmit(pos).result())
                    except BaseException as retry_exc:  # sparkdl-lint: allow[H13] -- bounded + paced by engine.retry_policy: each lap re-asks grant(), which enforces max attempts, the retry budget, and exponential backoff, and its None raises out of the loop
                        attempt += 1
                        exc = retry_exc

        def _gen():
            sid = _enter_stream(workers)
            pending: Dict[int, Future] = {}
            # one watchdog source per EXECUTING partition — begun
            # lazily once a future reports running (merely-queued
            # siblings behind a wedged worker must not fire stalls
            # mis-naming healthy partitions), ended at completion
            # (done callback) so a finished fragment parked in the
            # reorder buffer cannot read as a stall either. A worker
            # that stops making progress fires a stall NAMING its
            # partition; completion recovers it.
            watched: set = set()
            watch_lock = threading.Lock()

            def _watch(pos: int) -> None:
                with watch_lock:
                    if pos in watched:
                        return
                    watched.add(pos)
                wd.begin(_wd_source(pos))

            def _unwatch(pos: int) -> None:
                with watch_lock:
                    if pos not in watched:
                        return
                    watched.discard(pos)
                wd.end(_wd_source(pos))

            next_to_submit = 0
            next_to_yield = 0
            n = len(sources)
            try:
                while next_to_yield < n:
                    window = max(1, int(getattr(
                        engine, "pipeline_read_ahead", 0) or 1))
                    while (next_to_submit < n
                           and len(pending) < window):
                        pos = next_to_submit
                        fut = submit(pos)
                        pending[pos] = fut
                        fut.add_done_callback(
                            lambda _f, p=pos: _unwatch(p))
                        next_to_submit += 1
                        inflight.set(len(pending))
                        inflight_peak.set_max(len(pending))
                    for p, f in pending.items():
                        if f.running():
                            _watch(p)
                    pos = next_to_yield
                    fut = pending.pop(pos)
                    # we block on it next, so it counts as executing
                    # even if the running() snapshot above missed it
                    if not fut.done():
                        _watch(pos)
                    try:
                        with span("pipeline.fragment", lane="engine",
                                  partition=_logical(pos), mode=mode,
                                  workers=workers):
                            batch = _result(pos, fut)
                    finally:
                        _unwatch(pos)
                        inflight.set(len(pending))
                    _count("tasks")
                    _count("rows", batch.num_rows)
                    yield _logical(pos), batch
                    next_to_yield += 1
            finally:
                for pos, fut in pending.items():
                    if not fut.cancel():
                        # running (or already done): release any
                        # completed fragment's shm segment — an
                        # abandoned stream must not leak /dev/shm
                        if consume is not None:
                            fut.add_done_callback(self._on_abandoned)
                    _unwatch(pos)
                if drain:
                    # QUIESCE (the engine's discipline): an effectful
                    # straggler finishing AFTER the caller's cleanup
                    # ran corrupts the cleanup's outcome
                    for fut in pending.values():
                        if not fut.cancelled():
                            try:
                                fut.result()
                            except Exception as drain_err:
                                # the primary error is already
                                # propagating; record the secondary
                                logger.debug(
                                    "pipeline quiesce drain error: %s",
                                    drain_err)
                inflight.set(0)
                _exit_stream(sid)
                self._release(handle)

        return _gen()

    @staticmethod
    def _on_abandoned(fut: Future) -> None:
        try:
            result = fut.result()
        except BaseException as e:
            logger.debug("pipeline: abandoned task failed: %s", e)
            return
        _release_result(result)

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            ph, th = self._proc_handle, self._thread_handle
            return {"mode": self.mode,
                    "process_pool_workers":
                        ph.workers if ph is not None else 0,
                    "process_pool_broken": self._proc_broken,
                    "thread_pool_workers":
                        th.workers if th is not None else 0,
                    "shm_min_bytes": self.shm_min_bytes}
