"""Local partition-parallel execution engine.

The seam where Spark executors + TensorFrames sat in the reference
(SURVEY §1 L0/L1). Host stages (decode, resize, Arrow shuffling) run
concurrently in a thread pool — the analogue of Spark python workers —
while device stages (jitted TPU applies) are serialized behind a lock so
a single accelerator sees one batch stream and HBM isn't oversubscribed
by concurrent partitions. Results stream back in partition order.

Plans containing a re-chunkable device stage (row-preserving,
index-free, with a ``Stage.batch_hint``) execute in two phases: the
host prefix runs per-partition in the pool as always, then the ordered
partition stream flows through the remaining stages on the consumer
thread — the device stage is fed batch-hint-aligned row blocks that
SPAN partition boundaries (outputs re-sliced back to the original
partitions), so partitions smaller than the static device batch stop
padding it. TensorFrames never had this problem (its blocks were
whatever size the partition was); static-shape XLA makes batch
alignment the engine's job rather than the user's.

With ``pipeline_workers >= 2`` (ctor arg or
``SPARKDL_TPU_PIPELINE_WORKERS``; typos degrade to serial) the host
prefix instead runs on the parallel host pipeline
(``data/pipeline.py``): a process pool (thread fallback where the plan
is not pickle-safe) executes source load + decode per partition, hands
fragments back through shared-memory Arrow buffers, and an ordered
bounded re-merge feeds the same consumer-thread re-chunk/ship path —
decode then OVERLAPS ship/dispatch instead of serializing with it
(ROADMAP item 3, the tf.data shape; docs/PERFORMANCE.md "Parallel
host pipeline").

A Spark/mapInArrow binding can replace this class behind the same
``execute(sources, plan)`` contract when pyspark is available (there,
one partition per task — the hint is advisory; see spark_binding).
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Iterator, Optional, Sequence, Tuple

import pyarrow as pa

from sparkdl_tpu.obs import default_registry, span
from sparkdl_tpu.resilience.errors import (
    default_retryable_exceptions,
    is_deterministic_jax_error,
)
from sparkdl_tpu.resilience.faults import maybe_fail
from sparkdl_tpu.resilience.policy import RetryPolicy

# NOTE: the retryable taxonomy moved to resilience/errors.py (one
# shared Transient-vs-Permanent split for the engine AND the serve
# layer); `default_retryable_exceptions` / `is_deterministic_jax_error`
# stay importable from this module for existing callers.

logger = logging.getLogger(__name__)

#: the engine's retry pacing: short backoff (partition re-runs are
#: batch work racing nothing), generous budget (ratio 1.0 bounds
#: sustained amplification at 2x offered load — the serve layer's
#: latency-sensitive 0.2 would starve long scans with sparse
#: transients)
ENGINE_RETRY_BASE_BACKOFF_S = 0.02
ENGINE_RETRY_MAX_BACKOFF_S = 1.0
ENGINE_RETRY_BUDGET_RATIO = 1.0
ENGINE_RETRY_BUDGET_CAP = 16.0


def _concat_batches(frags: Sequence[pa.RecordBatch]) -> pa.RecordBatch:
    if len(frags) == 1:
        return frags[0]
    tbl = pa.Table.from_batches(frags).combine_chunks()
    batches = tbl.to_batches()
    if len(batches) == 1:
        return batches[0]
    # combine_chunks yields one chunk per column for any sane size; a
    # >2GB column can still split. Returning a subset would silently
    # drop rows and corrupt the re-chunk bookkeeping — fail loudly if
    # no true concat exists.
    if hasattr(pa, "concat_batches"):
        return pa.concat_batches(batches)
    raise RuntimeError(
        f"cannot concatenate {len(batches)} oversized Arrow chunks on "
        "this pyarrow build; reduce the device batch_hint or partition "
        "size")


def _take_rows(frags: list, n: int) -> pa.RecordBatch:
    """Remove and return the first ``n`` rows from a fragment list
    (zero-copy slices; a copy only when a block spans fragments)."""
    take = []
    taken = 0
    while taken < n:
        b = frags[0]
        need = n - taken
        if b.num_rows <= need:
            take.append(b)
            taken += b.num_rows
            frags.pop(0)
        else:
            take.append(b.slice(0, need))
            frags[0] = b.slice(need)
            taken = n
    return _concat_batches(take)


class LocalEngine:
    """Thread-pool engine with ordered streaming and bounded in-flight
    partitions (backpressure keeps memory flat on large frames).

    Transient failures are retried ``max_retries`` times before
    propagating — the counterpart of Spark's task retry, which gave the
    reference free retry of inference partitions (SURVEY §5 "failure
    detection"). Retry runs on the shared
    :class:`~sparkdl_tpu.resilience.policy.RetryPolicy` (bounded
    attempts, exponential backoff with deterministic jitter, a retry
    budget bounding sustained amplification; each granted retry counts
    ``engine.retries``). The retryable set defaults to
    :func:`default_retryable_exceptions` (IO + jax/PJRT transients +
    the typed ``TransientError`` family) and is configurable via
    ``retryable_exceptions``. Deterministic errors (bad column names,
    shape mismatches, jax statuses a re-run cannot fix) propagate
    immediately and unchanged.
    """

    def __init__(self, num_workers: Optional[int] = None,
                 max_inflight: Optional[int] = None,
                 max_retries: int = 2,
                 stage_metrics=None,
                 retryable_exceptions: Optional[Tuple[type, ...]] = None,
                 pipeline_workers: Optional[int] = None,
                 pipeline_read_ahead: Optional[int] = None,
                 pipeline_mode: Optional[str] = None,
                 inputsvc_endpoints=None):
        self.num_workers = num_workers or min(32, (os.cpu_count() or 4))
        # the parallel host pipeline (data/pipeline.py): >= 2 resolved
        # workers select the pooled streaming mode per execute() —
        # source load + the host-stage prefix run on N pool workers
        # with an ordered bounded re-merge, so decode overlaps
        # ship/dispatch instead of serializing with it. 0/1 (and env
        # typos) = the serial path below, unchanged. Both knobs are
        # plain int attributes re-read at each execute()/wave — the
        # autotune controller's PipelineTarget moves them live
        # (single attribute stores, the repo-wide apply discipline).
        from sparkdl_tpu.data.pipeline import (
            resolve_mode,
            resolve_read_ahead,
            resolve_workers,
        )
        self.pipeline_workers = resolve_workers(pipeline_workers)
        self.pipeline_read_ahead = resolve_read_ahead(
            pipeline_read_ahead, self.pipeline_workers)
        self.pipeline_mode = resolve_mode(pipeline_mode)
        # the disaggregated decode fleet (sparkdl_tpu/inputsvc;
        # docs/DATA_SERVICE.md): configured endpoints route the host
        # prefix to remote DecodeServers per execute(), with loud
        # local fallback when the fleet is unreachable.
        # ``inputsvc_workers`` is the LIVE fan-out width — a plain int
        # attribute re-read per execute, so the autotune controller's
        # PipelineTarget can move it like the pipeline knobs
        from sparkdl_tpu.inputsvc.client import resolve_endpoints
        self.inputsvc_endpoints = resolve_endpoints(inputsvc_endpoints)
        self.inputsvc_workers = len(self.inputsvc_endpoints)
        self._pipeline = None           # lazily-built HostPipeline
        self._pipeline_lock = threading.Lock()
        # Enough in-flight partitions to keep workers busy while the
        # consumer drains in order. A falsy sentinel (0/None) is NOT an
        # explicit window: treating 0 as explicit would disable the
        # adaptive widening while the `or` fallback discarded the 0
        # itself — the engine would honor a value the caller never got.
        self._explicit_inflight = (max_inflight is not None
                                   and max_inflight > 0)
        self.max_inflight = (max_inflight if self._explicit_inflight
                             else self.num_workers * 2)
        self.max_retries = max_retries
        # normalize to tuple: `except` rejects lists/sets at failure
        # time (masking the real error); an explicit () means "retry
        # nothing" and must not fall back to the defaults
        self.retryable_exceptions = (
            tuple(retryable_exceptions) if retryable_exceptions is not None
            else default_retryable_exceptions())
        # optional sparkdl_tpu.utils.StageMetrics for per-stage timing
        self.stage_metrics = stage_metrics
        # ONE policy per engine, shared by every pool worker and the
        # consumer-thread stream stages: the budget only bounds retry
        # amplification if the retrying threads share the bucket
        # (resilience/policy.py)
        self.retry_policy = RetryPolicy(
            attempts=1 + max(0, self.max_retries),
            base_backoff_s=ENGINE_RETRY_BASE_BACKOFF_S,
            max_backoff_s=ENGINE_RETRY_MAX_BACKOFF_S,
            budget_ratio=ENGINE_RETRY_BUDGET_RATIO,
            budget_cap=ENGINE_RETRY_BUDGET_CAP,
            retryable=self._retryable)
        self._pool = ThreadPoolExecutor(
            max_workers=self.num_workers,
            thread_name_prefix="sparkdl-tpu-host")
        self._device_lock = threading.Lock()

    def _retryable(self, exc: BaseException) -> bool:
        """The engine's retry classifier: inside the configured
        exception set AND not a deterministic jax status (re-running a
        program whose shapes are wrong just triples time-to-failure)."""
        return (isinstance(exc, self.retryable_exceptions)
                and not is_deterministic_jax_error(exc))

    # Locks and thread pools don't pickle; frames normally drop their
    # engine before shipping (frame.Source pickles engine=None), but an
    # engine reachable through any other closure must survive the wire
    # the same way — fresh pool, fresh lock, zero in-flight state on
    # arrival (the sparkdl-lint H3 contract).
    def __getstate__(self):
        state = self.__dict__.copy()
        del state["_pool"]
        del state["_device_lock"]
        # the host-pipeline pool follows the same H3 contract: pools
        # and their lock drop on the wire; the pipeline_workers /
        # read_ahead / mode CONFIG travels, so a shipped engine
        # rebuilds an equivalent pool on first pooled execute
        del state["_pipeline"]
        del state["_pipeline_lock"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._pool = ThreadPoolExecutor(
            max_workers=self.num_workers,
            thread_name_prefix="sparkdl-tpu-host")
        self._device_lock = threading.Lock()
        self._pipeline = None
        self._pipeline_lock = threading.Lock()

    def _run_stage(self, stage, batch, index, timings) -> pa.RecordBatch:
        # fault-injection site (resilience/faults.py; disarmed: one
        # armed-check): every stage apply, pooled and stream paths
        maybe_fail("engine.stage_apply")
        # every stage call lands on the tracer's "engine" lane
        # (obs/trace.py — a no-op when SPARKDL_TPU_TRACE is unset)
        with span(f"stage:{stage.name}", lane="engine",
                  rows=batch.num_rows, kind=stage.kind):
            t0 = time.perf_counter()
            out = (stage.fn(batch, index) if stage.with_index
                   else stage.fn(batch))
            dt = time.perf_counter() - t0
        if stage.kind != "device":
            # the utilization ledger's decode-lane feed (obs/ledger.py):
            # host-stage busy time — device-stage applies wrap
            # runner.run, which feeds device.run_seconds itself, so
            # counting them here would double-attribute the window
            default_registry().counter("engine.busy_seconds").add(dt)
        if timings is not None:
            timings.append((stage.name, dt, batch.num_rows))
        return out

    def _run_once(self, source, plan, index) -> pa.RecordBatch:
        # Buffer stage timings locally and flush only on success, so a
        # retried partition doesn't double-count its completed stages.
        timings = [] if self.stage_metrics is not None else None
        # fault-injection site: the partition's source read (the
        # worker-death drill for ROADMAP item 1's multi-host plan)
        maybe_fail("engine.source_load")
        with span("source.load", lane="engine", partition=index):
            t0 = time.perf_counter()
            batch = source.load()
            # source reads (decode/IO) are decode-lane busy time too
            default_registry().counter("engine.busy_seconds").add(
                time.perf_counter() - t0)
        for stage in plan:
            if stage.kind == "device":
                with self._device_lock:
                    batch = self._run_stage(stage, batch, index, timings)
            else:
                batch = self._run_stage(stage, batch, index, timings)
        if timings:
            for name, seconds, rows in timings:
                self.stage_metrics.add(name, seconds, rows)
        return batch

    def _run_partition(self, source, plan, index) -> pa.RecordBatch:
        # with_index stages see the partition's logical identity, not
        # its position in a reordered/subset frame (frame.Source)
        logical = getattr(source, "logical_index", None)
        if logical is not None:
            index = logical
        # the shared RetryPolicy owns attempts/backoff/budget
        # (resilience/policy.py): a transient partition failure
        # re-runs cleanly from its source; deterministic errors and
        # budget exhaustion propagate typed
        return self.retry_policy.call(
            lambda: self._run_once(source, plan, index),
            key=f"partition:{index}",
            on_retry=self._log_retry(f"partition {index}"))

    def _log_retry(self, what: str):
        def on_retry(attempt, exc, delay_s):
            default_registry().counter("engine.retries").add()
            logger.warning(
                "%s attempt %d/%d failed (%s); retrying in %.3fs",
                what, attempt, 1 + max(0, self.max_retries), exc,
                delay_s)
        return on_retry

    @staticmethod
    def _rechunkable(stage) -> bool:
        """Whether the engine may feed this stage row blocks cut at its
        ``batch_hint`` instead of per-partition blocks (see Stage
        docstring): device stages that preserve rows 1:1 and don't
        depend on partition identity."""
        return (stage.kind == "device" and stage.row_preserving
                and not stage.with_index
                and bool(getattr(stage, "batch_hint", None)))

    def execute(self, sources: Sequence, plan: Sequence) -> Iterator[pa.RecordBatch]:
        """Yield transformed partition batches in partition order, running
        at most ``max_inflight`` partitions concurrently.

        Plans whose tail contains a re-chunkable device stage split into
        two phases: the host prefix runs per-partition in the pool (as
        always), then the ordered partition stream flows through the
        remaining stages on the consumer thread, with re-chunkable
        device stages fed batch-hint-aligned row blocks that span
        partition boundaries — small partitions stop padding the static
        device shape — and their outputs re-sliced to the original
        partition boundaries (row identity and order unchanged)."""
        if not sources:
            return iter(())
        plan = list(plan)
        if self.inputsvc_endpoints and int(self.inputsvc_workers
                                           or 0) >= 1:
            # the disaggregated decode fleet (sparkdl_tpu/inputsvc):
            # the host prefix runs on remote DecodeServers; returns
            # None when no endpoint answers (counted + warned) and
            # the local paths below take over unchanged
            remoted = self._execute_remote(sources, plan)
            if remoted is not None:
                return remoted
        if int(self.pipeline_workers or 0) >= 2:
            # the parallel host pipeline (data/pipeline.py): the
            # source-load + host-stage prefix runs on N pool workers
            # with an ordered bounded re-merge; returns None when the
            # pool degrades to serial (1-core auto mode, config typo)
            # and the unchanged path below takes over
            pipelined = self._execute_pipelined(sources, plan)
            if pipelined is not None:
                return pipelined
        split = next((i for i, st in enumerate(plan)
                      if self._rechunkable(st)), None)
        if split is None:
            return (b for _, b in self._execute_indexed(sources, plan))
        # While the consumer blocks in a device call, the pool keeps
        # loading partitions ahead — the window must cover a device
        # chunk's worth of SMALL partitions or decode stalls behind the
        # device (measured on the 1-core tunnel host: 32-row partitions
        # at batch 128 ran 467 vs 552 img/s aligned with the default
        # 2-deep window; ≥8-deep reached 513–567 ≈ parity). The window
        # grows ADAPTIVELY: the first re-chunk stage measures actual
        # partition rows against its hint and widens the box up to 16 —
        # large (already-aligned) partitions never pay extra buffering;
        # an explicit ctor max_inflight is respected as given.
        inflight_box = [self.max_inflight]
        hints = [int(st.batch_hint) for st in plan[split:]
                 if self._rechunkable(st)]
        stream = self._execute_indexed(sources, plan[:split],
                                       inflight_box=inflight_box)
        first = True
        for stage in plan[split:]:
            if self._rechunkable(stage):
                widen = first and not self._explicit_inflight
                stream = self._stream_rechunk(
                    stream, stage,
                    inflight_box=inflight_box if widen else None,
                    max_hint=max(hints))
                first = False
            elif stage.kind == "device":
                stream = self._stream_plain(stream, stage)
            else:
                # host stages downstream of the device stage keep pool
                # parallelism (ordered futures) so device dispatch never
                # waits on host post-processing
                stream = self._stream_pooled(stream, stage)
        return (b for _, b in stream)

    def _host_pipeline(self):
        from sparkdl_tpu.data.pipeline import HostPipeline
        with self._pipeline_lock:
            if self._pipeline is None:
                self._pipeline = HostPipeline(mode=self.pipeline_mode)
            return self._pipeline

    def _execute_remote(self, sources: Sequence, plan: Sequence
                        ) -> Optional[Iterator[pa.RecordBatch]]:
        """The decode-fleet streaming mode (sparkdl_tpu/inputsvc): the
        plan's host prefix runs on remote DecodeServers with an
        ordered re-merge; the fragment stream then flows through the
        same consumer-thread stage machinery as the pooled/serial
        paths. ``inputsvc_workers`` bounds the fan-out width (the
        autotune knob); None — nothing picklable, or no endpoint
        reachable — falls through to the local paths, loudly
        (``inputsvc.fallbacks``)."""
        from sparkdl_tpu.inputsvc.client import RemotePipeline
        width = max(1, int(self.inputsvc_workers))
        dsplit = next((i for i, st in enumerate(plan)
                       if st.kind == "device"), len(plan))
        stream = RemotePipeline(
            self.inputsvc_endpoints[:width]).stream(
                sources, plan[:dsplit], self)
        if stream is None:
            return None
        hints = [int(st.batch_hint) for st in plan[dsplit:]
                 if self._rechunkable(st)]
        for stage in plan[dsplit:]:
            if self._rechunkable(stage):
                stream = self._stream_rechunk(stream, stage,
                                              max_hint=max(hints))
            elif stage.kind == "device":
                stream = self._stream_plain(stream, stage)
            else:
                stream = self._stream_pooled(stream, stage)
        return (b for _, b in stream)

    def _execute_pipelined(self, sources: Sequence, plan: Sequence
                           ) -> Optional[Iterator[pa.RecordBatch]]:
        """The pooled streaming mode (data/pipeline.py): the plan's
        host prefix — everything before the FIRST device stage — runs
        per-partition on the worker pool; the ordered fragment stream
        then flows through the same consumer-thread stage machinery as
        the serial path (re-chunkable device stages get hint-aligned
        blocks, downstream host stages keep thread-pool parallelism).
        Returns None when the pool resolves to serial — the caller
        falls through to the unchanged single-stream path."""
        from sparkdl_tpu.data import pipeline as host_pipeline
        workers = host_pipeline.effective_workers(
            int(self.pipeline_workers), self.pipeline_mode)
        if workers < 2:
            return None
        dsplit = next((i for i, st in enumerate(plan)
                       if st.kind == "device"), len(plan))
        stream = self._host_pipeline().stream(
            sources, plan[:dsplit], self, workers)
        hints = [int(st.batch_hint) for st in plan[dsplit:]
                 if self._rechunkable(st)]
        for stage in plan[dsplit:]:
            if self._rechunkable(stage):
                # no adaptive inflight widening here: the pipeline's
                # read_ahead knob IS the pooled look-ahead window (an
                # autotuner knob, not a heuristic)
                stream = self._stream_rechunk(stream, stage,
                                              max_hint=max(hints))
            elif stage.kind == "device":
                stream = self._stream_plain(stream, stage)
            else:
                stream = self._stream_pooled(stream, stage)
        return (b for _, b in stream)

    def _execute_indexed(self, sources: Sequence, plan: Sequence,
                         inflight_box: Optional[list] = None
                         ) -> Iterator[Tuple[int, pa.RecordBatch]]:
        """The pooled per-partition path, yielding
        ``(logical_index, batch)`` in partition order. ``inflight_box``
        is a one-element mutable window size a downstream re-chunk
        stage may widen once it has seen real partition sizes."""
        box = inflight_box or [self.max_inflight]
        # Drain in-flight siblings on exit only when the plan OR a
        # source has side effects: a straggler _write_part re-creating
        # write_parquet's just-swept staging dir AFTER cleanup ran
        # corrupts the cleanup's outcome — and cache_to_disk spill
        # sources write IPC files inside Source.load, so a straggler
        # LOAD can equally re-create spill files after the
        # tuning-cleanup rmtree (ADVICE r5). Pure plans over pure
        # sources cancel-only — take(1)/first() on a decode-heavy
        # frame must not block for a whole in-flight wave of partition
        # decodes (review r5).
        drain = (any(getattr(st, "effectful", False) for st in plan)
                 or any(getattr(src, "effectful", False)
                        for src in sources))

        def _logical(pos: int) -> int:
            logical = getattr(sources[pos], "logical_index", None)
            return pos if logical is None else logical

        def _gen():
            pending: dict[int, Future] = {}
            next_to_submit = 0
            next_to_yield = 0
            n = len(sources)
            try:
                while next_to_yield < n:
                    while (next_to_submit < n
                           and len(pending) < box[0]):
                        fut = self._pool.submit(
                            self._run_partition, sources[next_to_submit],
                            plan, next_to_submit)
                        pending[next_to_submit] = fut
                        next_to_submit += 1
                    fut = pending.pop(next_to_yield)
                    yield _logical(next_to_yield), fut.result()
                    next_to_yield += 1
            finally:
                for fut in pending.values():
                    fut.cancel()
                if drain:
                    # QUIESCE before returning control: a running task
                    # can't be cancelled and would otherwise keep
                    # producing side effects AFTER the caller's
                    # cleanup ran
                    for fut in pending.values():
                        if not fut.cancelled():
                            try:
                                fut.result()
                            except Exception as drain_err:
                                # the primary error is already
                                # propagating; record the secondary
                                # one instead of masking the drain
                                logger.debug(
                                    "quiesce drain error: %s",
                                    drain_err)

        return _gen()

    # -- stream phase (consumer thread) --------------------------------------

    def _apply_stream_stage(self, stage, batch, index) -> pa.RecordBatch:
        """Run one stage call on the consumer thread with the same
        retry/metrics semantics as the pooled path (the shared
        RetryPolicy). Retrying here is pure: the input block is
        already materialized (no source re-load), and stage fns are
        pure by the plan contract."""
        def once():
            timings = [] if self.stage_metrics is not None else None
            if stage.kind == "device":
                with self._device_lock:
                    out = self._run_stage(stage, batch, index, timings)
            else:
                out = self._run_stage(stage, batch, index, timings)
            if timings:
                for name, seconds, rows in timings:
                    self.stage_metrics.add(name, seconds, rows)
            return out

        return self.retry_policy.call(
            once, key=f"stream:{stage.name}",
            on_retry=self._log_retry(f"stream stage {stage.name}"))

    def _stream_plain(self, stream, stage):
        for idx, batch in stream:
            yield idx, self._apply_stream_stage(stage, batch, idx)

    def _stream_pooled(self, stream, stage):
        """Host stages downstream of a re-chunked device stage, run in
        the pool with a bounded ordered future window (tasks are
        independent units, so sharing the pool with the upstream prefix
        cannot deadlock)."""
        pending: collections.deque = collections.deque()
        try:
            for idx, batch in stream:
                pending.append((idx, self._pool.submit(
                    self._apply_stream_stage, stage, batch, idx)))
                # >=: the documented bound is AT MOST max_inflight
                # in-flight (submit-then-drain at > held one extra
                # partition's device output beyond the window)
                while len(pending) >= self.max_inflight:
                    i, fut = pending.popleft()
                    yield i, fut.result()
            while pending:
                i, fut = pending.popleft()
                yield i, fut.result()
        finally:
            # same QUIESCE discipline as _execute_indexed, gated the
            # same way: only an EFFECTFUL stage (a _write_part task
            # re-creating write_parquet's just-swept staging dir AFTER
            # the caller's cleanup ran) needs its in-flight siblings
            # drained before control returns; pure stages cancel-only
            # so take(n) stays interactive.
            for _, fut in pending:
                fut.cancel()
            if getattr(stage, "effectful", False):
                for _, fut in pending:
                    if not fut.cancelled():
                        try:
                            fut.result()
                        except Exception as drain_err:
                            # primary error already propagating;
                            # record, don't mask the drain outcome
                            logger.debug("quiesce drain error: %s",
                                         drain_err)

    def _stream_rechunk(self, stream, stage, inflight_box=None,
                        max_hint=None):
        """Feed ``stage`` row blocks cut at multiples of its batch_hint
        from the ordered partition stream; re-slice outputs back to the
        original partition boundaries. Greedy dispatch (all full hints
        available per arrival go in ONE stage call) preserves the
        runner's internal async chunk pipelining for large partitions.

        The hint is re-read BETWEEN blocks (``cur_hint``), not frozen
        at stream start: a ``LiveBatchHint`` whose runner the autotune
        controller moves along its pre-warmed shape ladder
        (``sparkdl_tpu/autotune``) re-aligns the cut mid-stream. Row
        identity and order are hint-independent — the ``segs``
        bookkeeping re-slices outputs to the original partition
        boundaries whatever sizes the blocks were cut at (pinned by
        ``tests/test_autotune.py::TestMidStreamHintChange``)."""

        def cur_hint() -> int:
            return max(1, int(stage.batch_hint))

        in_frags: list = []      # un-dispatched input fragments
        in_rows = 0
        out_frags: list = []     # stage outputs not yet re-sliced
        out_rows = 0
        segs: collections.deque = collections.deque()  # (idx, nrows, out)

        def run_rows(total: int):
            # Cut at fragment boundaries that land on hint multiples: a
            # whole fragment that is itself a hint multiple dispatches
            # AS-IS — its Arrow buffers reach the device stage as
            # zero-copy views (the runner stages nothing for aligned
            # contiguous blocks), where folding it into one greedy
            # concat with its neighbors would re-copy every row. Only
            # misaligned spans concatenate; they still dispatch
            # greedily so the runner's internal async chunk pipelining
            # is preserved.
            nonlocal in_rows, out_rows
            hint = cur_hint()
            while total:
                head = in_frags[0]
                if 0 < head.num_rows <= total \
                        and head.num_rows % hint == 0:
                    n = head.num_rows
                else:
                    n = total
                with span("rechunk.cut", lane="engine", rows=n):
                    chunk = _take_rows(in_frags, n)
                in_rows -= n
                total -= n
                out = self._apply_stream_stage(stage, chunk, -1)
                if out.num_rows != chunk.num_rows:
                    raise RuntimeError(
                        f"stage {stage.name!r} declared row_preserving "
                        f"but returned {out.num_rows} rows for "
                        f"{chunk.num_rows}")
                out_frags.append(out)
                out_rows += out.num_rows

        def ready():
            nonlocal out_rows
            while segs:
                idx, nrows, out = segs[0]
                if out is None:
                    if out_rows < nrows:
                        return
                    out = _take_rows(out_frags, nrows)
                    out_rows -= nrows
                segs.popleft()
                yield idx, out

        for idx, batch in stream:
            if inflight_box is not None and batch.num_rows:
                # first real partition: widen the prefix load-ahead
                # window so the pool can cover ~2 device chunks of
                # small partitions while the consumer blocks in a
                # device call (execute() docstring measurement); large
                # partitions leave the window as-is
                need = -(-2 * int(max_hint or cur_hint())
                         // batch.num_rows)
                # widen-only: never shrink an already-deeper default
                # (many-core hosts run num_workers*2 > 16)
                inflight_box[0] = max(inflight_box[0], min(16, need))
                inflight_box = None
            if batch.num_rows == 0:
                # empty partitions keep their schema by running the
                # stage directly (runners short-circuit N=0)
                segs.append((idx, 0,
                             self._apply_stream_stage(stage, batch, idx)))
            else:
                segs.append((idx, batch.num_rows, None))
                in_frags.append(batch)
                in_rows += batch.num_rows
                hint = cur_hint()
                if in_rows >= hint:
                    run_rows((in_rows // hint) * hint)
            yield from ready()
        if in_rows:
            run_rows(in_rows)  # final partial block; the stage pads it
        yield from ready()
        assert not segs, "re-chunk bookkeeping leaked partitions"

    def shutdown(self):
        self._pool.shutdown(wait=False, cancel_futures=True)
        with self._pipeline_lock:
            pipeline, self._pipeline = self._pipeline, None
        if pipeline is not None:
            pipeline.shutdown()


_default: Optional[LocalEngine] = None
_default_lock = threading.Lock()


def default_engine() -> LocalEngine:
    global _default
    with _default_lock:
        if _default is None:
            _default = LocalEngine()
        return _default


def set_default_engine(engine: LocalEngine):
    global _default
    with _default_lock:
        _default = engine
