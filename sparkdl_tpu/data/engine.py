"""Local partition-parallel execution engine.

The seam where Spark executors + TensorFrames sat in the reference
(SURVEY §1 L0/L1). Host stages (decode, resize, Arrow shuffling) run
concurrently in a thread pool — the analogue of Spark python workers —
while device stages (jitted TPU applies) are serialized behind a lock so
a single accelerator sees one batch stream and HBM isn't oversubscribed
by concurrent partitions. Results stream back in partition order.

A Spark/mapInArrow binding can replace this class behind the same
``execute(sources, plan)`` contract when pyspark is available.
"""

from __future__ import annotations

import logging
import os
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Iterator, Optional, Sequence, Tuple

import pyarrow as pa

logger = logging.getLogger(__name__)


def default_retryable_exceptions() -> Tuple[type, ...]:
    """Exception families a partition re-run can plausibly fix.

    ``OSError`` covers disk and Arrow IO. The jax runtime-error family
    covers transient device failures — a dropped PJRT tunnel connection
    mid-partition (realistic in this very environment), a preempted
    device — which re-run cleanly because sources re-load from disk and
    stages are pure. jax errors carrying a DETERMINISTIC status code
    (INVALID_ARGUMENT, a genuine RESOURCE_EXHAUSTED allocation failure,
    ...) are filtered out by :func:`is_deterministic_jax_error` even
    though the class is listed here. Python-level user errors (bad
    column names, trace-time shape mismatches) are never retried.
    """
    excs = [OSError]
    try:
        from jax.errors import JaxRuntimeError
        excs.append(JaxRuntimeError)
    except ImportError:  # pragma: no cover - jax is a hard dep in env
        pass
    return tuple(excs)


# Status codes that mean "this exact program will fail this exact way
# again" — re-running the partition cannot help, so time-to-failure must
# not triple and the retry warning must not suggest transience.
# (RESOURCE_EXHAUSTED: a program whose allocations exceed HBM fails
# deterministically; transient allocator races surface as INTERNAL or
# UNAVAILABLE in PJRT.)
_DETERMINISTIC_JAX_STATUSES = (
    "INVALID_ARGUMENT", "NOT_FOUND", "ALREADY_EXISTS", "PERMISSION_DENIED",
    "FAILED_PRECONDITION", "OUT_OF_RANGE", "UNIMPLEMENTED",
    "RESOURCE_EXHAUSTED", "UNAUTHENTICATED",
)


def is_deterministic_jax_error(exc: BaseException) -> bool:
    """True when a jax/PJRT runtime error carries a status code that a
    re-run cannot fix. XlaRuntimeError IS JaxRuntimeError; the absl
    status name is searched as a ``NAME:`` token in the message's first
    line rather than only at position 0 — wrapping layers commonly
    prefix context ("Execution failed: INVALID_ARGUMENT: ...")."""
    try:
        from jax.errors import JaxRuntimeError
    except ImportError:  # pragma: no cover
        return False
    if not isinstance(exc, JaxRuntimeError):
        return False
    msg = str(exc).lstrip()
    first_line = msg.splitlines()[0] if msg else ""
    return any(f"{s}:" in first_line
               for s in _DETERMINISTIC_JAX_STATUSES)


class LocalEngine:
    """Thread-pool engine with ordered streaming and bounded in-flight
    partitions (backpressure keeps memory flat on large frames).

    Transient failures are retried ``max_retries`` times before
    propagating — the counterpart of Spark's task retry, which gave the
    reference free retry of inference partitions (SURVEY §5 "failure
    detection"). The retryable set defaults to
    :func:`default_retryable_exceptions` (IO + jax/PJRT transients) and
    is configurable via ``retryable_exceptions``. Deterministic errors
    (bad column names, shape mismatches) propagate immediately and
    unchanged.
    """

    def __init__(self, num_workers: Optional[int] = None,
                 max_inflight: Optional[int] = None,
                 max_retries: int = 2,
                 stage_metrics=None,
                 retryable_exceptions: Optional[Tuple[type, ...]] = None):
        self.num_workers = num_workers or min(32, (os.cpu_count() or 4))
        # Enough in-flight partitions to keep workers busy while the
        # consumer drains in order.
        self.max_inflight = max_inflight or self.num_workers * 2
        self.max_retries = max_retries
        # normalize to tuple: `except` rejects lists/sets at failure
        # time (masking the real error); an explicit () means "retry
        # nothing" and must not fall back to the defaults
        self.retryable_exceptions = (
            tuple(retryable_exceptions) if retryable_exceptions is not None
            else default_retryable_exceptions())
        # optional sparkdl_tpu.utils.StageMetrics for per-stage timing
        self.stage_metrics = stage_metrics
        self._pool = ThreadPoolExecutor(
            max_workers=self.num_workers,
            thread_name_prefix="sparkdl-tpu-host")
        self._device_lock = threading.Lock()

    def _run_stage(self, stage, batch, index, timings) -> pa.RecordBatch:
        if timings is None:
            return (stage.fn(batch, index) if stage.with_index
                    else stage.fn(batch))
        import time
        t0 = time.perf_counter()
        out = (stage.fn(batch, index) if stage.with_index
               else stage.fn(batch))
        timings.append((stage.name, time.perf_counter() - t0,
                        batch.num_rows))
        return out

    def _run_once(self, source, plan, index) -> pa.RecordBatch:
        # Buffer stage timings locally and flush only on success, so a
        # retried partition doesn't double-count its completed stages.
        timings = [] if self.stage_metrics is not None else None
        batch = source.load()
        for stage in plan:
            if stage.kind == "device":
                with self._device_lock:
                    batch = self._run_stage(stage, batch, index, timings)
            else:
                batch = self._run_stage(stage, batch, index, timings)
        if timings:
            for name, seconds, rows in timings:
                self.stage_metrics.add(name, seconds, rows)
        return batch

    def _run_partition(self, source, plan, index) -> pa.RecordBatch:
        # with_index stages see the partition's logical identity, not
        # its position in a reordered/subset frame (frame.Source)
        logical = getattr(source, "logical_index", None)
        if logical is not None:
            index = logical
        attempts = 1 + max(0, self.max_retries)
        for attempt in range(attempts):
            try:
                return self._run_once(source, plan, index)
            except self.retryable_exceptions as e:
                if is_deterministic_jax_error(e):
                    raise
                if attempt + 1 >= attempts:
                    raise
                logger.warning(
                    "partition attempt %d/%d failed (%s); retrying",
                    attempt + 1, attempts, e)

    def execute(self, sources: Sequence, plan: Sequence) -> Iterator[pa.RecordBatch]:
        """Yield transformed partition batches in partition order, running
        at most ``max_inflight`` partitions concurrently."""
        if not sources:
            return iter(())

        def _gen() -> Iterator[pa.RecordBatch]:
            pending: dict[int, Future] = {}
            next_to_submit = 0
            next_to_yield = 0
            n = len(sources)
            try:
                while next_to_yield < n:
                    while (next_to_submit < n
                           and len(pending) < self.max_inflight):
                        fut = self._pool.submit(
                            self._run_partition, sources[next_to_submit],
                            plan, next_to_submit)
                        pending[next_to_submit] = fut
                        next_to_submit += 1
                    fut = pending.pop(next_to_yield)
                    yield fut.result()
                    next_to_yield += 1
            finally:
                for fut in pending.values():
                    fut.cancel()

        return _gen()

    def shutdown(self):
        self._pool.shutdown(wait=False, cancel_futures=True)


_default: Optional[LocalEngine] = None
_default_lock = threading.Lock()


def default_engine() -> LocalEngine:
    global _default
    with _default_lock:
        if _default is None:
            _default = LocalEngine()
        return _default


def set_default_engine(engine: LocalEngine):
    global _default
    with _default_lock:
        _default = engine
