"""Spark binding: run sparkdl_tpu plans on a Spark cluster.

The reference WAS a Spark library; this build's engine abstraction keeps
that seam open (SURVEY §7: "a real Spark/mapInArrow binding can be
dropped in where available"). The binding has two halves:

* :func:`plan_to_map_in_arrow` — compile a DataFrame plan into the
  ``iterator[RecordBatch] → iterator[RecordBatch]`` function Spark's
  ``DataFrame.mapInArrow`` expects. Stage closures ship in the Spark
  task the same way the reference shipped frozen GraphDefs; device
  stages run on whatever accelerator the executor's host owns (one JAX
  process per executor). This half is pure and testable without Spark.
* :class:`SparkEngine` — an engine implementing the local
  ``execute(sources, plan)`` contract by parallelizing partition loads
  as a Spark job. Constructing one without pyspark raises with
  instructions; passing an explicit session duck-types (execute() only
  needs ``sparkContext.parallelize(seq, n).map(fn)`` plus
  ``toLocalIterator()`` — or ``collect()`` on minimal fakes), which
  is how the contract test drives the full path — including cloudpickle
  round-trips of the task closures, the way Spark ships them.
  Shippability is designed, not assumed: RunnerMetrics recreates its
  lock on arrival, ModelFunction drops process-local jit/device caches
  on the wire, and host-backend (TF) functions refuse to serialize with
  a re-ingest instruction. Driver-side ``RunnerMetrics``/``StageMetrics``
  counters do NOT aggregate across Spark tasks (each task counts into
  its own copy and discards it) — on a cluster, use Spark's task
  metrics/UI; driver-side metrics are a LocalEngine feature.
"""

from __future__ import annotations

from typing import Callable, Iterator, Sequence

import pyarrow as pa


def apply_plan(stages: Sequence, batch: pa.RecordBatch,
               index: int) -> pa.RecordBatch:
    """Apply a stage plan to one batch — the stage contract
    (``with_index`` stages receive the partition's logical index),
    shared by both binding halves. (``LocalEngine._run_stage`` applies
    the same contract per stage, separately, because it interleaves the
    device lock and per-stage timing.)"""
    for stage in stages:
        batch = (stage.fn(batch, index)
                 if getattr(stage, "with_index", False)
                 else stage.fn(batch))
    return batch


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
        return pyspark
    except ImportError as e:
        raise RuntimeError(
            "SparkEngine requires pyspark (>= 3.3 for mapInArrow). "
            "Install pyspark, or use the default LocalEngine — every "
            "pipeline runs identically on it.") from e


def plan_to_map_in_arrow(plan: Sequence) -> Callable[
        [Iterator[pa.RecordBatch]], Iterator[pa.RecordBatch]]:
    """Compile a stage plan into a ``mapInArrow`` function.

    Usage with Spark::

        fn = plan_to_map_in_arrow(df_tpu._plan)
        out = spark_df.mapInArrow(fn, schema=arrow_schema_ddl)

    ``with_index`` stages receive the Spark partition id from the
    ``TaskContext`` (0 outside Spark). :class:`SparkEngine` instead
    bakes each source's LOGICAL index into its task tuples and applies
    the plan via :func:`apply_plan` directly.

    All stages run inline on the Spark task's Python worker. Executors
    that own an exclusive accelerator (TPU) must run ONE task at a time
    (``spark.task.cpus`` = executor cores, the standard accelerator
    config) — concurrent Python workers would each try to initialize
    the same device.
    """
    stages = list(plan)

    def apply_batches(batches: Iterator[pa.RecordBatch]
                      ) -> Iterator[pa.RecordBatch]:
        index = 0
        try:  # Spark partition id for with_index stages
            from pyspark import TaskContext
            ctx = TaskContext.get()
            if ctx is not None:
                index = ctx.partitionId()
        except ImportError:
            pass
        for batch in batches:
            yield apply_plan(stages, batch, index)

    return apply_batches


def udf_to_column_fn(model_udf, outputMode: str = "vector"
                     ) -> Callable:
    """Compile a :class:`~sparkdl_tpu.udf.registry.ModelUDF` into a pure
    column → column function — the SQL scalar-function body the
    reference's ``makeGraphUDF`` registered through TensorFrames (SURVEY
    §3.5: the call stack ends in ``spark.sql("SELECT udf(image)...")``).

    The returned function accepts an Arrow ``Array``/``ChunkedArray``
    (or a pandas ``Series``, returning a ``Series`` — the
    ``pandas_udf`` calling convention) holding the UDF's input column —
    image structs for ``kind="image"``, numeric/tensor rows for
    ``kind="tensor"`` — and returns the model output as a
    ``list<float>`` column. Execution routes through
    ``ModelUDF.apply`` on a single-batch LocalEngine frame, so a SQL
    call computes exactly what the pipeline transformers compute.
    Cloudpickle-shippable: the ModelFunction drops process-local
    jit/device caches on the wire (same contract as plan stages)."""
    if outputMode != "vector":
        raise ValueError(
            "SQL UDF registration supports outputMode='vector' (a "
            f"list<float> column); got {outputMode!r} — use the "
            "Image/Tensor transformers for struct outputs")

    def column_fn(col):
        pandas_in = False
        if isinstance(col, pa.ChunkedArray):
            arr = col.combine_chunks()
        elif isinstance(col, pa.Array):
            arr = col
        elif hasattr(col, "index") and hasattr(col, "columns"):
            # pandas DataFrame: how pyspark hands a STRUCT column (the
            # image struct) to a scalar pandas_udf — one frame column
            # per struct field
            pandas_in = True
            tbl = pa.Table.from_pandas(col, preserve_index=False)
            children = [tbl.column(i).combine_chunks()
                        for i in range(tbl.num_columns)]
            # pyspark flattens a NULL struct row to all-null fields;
            # rebuild the row-level validity so downstream sees a null
            # image (imageColumnViews' clear error), not a struct of
            # NaNs that dies in a cast
            nulls = None
            if children and any(c.null_count for c in children):
                import numpy as np
                all_null = np.logical_and.reduce(
                    [np.asarray(pa.compute.is_null(c)) for c in children])
                if all_null.any():
                    nulls = pa.array(all_null)  # mask: True = null row
            arr = pa.StructArray.from_arrays(
                children, names=list(tbl.column_names), mask=nulls)
        elif hasattr(col, "index") and hasattr(col, "dtype"):
            # pandas Series: scalar / list (tensor) columns
            pandas_in = True
            arr = pa.Array.from_pandas(col)
        else:  # ndarray / sequence
            arr = pa.array(col)
        from sparkdl_tpu.data.frame import DataFrame
        batch = pa.RecordBatch.from_arrays([arr], names=["__in__"])
        frame = DataFrame.from_batches([batch])
        out = model_udf.apply(frame, "__in__", "__out__",
                              outputMode=outputMode)
        res = out.collect().column("__out__").combine_chunks()
        if pandas_in:
            import pandas as pd
            return pd.Series(res.to_pylist())
        return res

    return column_fn


def register_udf(session, model_udf, name: str = None,
                 outputMode: str = "vector") -> Callable:
    """Register a ModelUDF as a named SQL function on a Spark session —
    the catalog-registration half of the reference's ``makeGraphUDF``.

    With real pyspark, the column function wraps in a ``pandas_udf``
    returning ``array<float>`` and registers via
    ``session.udf.register(name, ...)``, after which
    ``spark.sql(f"SELECT {name}(col) FROM t")`` works. A duck-typed
    session only needs ``udf.register(name, fn)`` — the contract tests
    drive that seam, cloudpickle round-trips included. Returns the
    registered callable."""
    name = name or model_udf.name
    column_fn = udf_to_column_fn(model_udf, outputMode=outputMode)
    # wrap in pandas_udf only for a REAL SparkSession — keyed on the
    # session's type, not pyspark importability, so duck-typed sessions
    # keep the raw column function even where pyspark is installed
    fn = column_fn
    try:
        from pyspark.sql import SparkSession
        is_spark = isinstance(session, SparkSession)
    except ImportError:
        is_spark = False
    if is_spark:
        # errors here (e.g. pyarrow missing/too old for pandas_udf)
        # must PROPAGATE: silently registering the raw Series-convention
        # function as a row-wise UDF would fail per-row at query time
        from pyspark.sql.functions import pandas_udf
        from pyspark.sql.types import ArrayType, FloatType
        fn = pandas_udf(column_fn, returnType=ArrayType(FloatType()))
    registrar = getattr(session, "udf", None)
    if registrar is None or not hasattr(registrar, "register"):
        raise TypeError(
            "session does not expose udf.register(name, fn) — pass a "
            "SparkSession (or a duck-typed session with that seam)")
    registrar.register(name, fn)
    return fn


class SparkEngine:
    """Engine running partition plans as Spark tasks.

    Drop-in for :class:`~sparkdl_tpu.data.engine.LocalEngine` behind the
    same ``execute(sources, plan)`` contract: partition sources are
    parallelized one-per-task, each task loads its batch and applies the
    compiled plan, and results stream back lazily in partition order
    (windowed ``runJob`` collections), keeping driver memory
    O(``stream_chunk_size`` partitions) while the cluster still runs a
    whole window's tasks in parallel.
    """

    def __init__(self, spark=None, stream_chunk_size: int = 64):
        if spark is None:
            _require_pyspark()
            from pyspark.sql import SparkSession
            spark = SparkSession.builder.getOrCreate()
        # An explicit session is duck-typed: execute() only needs
        # sparkContext.parallelize(seq, n).map(fn) plus one of
        # runJob / toLocalIterator / collect, which also makes the
        # engine contract-testable without pyspark.
        self.spark = spark
        self.stream_chunk_size = max(1, int(stream_chunk_size))

    def execute(self, sources: Sequence, plan: Sequence
                ) -> Iterator[pa.RecordBatch]:
        # Stage.batch_hint is advisory and unused here: Spark maps one
        # partition per task, so cross-partition device re-chunking
        # (LocalEngine.execute) has no cross-task seam to work in —
        # each task's device stage pads its own tail. On Spark, size
        # partitions near the device batch (or a multiple) to avoid
        # padding; LocalEngine makes sizing irrelevant.
        stages = list(plan)
        # Ship (load, logical_index) in the task closure — Spark
        # serializes tasks with cloudpickle, which handles the local
        # closures every Source in this codebase uses (stdlib pickle
        # does not). Baking the index in keeps with_index stages on the
        # partition's LOGICAL identity (same contract LocalEngine
        # honors), not the Spark task's positional id.
        loads = [(s.load,
                  s.logical_index if getattr(s, "logical_index", None)
                  is not None else i)
                 for i, s in enumerate(sources)]

        def run_partition(task) -> bytes:
            load, index = task
            batch = apply_plan(stages, load(), index)
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, batch.schema) as w:
                w.write_batch(batch)
            return sink.getvalue().to_pybytes()

        sc = self.spark.sparkContext
        rdd = sc.parallelize(loads, len(loads)).map(run_partition)
        # Stream results back in bounded windows. collect() would
        # materialize EVERY partition's Arrow IPC bytes on the driver at
        # once — at north-star scale (1M rows × 2048-d float32 ≈ 8 GB)
        # that is a driver OOM by construction, where LocalEngine
        # deliberately bounds inflight results. Plain toLocalIterator
        # has the opposite failure: pyspark schedules ONE JOB PER
        # PARTITION sequentially, so a wide cluster degrades from
        # max(partition time) to sum(partition times). Windowed runJob
        # keeps both properties: each window's tasks run in parallel
        # across the cluster, driver memory stays
        # O(stream_chunk_size) partitions.
        run_job = getattr(sc, "runJob", None)
        if callable(run_job):
            for lo in range(0, len(loads), self.stream_chunk_size):
                window = list(range(lo, min(lo + self.stream_chunk_size,
                                            len(loads))))
                for raw in run_job(rdd, lambda it: list(it), window):
                    with pa.ipc.open_stream(pa.BufferReader(raw)) as r:
                        yield from r
            return
        if hasattr(rdd, "toLocalIterator"):
            results = rdd.toLocalIterator()
        else:
            # A duck-typed session may only offer collect(); accept it
            # so minimal fakes still satisfy the contract, but memory is
            # then O(dataset) — fine only for test-sized frames.
            results = iter(rdd.collect())
        for raw in results:
            with pa.ipc.open_stream(pa.BufferReader(raw)) as r:
                yield from r
