"""Thread-topology inference for the H17–H19 race rules.

Rules H1–H16 model locks (H7/H8) without ever asking
*which thread* executes a function — so an unguarded shared-attribute
write from a pool done-callback is invisible: the lock model sees no
lock and the per-file H3 sees no ``_lock_guards`` violation. This
module adds the missing axis. Per function, one scan records a
serializable fact set (:class:`ThreadFacts`):

* **spawn events** — every place a callable is handed to another
  thread: ``threading.Thread(target=...)`` / ``Timer``, executor
  ``submit``/``map`` (pool-shaped receivers), ``add_done_callback``
  (directly or through a single-call lambda), ``ThreadingHTTPServer``
  handler classes (their ``do_*`` methods run one-thread-per-request),
  and ``signal.signal`` handlers;
* **shared-attribute accesses** — every ``self.X`` read / write /
  container mutation / branch-test check, each carrying the exact
  lock *regions* lexically held at that point (``with self._lock:``
  blocks keyed by their opening line; ``acquire()``..``release()``
  line regions). Regions — not just held sets — are what lets H19
  see a check and an act under the SAME lock but in SEPARATE holds;
* **publication material** — mutable locals (list/dict/set/deque
  bindings), local mutations with their held sets, and parameter
  mutations, which is what H18's hand-off analysis runs on.

At program time :class:`ThreadTopology` resolves every spawn target
through the PR-8 call graph (same lexical contract as ``may_block``,
plus the nested-def rule hot-path classification uses) into a **thread
-root inventory**, then flows thread context DOWN the call graph
exactly like ``hotpath.py`` hotness: every function carries the set of
thread roots that may execute it plus a witness chain back to each
root. The main thread is implicit — any function the program can call
runs on it — so "reachable by >= 2 threads" reduces to "reachable
from >= 1 spawn root" (plus the class rule below), and a function
no spawn root reaches stays single-threaded and exempt.

**The class rule.** A method nobody calls from a thread root can
still race: ``StallWatchdog.arm()`` runs on the caller's thread while
``_monitor`` (the spawned root) reads the same instance state. So a
method of class ``C`` is also considered concurrent when ANY method
of ``C`` is thread-root-reachable — the instance is shared with that
thread, and the witness names the sibling method that carries the
root (RacerD's ownership idea, reduced to lexical classes).

Known loops that are roots by construction (the serve dispatcher, the
watchdog monitor, the autotune apply path — driven by ``poll()`` from
every hot-loop thread at once) sit in :data:`KNOWN_THREAD_ROOTS`, the
``EXTRA_HOT_ROOTS`` precedent: spawn-site detection finds them too,
but the table keeps them roots even when the spawn site moves.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# NOTE: no import of callgraph here — callgraph imports this module
# for the per-file scan; the CallGraph is always passed in (the same
# no-cycle discipline hotpath.py keeps).
from sparkdl_tpu.analysis.locks import (
    CallEvent,
    FunctionScanner,
    ModuleLocks,
    _dotted,
)

#: thread/timer constructors whose target runs on a NEW thread
_THREAD_CTORS = {"threading.Thread": "thread", "Thread": "thread",
                 "threading.Timer": "timer", "Timer": "timer"}

#: receiver names that make a ``.submit``/``.map`` call an executor
#: hand-off (the repo's pools are all named like pools)
_POOLISH = re.compile(r"pool|executor|workers", re.IGNORECASE)

#: container-mutator method names (the "mut" access kind): calling
#: one of these on ``self.X`` / a local mutates the object in place
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "add",
             "insert", "remove", "discard", "pop", "popleft", "clear",
             "update", "setdefault", "put", "put_nowait", "rotate",
             "sort", "reverse"}

#: ctor names that bind a MUTABLE container to a local (H18 material)
_MUTABLE_CTORS = {"list", "dict", "set", "deque", "collections.deque",
                  "defaultdict", "collections.defaultdict",
                  "OrderedDict", "collections.OrderedDict",
                  "bytearray"}

#: (module suffix, qualname, label, multi): thread roots by
#: construction — found at their spawn sites too, but pinned here so
#: a moved spawn site cannot silently drop the package's known
#: concurrent loops out of the model (the EXTRA_HOT_ROOTS precedent)
KNOWN_THREAD_ROOTS: Tuple[Tuple[str, str, str, bool], ...] = (
    ("serve.server", "ModelSession._serve_loop",
     "the serve dispatcher thread", False),
    ("obs.watchdog", "StallWatchdog._monitor",
     "the watchdog monitor thread", False),
    ("autotune.core", "AutotuneController.step",
     "the autotune apply path (poll() drives it from every hot-loop "
     "thread at once)", True),
)


def _ref_text(node: ast.AST) -> str:
    """A stable textual handle for a handed-over argument: a bare
    local name, ``self.X``, or "" when the shape is untrackable."""
    if isinstance(node, ast.Name):
        return node.id
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return f"self.{node.attr}"
    return ""


# ---------------------------------------------------------------------------
# the serializable per-function facts


@dataclass
class SpawnEvent:
    """One callable handed across a thread boundary."""

    kind: str           # "thread"|"timer"|"pool"|"callback"|"http"|"signal"
    target_kind: str    # CallEvent kinds, plus "class" (HTTP handler)
    name: str           # callable/class name (last segment)
    qualifier: str      # "self": enclosing class; "dotted": import src
    line: int
    display: str        # what the source says, for messages
    args: Tuple[str, ...] = ()   # handed positional arg refs (_ref_text)
    multi: bool = False          # pool/per-request: >1 thread runs it


@dataclass
class AccessEvent:
    """One ``self.X`` touch with its exact lock-region context."""

    attr: str
    kind: str           # "read" | "write" | "mut" | "check"
    line: int
    #: (lock id, region opening line) for every lock lexically held —
    #: the region line is what tells H19 two holds of ONE lock apart
    regions: Tuple[Tuple[str, int], ...] = ()

    @property
    def held(self) -> Tuple[str, ...]:
        return tuple(lock for lock, _ in self.regions)


@dataclass
class ThreadFacts:
    """The per-function thread/race facts, plain data (cacheable)."""

    key: str
    spawns: List[SpawnEvent] = field(default_factory=list)
    accesses: List[AccessEvent] = field(default_factory=list)
    #: positional parameter names, call-mapping order (self dropped)
    params: List[str] = field(default_factory=list)
    #: local name -> line where it was bound to a mutable container
    mutable_locals: Dict[str, int] = field(default_factory=dict)
    #: in-place mutations of bare names: (name, line, held lock ids)
    local_muts: List[Tuple[str, int, Tuple[str, ...]]] = \
        field(default_factory=list)
    #: every bare name this function assigns (closure-capture fence:
    #: a name a nested def binds itself is NOT captured state)
    locals_bound: List[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "key": self.key,
            "spawns": [[s.kind, s.target_kind, s.name, s.qualifier,
                        s.line, s.display, list(s.args), s.multi]
                       for s in self.spawns],
            "accesses": [[a.attr, a.kind, a.line,
                          [[lk, ln] for lk, ln in a.regions]]
                         for a in self.accesses],
            "params": self.params,
            "mutable_locals": self.mutable_locals,
            "local_muts": [[n, ln, list(held)]
                           for n, ln, held in self.local_muts],
            "locals_bound": self.locals_bound,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ThreadFacts":
        tf = cls(key=d["key"])
        tf.spawns = [SpawnEvent(s[0], s[1], s[2], s[3], s[4], s[5],
                                tuple(s[6]), s[7]) for s in d["spawns"]]
        tf.accesses = [AccessEvent(a[0], a[1], a[2],
                                   tuple((lk, ln) for lk, ln in a[3]))
                       for a in d["accesses"]]
        tf.params = list(d["params"])
        tf.mutable_locals = dict(d["mutable_locals"])
        tf.local_muts = [(m[0], m[1], tuple(m[2]))
                         for m in d["local_muts"]]
        tf.locals_bound = list(d["locals_bound"])
        return tf


# ---------------------------------------------------------------------------
# the per-function scan


class ThreadScanner:
    """One function body → its :class:`ThreadFacts`. Mirrors
    ``locks.FunctionScanner``'s region discipline (lexical ``with``
    scoping; ``acquire()``..``release()`` by source-line region) but
    keeps each hold's IDENTITY — ``(lock, opening line)`` — because
    the race rules need to tell two separate holds of one lock apart.
    Lock identity itself is delegated to a ``FunctionScanner`` so the
    two models can never disagree about what a lock is."""

    def __init__(self, key: str, module: str, path: str,
                 cls: Optional[str], qualname: str, locks: ModuleLocks,
                 imports: Dict[str, str]):
        self.facts = ThreadFacts(key=key)
        self.cls = cls
        self.module = module
        self.imports = imports
        self._ids = FunctionScanner(module, path, cls, qualname, locks,
                                    imports)
        self._locks = locks
        #: flat acquire()..release() regions: (lock, lo, hi)
        self._flat: List[Tuple[str, int, int]] = []

    # -- entry ---------------------------------------------------------------

    def scan(self, fn: ast.AST) -> ThreadFacts:
        args = getattr(fn, "args", None)
        if args is not None:
            names = [a.arg for a in args.posonlyargs + args.args]
            if names and names[0] in ("self", "cls"):
                names = names[1:]
            self.facts.params = names
        self._walk(fn.body, ())
        self._apply_flat_regions()
        return self.facts

    # -- statement walk ------------------------------------------------------

    def _walk(self, stmts: List[ast.stmt],
              regions: Tuple[Tuple[str, int], ...]):
        for stmt in stmts:
            self._visit_stmt(stmt, regions)

    def _visit_stmt(self, stmt: ast.stmt,
                    regions: Tuple[Tuple[str, int], ...]):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return      # nested defs are scanned as their own functions
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            new = tuple(regions)
            for item in stmt.items:
                lock = self._ids._with_item_lock(item.context_expr)
                self._scan_expr(item.context_expr, regions,
                                skip_lock_read=True)
                if lock is not None and lock not in \
                        tuple(lk for lk, _ in new):
                    new = new + ((lock, stmt.lineno),)
            self._walk(stmt.body, new)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            # the branch test is where check-then-act checks live
            self._scan_expr(stmt.test, regions, check=True)
            self._walk(stmt.body, regions)
            if isinstance(stmt, ast.If):
                self._walk(stmt.orelse, regions)
            else:
                self._walk(stmt.orelse, regions)
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self._visit_assign(stmt, regions)
            return
        # acquire()/release() expression statements: flat regions
        if isinstance(stmt, ast.Expr) and isinstance(
                stmt.value, ast.Call) and isinstance(
                stmt.value.func, ast.Attribute):
            call = stmt.value
            attr = call.func.attr
            if attr in ("acquire", "release"):
                lock = self._ids.lock_id(call.func.value)
                if lock is not None:
                    if attr == "acquire" and not \
                            FunctionScanner._is_try_acquire(call):
                        self._flat.append((lock, call.lineno, 1 << 30))
                    elif attr == "release":
                        for i, (lk, lo, hi) in enumerate(self._flat):
                            if lk == lock and hi == 1 << 30 \
                                    and lo < call.lineno:
                                self._flat[i] = (lk, lo, call.lineno)
                                break
                    return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                self._visit_stmt(child, regions)
            elif isinstance(child, ast.expr):
                self._scan_expr(child, regions)
            elif isinstance(child, ast.ExceptHandler):
                self._walk(child.body, regions)
            elif isinstance(child, ast.match_case):
                self._walk(child.body, regions)

    def _visit_assign(self, stmt, regions: Tuple[Tuple[str, int], ...]):
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        value = stmt.value
        # the RHS first: reads happen before the store binds
        if value is not None:
            self._scan_expr(value, regions)
        aug = isinstance(stmt, ast.AugAssign)
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                self.facts.locals_bound.append(tgt.id)
                if aug:
                    # x += [..] rebinding still mutates shared state
                    # only for in-place types; treat as a local mut
                    self._note_local_mut(tgt.id, stmt.lineno, regions)
                elif value is not None and self._is_mutable_ctor(value):
                    self.facts.mutable_locals.setdefault(
                        tgt.id, stmt.lineno)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                for elt in tgt.elts:
                    if isinstance(elt, ast.Name):
                        self.facts.locals_bound.append(elt.id)
                    else:
                        self._visit_assign_target(elt, stmt.lineno,
                                                  regions, aug)
            else:
                self._visit_assign_target(tgt, stmt.lineno, regions,
                                          aug)

    def _visit_assign_target(self, tgt: ast.AST, line: int,
                             regions: Tuple[Tuple[str, int], ...],
                             aug: bool):
        if (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"):
            if aug:
                # read-modify-write: record the read half too
                self._note_access(tgt.attr, "read", line, regions)
            self._note_access(tgt.attr, "write", line, regions)
        elif isinstance(tgt, ast.Subscript):
            base = tgt.value
            if (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"):
                # self.X[k] = v mutates the container behind X
                self._note_access(base.attr, "mut", line, regions)
            elif isinstance(base, ast.Name):
                self._note_local_mut(base.id, line, regions)
            self._scan_expr(tgt.slice, regions)
        elif isinstance(tgt, ast.Attribute):
            # obj.attr = v: scan the receiver for self.X reads
            self._scan_expr(tgt.value, regions)

    # -- expression walk -----------------------------------------------------

    def _scan_expr(self, expr: ast.AST,
                   regions: Tuple[Tuple[str, int], ...],
                   check: bool = False, skip_lock_read: bool = False):
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._record_call(node, regions)
            elif (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and isinstance(node.ctx, ast.Load)):
                if skip_lock_read:
                    continue
                self._note_access(node.attr,
                                  "check" if check else "read",
                                  node.lineno, regions)

    def _record_call(self, call: ast.Call,
                     regions: Tuple[Tuple[str, int], ...]):
        spawn = self._classify_spawn(call)
        if spawn is not None:
            self.facts.spawns.append(spawn)
        if not isinstance(call.func, ast.Attribute):
            return
        recv = call.func.value
        if call.func.attr in _MUTATORS:
            if (isinstance(recv, ast.Attribute)
                    and isinstance(recv.value, ast.Name)
                    and recv.value.id == "self"):
                self._note_access(recv.attr, "mut", call.lineno,
                                  regions)
            elif isinstance(recv, ast.Name):
                self._note_local_mut(recv.id, call.lineno, regions)

    # -- spawn classification ------------------------------------------------

    def _classify_spawn(self, call: ast.Call) -> Optional[SpawnEvent]:
        name = _dotted(call.func)
        # Thread(target=f) / Timer(interval, f)
        if name in _THREAD_CTORS:
            kind = _THREAD_CTORS[name]
            target = None
            handed: List[ast.expr] = []
            for kw in call.keywords:
                if kw.arg in ("target", "function"):
                    target = kw.value
                elif kw.arg == "args" and isinstance(
                        kw.value, (ast.Tuple, ast.List)):
                    handed = list(kw.value.elts)
            if target is None and kind == "timer" and len(call.args) >= 2:
                target = call.args[1]
                handed = handed or (
                    list(call.args[2].elts)
                    if len(call.args) >= 3 and isinstance(
                        call.args[2], (ast.Tuple, ast.List)) else [])
            if target is None and kind == "thread" and call.args:
                # positional Thread(group, target) is never written
                # here; accept Thread(target) defensively
                target = call.args[0]
            return self._spawn_from(kind, target, handed, call,
                                    multi=False)
        # signal.signal(SIG, handler)
        if name in ("signal.signal",) and len(call.args) >= 2:
            return self._spawn_from("signal", call.args[1], [], call,
                                    multi=False)
        if not isinstance(call.func, ast.Attribute):
            return None
        attr = call.func.attr
        recv_name = (_dotted(call.func.value) or "").rsplit(".", 1)[-1]
        # executor.submit(f, *args) / executor.map(f, it)
        if attr in ("submit", "map") and _POOLISH.search(recv_name):
            if not call.args:
                return None
            handed = list(call.args[1:]) if attr == "submit" else []
            return self._spawn_from("pool", call.args[0], handed,
                                    call, multi=True)
        # fut.add_done_callback(cb): cb runs on a pool/worker thread
        if attr == "add_done_callback" and call.args:
            return self._spawn_from("callback", call.args[0], [],
                                    call, multi=True)
        # ThreadingHTTPServer(addr, Handler): every do_* method of
        # Handler runs per-request on its own thread
        if name and name.rsplit(".", 1)[-1] == "ThreadingHTTPServer" \
                and len(call.args) >= 2 and isinstance(
                    call.args[1], ast.Name):
            return SpawnEvent(
                kind="http", target_kind="class",
                name=call.args[1].id, qualifier="", line=call.lineno,
                display=f"{name}(..., {call.args[1].id})", multi=True)
        return None

    def _spawn_from(self, kind: str, target: Optional[ast.AST],
                    handed: List[ast.expr], call: ast.Call,
                    multi: bool) -> Optional[SpawnEvent]:
        if target is None:
            return None
        # a single-call lambda hands its CALLEE across the boundary
        # (the pipeline's `lambda _f, p=pos: _unwatch(p)` idiom)
        if isinstance(target, ast.Lambda) and isinstance(
                target.body, ast.Call):
            inner = target.body
            handed = handed or list(inner.args)
            target = inner.func
        name = _dotted(target)
        if name is None:
            return None
        args = tuple(_ref_text(a) for a in handed)
        parts = name.split(".")
        if parts[0] == "self" and len(parts) == 2:
            return SpawnEvent(kind, "self", parts[1], self.cls or "",
                              call.lineno, name, args, multi)
        if len(parts) == 1:
            return SpawnEvent(kind, "name", parts[0], "",
                              call.lineno, name, args, multi)
        if len(parts) == 2 and parts[0] in self.imports:
            return SpawnEvent(kind, "dotted", parts[1],
                              self.imports[parts[0]], call.lineno,
                              name, args, multi)
        return SpawnEvent(kind, "method", parts[-1], "", call.lineno,
                          name, args, multi)

    # -- bookkeeping ---------------------------------------------------------

    def _note_access(self, attr: str, kind: str, line: int,
                     regions: Tuple[Tuple[str, int], ...]):
        # a lock attribute is synchronization, not shared data — and
        # its Condition alias is the same lock
        cls = self.cls or ""
        canon = self._locks.canonical_attr(cls, attr)
        if canon in self._locks.class_locks.get(cls, ()):
            return
        if attr != canon or re.search(
                r"^_?(lock|mutex|cond|sem)\b", attr):
            return
        self.facts.accesses.append(AccessEvent(attr, kind, line,
                                               regions))

    def _note_local_mut(self, name: str, line: int,
                        regions: Tuple[Tuple[str, int], ...]):
        self.facts.local_muts.append(
            (name, line, tuple(lk for lk, _ in regions)))

    def _apply_flat_regions(self):
        """Fold acquire()..release() line regions into every recorded
        event (the lexical ``with`` regions were exact already)."""
        if not self._flat:
            return

        def fold(line: int, regions: Tuple[Tuple[str, int], ...]
                 ) -> Tuple[Tuple[str, int], ...]:
            out = list(regions)
            held = {lk for lk, _ in out}
            for lk, lo, hi in self._flat:
                if lo < line <= hi and lk not in held:
                    out.append((lk, lo))
                    held.add(lk)
            return tuple(out)

        for a in self.facts.accesses:
            a.regions = fold(a.line, a.regions)
        self.facts.local_muts = [
            (n, ln, tuple(dict.fromkeys(
                list(held) + [lk for lk, lo, hi in self._flat
                              if lo < ln <= hi])))
            for n, ln, held in self.facts.local_muts]

    @staticmethod
    def _is_mutable_ctor(value: ast.AST) -> bool:
        if isinstance(value, (ast.List, ast.Dict, ast.Set,
                              ast.ListComp, ast.DictComp, ast.SetComp)):
            return True
        if isinstance(value, ast.Call):
            name = _dotted(value.func)
            return name in _MUTABLE_CTORS
        return False


def scan_threads(fn: ast.AST, key: str, module: str, path: str,
                 cls: Optional[str], qualname: str, locks: ModuleLocks,
                 imports: Dict[str, str]) -> ThreadFacts:
    """One function def → its serializable thread/race facts."""
    return ThreadScanner(key, module, path, cls, qualname, locks,
                         imports).scan(fn)


# ---------------------------------------------------------------------------
# program-time topology


def _short(key: str) -> str:
    mod, _, qual = key.partition("::")
    mod = mod[len("sparkdl_tpu."):] if mod.startswith("sparkdl_tpu.") \
        else mod
    return f"{mod}:{qual}" if qual else mod


@dataclass
class ThreadRoot:
    """One entry in the thread-root inventory."""

    key: str            # function key of the root
    label: str          # human "why is this a thread"
    kind: str           # spawn kind, or "known"
    multi: bool         # more than one OS thread may run this root
    site: str = ""      # "path:line" of the spawn, "" for known roots


class ThreadTopology:
    """Thread-context reachability over one CallGraph.

    ``reach[key]`` maps each thread root that may execute ``key`` to
    the witness chain (function keys, root first). ``class_reach``
    lifts that to classes: a method of a class with any thread-rooted
    method shares the instance with that thread (see module
    docstring). The main thread is implicit everywhere.
    """

    def __init__(self, graph, tfacts: Dict[str, ThreadFacts]):
        self.graph = graph
        self.tfacts = tfacts
        self.roots: Dict[str, ThreadRoot] = {}
        #: fn key -> {root key -> witness chain (keys, root first)}
        self.reach: Dict[str, Dict[str, Tuple[str, ...]]] = {}
        #: "module::Class" -> {root key -> the reachable method's key}
        self.class_reach: Dict[str, Dict[str, str]] = {}
        self._collect_roots()
        self._close()
        self._lift_classes()

    # -- roots ---------------------------------------------------------------

    def _collect_roots(self) -> None:
        for key, f in self.graph.functions.items():
            for suffix, qual, label, multi in KNOWN_THREAD_ROOTS:
                if f.qualname == qual and (
                        f.module == suffix
                        or f.module.endswith("." + suffix)):
                    self.roots.setdefault(key, ThreadRoot(
                        key, label, "known", multi))
        for key, tf in self.tfacts.items():
            caller = self.graph.functions.get(key)
            if caller is None:
                continue
            for sp in tf.spawns:
                for target in self._spawn_targets(caller, sp):
                    site = f"{caller.path}:{sp.line}"
                    label = self._root_label(sp, caller)
                    have = self.roots.get(target)
                    if have is None or (sp.multi and not have.multi):
                        self.roots[target] = ThreadRoot(
                            target, label, sp.kind,
                            sp.multi or (have.multi if have else False),
                            site)

    def _root_label(self, sp: SpawnEvent, caller) -> str:
        what = {"thread": "threading.Thread target",
                "timer": "threading.Timer callback",
                "pool": "executor worker task",
                "callback": "future done-callback (runs on a worker "
                            "thread)",
                "http": "ThreadingHTTPServer per-request handler",
                "signal": "signal handler"}[sp.kind]
        return (f"{what} spawned by {_short(caller.key)} "
                f"({caller.path}:{sp.line})")

    def _spawn_targets(self, caller, sp: SpawnEvent) -> List[str]:
        """Resolved function keys a spawn event hands over (an HTTP
        handler class contributes every per-request method)."""
        if sp.target_kind == "class":
            mod = caller.module
            methods = self.graph.modules.get(mod)
            out = []
            if methods is not None:
                for m in methods.classes.get(sp.name, ()):
                    if m.startswith("do_") or m == "log_message":
                        k = f"{mod}::{sp.name}.{m}"
                        if k in self.graph.functions:
                            out.append(k)
            return out
        # the nested-def rule first (the pipeline's lambda ->
        # _unwatch hand-off binds to the enclosing def's nested fn)
        if sp.target_kind == "name":
            probe = caller.qualname
            while True:
                nested = f"{caller.module}::{probe}.{sp.name}" if probe \
                    else f"{caller.module}::{sp.name}"
                if nested in self.graph.functions:
                    return [nested]
                if "." not in probe:
                    break
                probe = probe.rsplit(".", 1)[0]
        call = CallEvent(sp.target_kind, sp.name, sp.display, sp.line,
                         (), sp.qualifier)
        target = self.graph.resolve(caller, call)
        return [target] if target is not None else []

    # -- reachability --------------------------------------------------------

    def _close(self) -> None:
        """BFS the resolved call edges from every root: thread
        context flows DOWN the call graph, exactly like hotness."""
        from sparkdl_tpu.analysis.hotpath import _resolve
        for root in sorted(self.roots):
            work = [root]
            self.reach.setdefault(root, {})[root] = (root,)
            while work:
                key = work.pop(0)
                f = self.graph.functions.get(key)
                if f is None:
                    continue
                chain = self.reach[key][root]
                for call in f.calls:
                    target = _resolve(self.graph, f, call)
                    if target is None:
                        continue
                    seen = self.reach.setdefault(target, {})
                    if root in seen:
                        continue
                    seen[root] = chain + (target,)
                    work.append(target)

    def _lift_classes(self) -> None:
        for key, roots in self.reach.items():
            f = self.graph.functions.get(key)
            if f is None or "." not in f.qualname:
                continue
            cls = f.qualname.split(".", 1)[0]
            mod = self.graph.modules.get(f.module)
            if mod is None or cls not in mod.classes:
                continue    # a nested def's prefix is not a class
            ck = f"{f.module}::{cls}"
            table = self.class_reach.setdefault(ck, {})
            for root in roots:
                table.setdefault(root, key)

    # -- queries -------------------------------------------------------------

    def threads_of(self, key: str) -> Dict[str, Tuple[str, ...]]:
        """root key -> witness chain for every thread root that may
        execute ``key``, including class-shared roots (the chain then
        runs to the sibling method that carries the root)."""
        out = dict(self.reach.get(key, {}))
        f = self.graph.functions.get(key)
        if f is not None and "." in f.qualname:
            cls = f.qualname.split(".", 1)[0]
            ck = f"{f.module}::{cls}"
            for root, via in self.class_reach.get(ck, {}).items():
                out.setdefault(root, self.reach[via][root])
        return out

    def is_concurrent(self, key: str) -> bool:
        """True when >= 2 OS threads may touch state this function
        touches: reachable from a spawn root (the main thread is the
        implicit second), or a method of a class with such a method."""
        return bool(self.threads_of(key))

    def witness(self, key: str, limit: int = 2) -> str:
        """The printable both-roots witness: each root's label plus
        its module-by-module chain, ending with the implicit main
        thread."""
        entries = []
        for root, chain in sorted(self.threads_of(key).items()):
            info = self.roots[root]
            path = " -> ".join(_short(k) for k in chain)
            onto = "" if chain[-1] == key else \
                f" (shares {_short(key).rsplit('.', 1)[0]}'s instance " \
                f"state)"
            many = " [multi-worker]" if info.multi else ""
            entries.append(f"[{info.label}{many}: {path}{onto}]")
            if len(entries) >= limit:
                break
        entries.append("[the main thread: any direct caller]")
        return " and ".join(entries)


def thread_topology(graph) -> ThreadTopology:
    """The (memoized) topology for one CallGraph — built once per
    analyzer invocation, shared by H17/H18/H19 (the _flow_state
    precedent)."""
    state = getattr(graph, "_sparkdl_thread_topology", None)
    if state is None:
        tfacts: Dict[str, ThreadFacts] = {}
        for m in graph.modules.values():
            tfacts.update(getattr(m, "threads", {}) or {})
        state = ThreadTopology(graph, tfacts)
        graph._sparkdl_thread_topology = state
    return state
