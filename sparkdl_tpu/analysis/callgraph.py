"""Package-wide symbol table + call graph for the H7/H8 program rules.

The per-file rules (H1–H6) see one module at a time; the concurrency
failure modes this repo has actually shipped fixes for — a serve-layer
lock held while a function from another module blocks inside it, two
modules acquiring the same pair of locks in opposite orders — only
exist in the WHOLE program. This module builds that view:

* :func:`scan_module` — one parsed module → :class:`ModuleFacts`:
  imports, class/method inventory, module/class lock identities
  (locks.py), and a per-function event stream (acquires, direct
  may-block operations, call sites — each carrying the lexically-held
  lock set at that point). The facts are plain-data serializable,
  which is what makes the analyzer's per-file result cache work.
* :class:`CallGraph` — all modules' facts → resolved call edges plus
  the two transitive facts the rules need, computed by bounded-depth
  memoized descent: ``may_block(f)`` (does any reachable callee block)
  and ``may_acquire(f)`` (which locks can a call into ``f`` end up
  taking), each with a recorded next-hop so a finding can print the
  actual witness chain module-by-module instead of "trust me".

Resolution is deliberately lexical (the sparkdl-lint contract): a
``self.m()`` call binds to the enclosing class's ``m``; a bare name to
the module table then the import table; ``mod.f`` through an imported
module; a plain ``obj.m()`` only when exactly ONE class in the
analyzed set defines ``m`` (the unique-method heuristic — ambiguity
resolves to "no edge", because a false edge would manufacture false
deadlocks, while a missed edge only costs recall the fixtures pin).
Bounded depth (:data:`MAX_DEPTH`) keeps the closure linear in
practice and is far deeper than any real chain in this repo.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from sparkdl_tpu.analysis import dataflow as _dataflow
from sparkdl_tpu.analysis import effects as _effects
from sparkdl_tpu.analysis import threads as _threads
from sparkdl_tpu.analysis.locks import (
    CallEvent,
    FunctionFacts,
    FunctionScanner,
    ModuleLocks,
    discover_locks,
)

#: transitive-closure depth bound: deep enough for every real chain
#: (serve dispatch -> runner.run -> dispatch_chunks -> sink.write ->
#: timed_device_get is 5), bounded so a pathological cycle costs
#: nothing
MAX_DEPTH = 8


def module_name(path: str) -> str:
    """A stable dotted module name from a (display) path: anchored at
    the package root when the path contains one, else the last two
    segments (``tools/measure_transfer.py`` → ``tools.measure_transfer``),
    else the stem."""
    norm = path.replace("\\", "/")
    parts = [p for p in norm.split("/") if p not in ("", ".")]
    stem = parts[-1][:-3] if parts[-1].endswith(".py") else parts[-1]
    parts = parts[:-1] + [stem]
    for anchor in ("sparkdl_tpu",):
        if anchor in parts:
            parts = parts[parts.index(anchor):]
            break
    else:
        parts = parts[-2:] if len(parts) >= 2 else parts
    if parts and parts[-1] == "__init__":
        parts = parts[:-1] or ["__init__"]
    return ".".join(parts)


@dataclass
class ModuleFacts:
    """Everything the program rules need from one module, plain data."""

    module: str
    path: str
    #: local name -> dotted source ("pkg.mod" for modules,
    #: "pkg.mod.obj" for from-imports)
    imports: Dict[str, str] = field(default_factory=dict)
    #: class name -> method names defined in its body
    classes: Dict[str, List[str]] = field(default_factory=dict)
    #: module-level function names
    functions: List[str] = field(default_factory=list)
    #: module-level lock names (confirms imported-lock candidates)
    module_locks: List[str] = field(default_factory=list)
    #: per-function facts, keyed "module::Qual"
    facts: Dict[str, FunctionFacts] = field(default_factory=dict)
    #: per-function effect facts (effects.py), same keys as ``facts``
    effects: Dict[str, "_effects.FunctionEffects"] = \
        field(default_factory=dict)
    #: per-function device-dataflow facts (dataflow.py), same keys
    flows: Dict[str, "_dataflow.DeviceFlow"] = \
        field(default_factory=dict)
    #: per-function thread/race facts (threads.py), same keys
    threads: Dict[str, "_threads.ThreadFacts"] = \
        field(default_factory=dict)
    #: class name -> attrs its ``_lock_guards`` declares (the H3
    #: convention, authoritative for guarded-by inference)
    class_guards: Dict[str, List[str]] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"module": self.module, "path": self.path,
                "imports": self.imports, "classes": self.classes,
                "functions": self.functions,
                "module_locks": self.module_locks,
                "class_guards": self.class_guards,
                "facts": {k: f.to_dict() for k, f in self.facts.items()},
                "effects": {k: e.to_dict()
                            for k, e in self.effects.items()},
                "flows": {k: fl.to_dict()
                          for k, fl in self.flows.items()},
                "threads": {k: t.to_dict()
                            for k, t in self.threads.items()}}

    @classmethod
    def from_dict(cls, d: dict) -> "ModuleFacts":
        mf = cls(module=d["module"], path=d["path"],
                 imports=dict(d["imports"]),
                 classes={k: list(v) for k, v in d["classes"].items()},
                 functions=list(d["functions"]),
                 module_locks=list(d.get("module_locks", [])))
        mf.facts = {k: FunctionFacts.from_dict(v)
                    for k, v in d["facts"].items()}
        mf.effects = {k: _effects.FunctionEffects.from_dict(v)
                      for k, v in d.get("effects", {}).items()}
        mf.flows = {k: _dataflow.DeviceFlow.from_dict(v)
                    for k, v in d.get("flows", {}).items()}
        mf.threads = {k: _threads.ThreadFacts.from_dict(v)
                      for k, v in d.get("threads", {}).items()}
        mf.class_guards = {k: list(v) for k, v in
                           d.get("class_guards", {}).items()}
        return mf


def _class_guards(node: ast.ClassDef) -> List[str]:
    """The class-body ``_lock_guards = ("field", ...)`` declaration
    (the H3 convention — writes to these hold ``self._lock``), made
    visible to the program-level guarded-by inference (races.py)."""
    for item in node.body:
        if not isinstance(item, ast.Assign):
            continue
        for tgt in item.targets:
            if isinstance(tgt, ast.Name) and tgt.id == "_lock_guards" \
                    and isinstance(item.value, (ast.Tuple, ast.List)):
                return sorted({e.value for e in item.value.elts
                               if isinstance(e, ast.Constant)
                               and isinstance(e.value, str)})
    return []


def _collect_imports(tree: ast.Module) -> Dict[str, str]:
    imports: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                imports[alias.asname or alias.name.split(".")[0]] = \
                    alias.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                imports[alias.asname or alias.name] = \
                    f"{node.module}.{alias.name}"
    return imports


def scan_module(tree: ast.Module, path: str,
                module: Optional[str] = None) -> ModuleFacts:
    """One parsed module → its serializable program-analysis facts
    (call/lock facts for H7/H8 plus the effect/jit/capture/resource
    facts the H10/H11 effect system runs on)."""
    module = module or module_name(path)
    mf = ModuleFacts(module=module, path=path)
    mf.imports = _collect_imports(tree)
    locks: ModuleLocks = discover_locks(tree, module)
    #: class -> instance attrs bound to mutable containers (the
    #: capture analysis consults the ENCLOSING class of a jitted fn)
    cls_mutables: Dict[str, set] = {}
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            cls_mutables[node.name] = _effects.mutable_class_attrs(node)
    #: def name -> fact keys (resolves `jax.jit(step)` call forms)
    name_keys: Dict[str, List[str]] = {}

    def scan_fn(fn, qualname: str, cls: Optional[str],
                enclosing_mutables: Dict[str, int]):
        scanner = FunctionScanner(module, path, cls, qualname, locks,
                                  mf.imports)
        scanner.scan(fn)
        key = f"{module}::{qualname}"
        mf.facts[key] = FunctionFacts(
            key=key, module=module, path=path, qualname=qualname,
            line=fn.lineno, acquires=scanner.acquires,
            blocks=scanner.blocks, calls=scanner.calls)
        fe = _effects.FunctionEffects(key=key)
        eff = _effects.EffectScanner(qualname, mf.imports,
                                     cls_mutables.get(cls or "", set()))
        fe.effects = eff.scan(fn)
        fe.resources = _effects._ResourceTracker(fn, qualname).run(
            mf.imports)
        fe.captures = _effects.scan_captures(
            fn, cls_mutables.get(cls or "", set()), enclosing_mutables)
        if any(_effects._is_jit_decorator(d)
               for d in getattr(fn, "decorator_list", ())):
            fe.jitted = True
            fe.jit_line = fn.lineno
        mf.effects[key] = fe
        mf.flows[key] = _dataflow.scan_flow(fn, key, mf.imports, cls)
        mf.threads[key] = _threads.scan_threads(
            fn, key, module, path, cls, qualname, locks, mf.imports)
        name_keys.setdefault(fn.name, []).append(key)

    def iter_defs(body):
        """Def/class statements anywhere in ``body``, descending into
        compound statements (for/if/with/try/match) but never into
        another def or class — a jitted step defined inside an epoch
        loop (the streaming-estimator idiom) is still THIS scope's
        def."""
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                yield node
                continue
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.stmt):
                    yield from iter_defs([child])
                elif isinstance(child, ast.ExceptHandler):
                    yield from iter_defs(child.body)
                elif isinstance(child, ast.match_case):
                    yield from iter_defs(child.body)

    def walk_defs(body, prefix: str, cls: Optional[str],
                  enclosing_mutables: Dict[str, int]):
        for node in iter_defs(body):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                qual = f"{prefix}{node.name}" if prefix else node.name
                scan_fn(node, qual, cls, enclosing_mutables)
                # nested defs get their own facts under a dotted qual;
                # their capture analysis sees THIS function's mutable
                # local bindings
                walk_defs(node.body, qual + ".", cls,
                          _effects._local_mutable_bindings(node))
            elif isinstance(node, ast.ClassDef):
                methods = [m.name for m in node.body
                           if isinstance(m, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))]
                mf.classes[node.name] = methods
                cls_mutables.setdefault(
                    node.name, _effects.mutable_class_attrs(node))
                guards = _class_guards(node)
                if guards:
                    mf.class_guards[node.name] = guards
                walk_defs(node.body, node.name + ".", node.name, {})

    walk_defs(tree.body, "", None, {})
    # jit call forms: jax.jit(step), partial(jax.jit, ...)(step) —
    # mark the named def(s) as jit roots (same resolution as H2)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _effects._jit_call(node):
            args = node.args
            if _effects._dotted(node.func) in _effects._PARTIAL_NAMES:
                args = args[1:]
        elif isinstance(node.func, ast.Call) and \
                _effects._jit_call(node.func):
            # partial(jax.jit, ...)(step): the OUTER call's args hold
            # the traced function
            args = node.args
        else:
            continue
        for arg in args:
            if isinstance(arg, ast.Name):
                for key in name_keys.get(arg.id, ()):
                    mf.effects[key].jitted = True
                    mf.effects[key].jit_line = \
                        mf.effects[key].jit_line or node.lineno
    # captures only mean anything at a jit boundary — dropping the
    # rest keeps the serialized facts (and the result cache) lean
    for fe in mf.effects.values():
        if not fe.jitted:
            fe.captures = []
    mf.functions = [mf.facts[q].qualname for q in mf.facts
                    if "." not in mf.facts[q].qualname]
    mf.module_locks = sorted(locks.module_locks)
    return mf


class CallGraph:
    """The resolved whole-program view over a set of ModuleFacts."""

    def __init__(self, modules: List[ModuleFacts]):
        self.modules = {m.module: m for m in modules}
        #: every function key -> facts
        self.functions: Dict[str, FunctionFacts] = {}
        #: method name -> defining keys across the analyzed set
        self._methods: Dict[str, List[str]] = {}
        #: module -> {function name -> key}
        self._module_fns: Dict[str, Dict[str, str]] = {}
        for m in modules:
            fns: Dict[str, str] = {}
            for key, f in m.facts.items():
                self.functions[key] = f
                qual = f.qualname
                if "." not in qual:
                    fns[qual] = key
                else:
                    cls, meth = qual.rsplit(".", 1)
                    if "." not in cls:   # plain Class.method
                        self._methods.setdefault(meth, []).append(key)
            self._module_fns[m.module] = fns
        self._may_block: Dict[str, Optional[Tuple[str, str]]] = {}
        self._may_acquire: Dict[str, Dict[str, Tuple[str, ...]]] = {}
        self._normalize_lock_ids()

    def _normalize_lock_ids(self) -> None:
        """An imported lock's id carries the import-path module
        (``collective::LAUNCH_LOCK``); the defining module's own id
        carries its display-derived name (``fixtures.collective::…``).
        Remap by unique module suffix so both spellings are ONE lock —
        cross-module lock identity is the whole point of H7. Imported
        CANDIDATES (``?mod::attr`` — a bare imported name used as a
        context manager) confirm against the defining module's
        module-lock table (or a lock-shaped name when the module is
        outside the analyzed set) and DROP otherwise: ``with
        some_imported_cm:`` is not a lock."""
        from sparkdl_tpu.analysis.locks import _LOCKISH_NAME
        cache: Dict[str, Optional[str]] = {}

        def norm(lock: str) -> Optional[str]:
            if lock in cache:
                return cache[lock]
            out: Optional[str] = lock
            candidate = lock.startswith("?")
            mod, sep, attr = lock.lstrip("?").partition("::")
            if sep and mod not in self.modules:
                match = self._match_module(mod)
                if match is not None:
                    mod = match
            if candidate:
                facts = self.modules.get(mod)
                if facts is not None:
                    out = (f"{mod}::{attr}"
                           if attr in facts.module_locks else None)
                else:
                    out = (f"{mod}::{attr}"
                           if _LOCKISH_NAME.search(attr) else None)
            elif sep:
                out = f"{mod}::{attr}"
            cache[lock] = out
            return out

        for f in self.functions.values():
            kept = []
            for acq in f.acquires:
                lock = norm(acq.lock)
                if lock is None:
                    continue
                acq.lock = lock
                acq.held = tuple(h2 for h2 in
                                 (norm(h) for h in acq.held)
                                 if h2 is not None)
                kept.append(acq)
            f.acquires = kept
            for b in f.blocks:
                b.held = tuple(h2 for h2 in (norm(h) for h in b.held)
                               if h2 is not None)
            for c in f.calls:
                c.held = tuple(h2 for h2 in (norm(h) for h in c.held)
                               if h2 is not None)
        # the thread/race facts carry the same lock ids in their
        # region tuples — same normalization, or a candidate spelling
        # ("?mod::attr") would never match its confirmed one and the
        # race rules would see "no common lock" where there is one
        for m in self.modules.values():
            for tf in getattr(m, "threads", {}).values():
                for a in tf.accesses:
                    a.regions = tuple(
                        (lk, ln) for lk, ln in
                        ((norm(lk0), ln0) for lk0, ln0 in a.regions)
                        if lk is not None)
                tf.local_muts = [
                    (n, ln, tuple(h2 for h2 in
                                  (norm(h) for h in held)
                                  if h2 is not None))
                    for n, ln, held in tf.local_muts]

    def _match_module(self, dotted: str) -> Optional[str]:
        """The analyzed module an import path names: exact, else by
        unique dotted-suffix (``from serve import f`` inside a tree
        whose display-derived module is ``fixtures.serve``)."""
        if dotted in self.modules:
            return dotted
        hits = [m for m in self.modules
                if m.endswith("." + dotted) or m == dotted]
        return hits[0] if len(hits) == 1 else None

    # -- resolution ----------------------------------------------------------

    def resolve(self, caller: FunctionFacts, call: CallEvent
                ) -> Optional[str]:
        """The callee's key, or None when lexical resolution cannot
        name exactly one target."""
        mod = self.modules.get(caller.module)
        if call.kind == "self":
            cls = call.qualifier
            key = f"{caller.module}::{cls}.{call.name}"
            if key in self.functions:
                return key
            # inherited method: unique across the analyzed classes
            return self._unique_method(call.name)
        if call.kind == "name":
            key = self._module_fns.get(caller.module, {}).get(call.name)
            if key is not None:
                return key
            if mod is not None:
                src = mod.imports.get(call.name)
                if src is not None:
                    m, _, fn = src.rpartition(".")
                    m = self._match_module(m) if m else None
                    if m is not None:
                        key = f"{m}::{fn}"
                        if key in self.functions:
                            return key
            return None
        if call.kind == "dotted":
            src = self._match_module(call.qualifier)
            if src is not None:
                key = f"{src}::{call.name}"
                if key in self.functions:
                    return key
            return None
        if call.kind == "method":
            return self._unique_method(call.name)
        return None

    def _unique_method(self, name: str) -> Optional[str]:
        keys = self._methods.get(name, [])
        if len(keys) == 1:
            return keys[0]
        return None

    # -- transitive facts ----------------------------------------------------

    def may_block(self, key: str, depth: int = MAX_DEPTH,
                  _seen: Optional[Set[str]] = None
                  ) -> Optional[Tuple[str, str]]:
        """(witness chain, blocking-op description) when a call into
        ``key`` can block the calling thread; None otherwise. The chain
        is " -> "-joined qualified names ending at the blocking op."""
        if key in self._may_block:
            return self._may_block[key]
        f = self.functions.get(key)
        if f is None or depth <= 0:
            return None
        seen = _seen if _seen is not None else set()
        if key in seen:
            return None
        seen.add(key)
        result: Optional[Tuple[str, str]] = None
        if f.blocks:
            b = f.blocks[0]
            result = (self.short(key), b.what)
        else:
            for call in f.calls:
                target = self.resolve(f, call)
                if target is None or target == key:
                    continue
                sub = self.may_block(target, depth - 1, seen)
                if sub is not None:
                    result = (f"{self.short(key)} -> {sub[0]}", sub[1])
                    break
        seen.discard(key)
        if _seen is None or result is not None or depth == MAX_DEPTH:
            self._may_block[key] = result
        return result

    def may_acquire(self, key: str, depth: int = MAX_DEPTH,
                    _seen: Optional[Set[str]] = None
                    ) -> Dict[str, Tuple[str, ...]]:
        """lock id -> witness chain (qualified names, " -> "-joined)
        for every lock a call into ``key`` may end up acquiring."""
        if key in self._may_acquire:
            return self._may_acquire[key]
        f = self.functions.get(key)
        if f is None or depth <= 0:
            return {}
        seen = _seen if _seen is not None else set()
        if key in seen:
            return {}
        seen.add(key)
        out: Dict[str, Tuple[str, ...]] = {}
        for acq in f.acquires:
            out.setdefault(acq.lock, (self.short(key),))
        for call in f.calls:
            target = self.resolve(f, call)
            if target is None or target == key:
                continue
            for lock, chain in self.may_acquire(
                    target, depth - 1, seen).items():
                out.setdefault(lock,
                               (self.short(key),) + chain)
        seen.discard(key)
        if _seen is None or depth == MAX_DEPTH:
            self._may_acquire[key] = out
        return out

    # -- display -------------------------------------------------------------

    @staticmethod
    def short(key: str) -> str:
        """`module::Qual` with the package prefix trimmed for humans."""
        mod, _, qual = key.partition("::")
        mod = mod[len("sparkdl_tpu."):] if \
            mod.startswith("sparkdl_tpu.") else mod
        return f"{mod}:{qual}" if qual else mod


def parse_file(path: str) -> Optional[ast.Module]:
    try:
        with open(path, encoding="utf-8") as f:
            return ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None


def build_graph(paths: List[str]) -> CallGraph:
    """Convenience for tests/tools: parse + scan + assemble."""
    mods = []
    for path in paths:
        tree = parse_file(path)
        if tree is not None:
            mods.append(scan_module(tree, os.path.relpath(path)
                                    if not path.startswith("..")
                                    else path))
    return CallGraph(mods)
