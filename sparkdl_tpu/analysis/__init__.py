"""sparkdl-lint: repo-specific static analysis for the hot-path
invariants.

PR 1's zero-copy ship path claims "0 host staging copies on aligned
runs"; RunnerMetrics counters and a handful of tests pin it, but
nothing stops the next refactor from reintroducing an implicit
device→host sync, an unlocked slab write, or a retracing hazard.
tf.data (arXiv 2101.12127) and the TensorFlow system paper (arXiv
1605.08695) both argue pipeline performance contracts must be checked
by *tooling*, not convention — this package is that tooling, the
static half of the enforcement pair (the dynamic half is
``sparkdl_tpu.runtime.sanitize``, which puts ``jax.transfer_guard``
under the ship path at runtime).

The per-file rules, each an AST visitor over every module analyzed:

* **H1 — implicit host transfers**: ``jax.device_get`` /
  ``.block_until_ready()`` / ``np.asarray(<jnp-producing call>)``
  outside the allowlisted drain-path set (SlabSink's drain, the
  measure tools). A stray sync on the ship path is exactly the
  stale-buffer collapse round 1 measured.
* **H2 — jit/retrace hazards**: Python side effects (``time.*``,
  ``print``, stateful RNG) inside ``jax.jit``/``pjit``-compiled
  functions — they run at trace time, not step time — and
  unhashable ``static_argnums``/``static_argnames`` literals.
* **H3 — concurrency discipline**: classes holding a
  ``threading.Lock`` must define ``__getstate__``/``__reduce__``
  (locks don't pickle; runner.py learned this the hard way), and
  writes to fields a class declares in ``_lock_guards`` must sit
  inside a ``with self._lock`` block.
* **H4 — quiesce hygiene**: bare ``except:`` anywhere; silently
  swallowed exceptions (``except ...: pass``) in cleanup paths
  (``finally`` blocks, ``close``/``quiesce``/``__exit__``-shaped
  functions) — a swallowed secondary error during quiesce masks
  the drain the engine's effectful-source contract depends on.
* **H5 — clock discipline** (path-scoped to ``sparkdl_tpu/obs/``
  and ``sparkdl_tpu/serve/``): ``time.time()`` / ``datetime.now()``
  are banned where span/latency math lives — everything must share
  the tracer's ``time.perf_counter`` clock, or wall-clock steps
  (NTP, suspend) silently skew the one timeline the obs layer
  exists to keep honest.
* **H6 — metric-name cardinality**: registry
  ``counter``/``gauge``/``reservoir`` names interpolating a
  request-shaped identifier (``request_id``/``req_id``/``rid``) —
  a per-request id as a metric key grows one eternal registry entry
  and Prometheus series per request; ids belong in the bounded
  ``RequestLog``, reservoir exemplars, and span args
  (``obs/request_log.py``), never in metric names.

Three WHOLE-PROGRAM rules run over every analyzed module at once
(callgraph.py builds the package-wide symbol table + call graph,
locks.py the lock-scope model; per-file results/facts are cached by
mtime+hash so the ci.sh gate stays fast):

* **H7 — lock-order cycles**: the acquired-while-holding graph
  (lock A held while lock B is acquired, directly or through any
  resolved call chain) must be acyclic; a cycle is reported with its
  module-by-module witness path — the PR-2 collective-enqueue
  deadlock, reconstructed as a fixture, is the canonical catch.
* **H8 — blocking call under a lock**: device syncs
  (``timed_device_get``/``.block_until_ready()``), ``Condition.wait``,
  ``queue.get``, ``time.sleep``, file/socket I/O, thread joins — or a
  transitively-may-block callee — reached while a lock is held. The
  serve dispatcher's intentional coalescing wait is allowlisted.
* **H9 — contract drift**: every registry key, span lane, env var,
  and ``/statusz`` field the code publishes is cross-checked against
  the docs tables (docs/OBSERVABILITY.md, docs/SERVING.md,
  docs/PERFORMANCE.md, README.md for env vars) in BOTH directions —
  an undocumented publish fails, and so does a documented-but-gone
  name.

Three rules consume the whole-program **effect system**
(``effects.py``: a bounded-depth transitive effect set per function —
registry writes, spans, logging, clocks/RNG, transfers, I/O, lock
acquires, mutation of captured state — with witness chains):

* **H10 — effectful call reachable from jit**: any effect
  transitively reachable from a ``jax.jit``/``pjit``-traced body
  through resolved call edges, printed module-by-module; plus
  mutable state (lists/dicts/instance attrs) captured into a jitted
  function — the stale-value/retrace hazard the lexical H2 cannot
  see.
* **H11 — resource lifecycle**: an object whose class defines a
  terminator (``close``/``quiesce``/``shutdown``/``disarm``) — plus
  ``open()``/tempfile handles and obs-singleton ``arm()``s —
  constructed in a scope must reach its terminator there or escape
  (returned, stored, registered, passed on).
* **H12 — exception-flow accounting** (``serve/``, ``obs/``,
  ``runtime/``): an ``except`` that swallows — ``pass``, bare
  ``continue``, or log-only — must record a failure counter/SLO
  outcome on the handler path or carry an inline suppression (PR 7's
  population-separation fix as a static invariant).
* **H13 — unbounded retry loops** (``serve/``, ``runtime/``,
  ``data/``, ``resilience/``): a ``while True`` whose except handler
  swallows and loops again with no escape — re-attempts must be
  bounded and backed-off (``resilience.RetryPolicy``: attempts +
  exponential backoff + retry budget), never a bare spin on a
  failing dependency.

Three rules consume the whole-program **device-dataflow layer**
(``dataflow.py``: per-function replayable device-value tracking,
propagated through assignments, returns, and resolved call edges;
``hotpath.py``: hot = transitively reachable from the
watchdog-instrumented runner/serve/engine/estimator loops, with
witness chains):

* **H14 — hot-path host sync**: a device-resident value
  materialized on host (``np.asarray``, ``.item()``, ``float()``/
  ``len()``, truthiness, iteration) inside a hot function, anywhere
  except the sanctioned ``timed_device_get`` drain — the hot chain
  is printed module-by-module.
* **H15 — missing buffer donation**: a jit call whose device-array
  argument is dead after the call but whose compile site declares
  no ``donate_argnums`` — HBM double-buffered every step.
* **H16 — dtype widening**: Python float / ``np.float64`` scalars
  and dtype-less numpy ctors mixed into device arithmetic on a hot
  path — a silent 2x payload tax on a link-bound pipeline.

CI annotation: ``--sarif out.sarif`` writes SARIF 2.1.0;
``--changed-only`` (``tools/lint.sh --fast``) lints only
git-dirty files for the pre-commit loop.

Findings suppress inline with a justification::

    jax.device_get(x)  # sparkdl-lint: allow[H1] -- epoch-end drain

or via the built-in allowlist (``sparkdl_tpu.analysis.suppress``).
CLI: ``python -m sparkdl_tpu.analysis [paths...]`` (exit 1 on any
unsuppressed finding); ``tools/lint.sh`` wraps it together with the
generic ruff/mypy baseline from pyproject.toml. Rule reference:
``docs/LINT.md``.
"""

from __future__ import annotations

from sparkdl_tpu.analysis.callgraph import (
    CallGraph,
    build_graph,
    scan_module,
)
from sparkdl_tpu.analysis.effects import may_effect
from sparkdl_tpu.analysis.findings import Finding, format_findings
from sparkdl_tpu.analysis.rules import RULES, rule_doc
from sparkdl_tpu.analysis.sarif import to_sarif, write_sarif
from sparkdl_tpu.analysis.suppress import DEFAULT_ALLOWLIST, AllowEntry
from sparkdl_tpu.analysis.walker import (
    ALL_RULES,
    analyze_paths,
    analyze_source,
    iter_python_files,
)

__all__ = [
    "ALL_RULES",
    "AllowEntry",
    "CallGraph",
    "DEFAULT_ALLOWLIST",
    "Finding",
    "RULES",
    "analyze_paths",
    "analyze_source",
    "build_graph",
    "format_findings",
    "iter_python_files",
    "may_effect",
    "rule_doc",
    "scan_module",
    "to_sarif",
    "write_sarif",
]
