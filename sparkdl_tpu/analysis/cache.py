"""Per-file mtime+hash result cache for the analyzer.

ci.sh runs the lint gate on every build; the package is ~100 modules
and the whole-program passes re-parse all of them even when one file
changed. The cache keeps the expensive per-file work — parse, the
H1–H6 rule passes, the callgraph/lock fact extraction — keyed by
``(mtime_ns, content sha256, analyzer version, rule set)``; program
rules (H7–H9) always re-run over the (cheap, already-extracted) facts
because their verdicts depend on every file at once.

The cache degrades to a no-op on ANY problem (unreadable file, bad
JSON, version bump): correctness never depends on it, and a corrupt
cache is silently discarded rather than trusted. ``__main__`` reports
hits/misses in ``--json`` output so CI can gate that a second run
actually hit (and a touched file actually re-analyzed).

Location: ``SPARKDL_TPU_LINT_CACHE`` (a file path), or the default
under the system temp dir, namespaced by euid so shared CI hosts do
not fight over one file. ``--no-cache`` disables entirely.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from dataclasses import asdict
from typing import Dict, List, Optional, Tuple

from sparkdl_tpu.analysis.callgraph import ModuleFacts
from sparkdl_tpu.analysis.contracts import CodeSurface
from sparkdl_tpu.analysis.findings import Finding

#: bump when rule logic or fact shape changes — stale entries miss
#: (v5: the effect-system facts — ModuleFacts.effects — joined the
#: per-file schema; v6: rule H13 unbounded-retry-loops; v7: the
#: device-dataflow facts — ModuleFacts.flows, rules H14–H16 — joined
#: the per-file schema; v8: the thread/race facts —
#: ModuleFacts.threads + class_guards, rules H17–H19; a version bump
#: MUST force a cold re-analysis, pinned by tests/test_effects.py and
#: tests/test_races.py)
ANALYZER_VERSION = 8


def default_cache_path() -> str:
    env = os.environ.get("SPARKDL_TPU_LINT_CACHE", "")
    if env:
        return env
    uid = getattr(os, "geteuid", lambda: 0)()
    return os.path.join(tempfile.gettempdir(),
                        f"sparkdl_lint_cache_{uid}.json")


def file_stamp(path: str, source: str) -> Tuple[int, str]:
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        mtime = 0
    digest = hashlib.sha256(source.encode("utf-8")).hexdigest()[:20]
    return mtime, digest


class ResultCache:
    """One JSON file: display path → cached per-file entry."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self.hits = 0
        self.misses = 0
        self._data: Dict[str, dict] = {}
        self._dirty = False
        if path and os.path.isfile(path):
            try:
                with open(path, encoding="utf-8") as f:
                    raw = json.load(f)
                if raw.get("version") == ANALYZER_VERSION and \
                        isinstance(raw.get("files"), dict):
                    self._data = raw["files"]
            except (OSError, ValueError):
                self._data = {}

    @property
    def enabled(self) -> bool:
        return self.path is not None

    def lookup(self, display: str, path: str, source: str,
               rules_key: str
               ) -> Optional[Tuple[List[Finding], ModuleFacts,
                                   CodeSurface]]:
        if not self.enabled:
            return None
        entry = self._data.get(display)
        mtime, digest = file_stamp(path, source)
        if (not entry or entry.get("sha") != digest
                or entry.get("mtime") != mtime
                or entry.get("rules") != rules_key):
            self.misses += 1
            return None
        try:
            findings = [Finding(**f) for f in entry["findings"]]
            facts = ModuleFacts.from_dict(entry["facts"])
            surface = CodeSurface.from_dict(entry["surface"])
        except (KeyError, TypeError, ValueError):
            self.misses += 1
            return None
        self.hits += 1
        return findings, facts, surface

    def store(self, display: str, path: str, source: str,
              rules_key: str, findings: List[Finding],
              facts: ModuleFacts, surface: CodeSurface) -> None:
        if not self.enabled:
            return
        mtime, digest = file_stamp(path, source)
        self._data[display] = {
            "mtime": mtime, "sha": digest, "rules": rules_key,
            # suppression state is recomputed per run (the annotation
            # lives in the source, whose hash keys this entry — but a
            # cheap replay keeps the walker logic in ONE place)
            "findings": [asdict(f) for f in findings],
            "facts": facts.to_dict(),
            "surface": surface.to_dict(),
        }
        self._dirty = True

    def save(self) -> None:
        if not (self.enabled and self._dirty):
            return
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"version": ANALYZER_VERSION,
                           "files": self._data}, f)
            os.replace(tmp, self.path)
        except OSError:
            # a read-only cache dir must never fail the lint
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def stats(self) -> dict:
        return {"enabled": self.enabled, "path": self.path,
                "hits": self.hits, "misses": self.misses}
