"""H9 — contract drift: what the code publishes vs what the docs table.

The observability surface IS a contract: registry keys scrape to
Prometheus series, span lanes are how an operator reads a trace, env
vars are the ops interface, ``/statusz`` fields feed dashboards. The
docs tables (docs/OBSERVABILITY.md, docs/SERVING.md,
docs/PERFORMANCE.md — plus README.md and the other docs for env vars)
promise those names; nothing has enforced the promise, and every PR so
far re-synced the tables by hand. H9 cross-checks BOTH directions:

* a name the code publishes but no doc table carries → the finding
  points at the publish site and names the table to edit;
* a name a doc table carries but the code no longer publishes → the
  finding points at the doc row (stale docs are worse than none — an
  operator greps for a key that no longer exists mid-incident).

What counts as "published" (lexical, same contract as H1–H6):

* **registry keys** — string/f-string names in
  ``*.counter(...)``/``*.gauge(...)``/``*.reservoir(...)`` calls;
  f-string holes become ``*`` segments. A publish through a variable
  (the ``RunnerMetrics.publish`` loop idiom) falls back to collecting
  the dotted string constants of the enclosing function.
* **span lanes** — ``lane="..."`` constants (plus the tracer's
  internal positional ``_record(name, lane, ...)`` form).
* **env vars** — ``SPARKDL_TPU_*`` string constants outside
  docstrings; the doc corpus for these is every ``docs/*.md`` plus
  ``README.md``, and the code corpus additionally text-scans the repo
  root's driver scripts (bench.py, tools/) so a var documented for the
  bench doesn't read as stale.
* **/statusz fields** — the top-level keys of the dict
  ``obs/export.py::TelemetryServer._statusz`` returns, against
  SERVING.md's field table (first path segment; ``servers[].…`` rows
  anchor ``servers``).

Doc tables parse from GitHub-flavored markdown: the first column of
any table whose header cell is ``key`` (registry), ``lane`` columns
anywhere, and the ``field`` table (statusz). ``<name>``/``<objective>``
placeholders and f-string holes both normalize to ``*``; match is
pattern OVERLAP (some concrete name satisfies both), so the docs'
``serve.*`` row covers the code's enumerated ``serve.…`` keys and vice
versa.
"""

from __future__ import annotations

import ast
import glob
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from sparkdl_tpu.analysis.findings import Finding

_ENV_RE = re.compile(r"\bSPARKDL_TPU_[A-Z0-9_]+\b")
_KEYISH = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_*]+)+$")
_BACKTICK = re.compile(r"`([^`]+)`")

#: the three tables H9 gates (named in findings so the fix is obvious)
REGISTRY_TABLE = "registry-key table (docs/OBSERVABILITY.md / docs/SERVING.md)"
LANE_TABLE = "span-lane table (docs/OBSERVABILITY.md)"
STATUSZ_TABLE = "/statusz field table (docs/SERVING.md)"

#: lanes never passed explicitly (the span() default) — not a contract
_IGNORED_LANES = {"host"}


@dataclass
class Publish:
    """One published name with its source location."""

    name: str               # pattern; '*' segments for dynamic parts
    path: str
    line: int


@dataclass
class CodeSurface:
    """Everything the analyzed code publishes."""

    registry: List[Publish] = field(default_factory=list)
    lanes: List[Publish] = field(default_factory=list)
    env: List[Publish] = field(default_factory=list)
    statusz: List[Publish] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {k: [[p.name, p.path, p.line] for p in getattr(self, k)]
                for k in ("registry", "lanes", "env", "statusz")}

    @classmethod
    def from_dict(cls, d: dict) -> "CodeSurface":
        s = cls()
        for k in ("registry", "lanes", "env", "statusz"):
            getattr(s, k).extend(
                Publish(e[0], e[1], e[2]) for e in d.get(k, []))
        return s

    def merge(self, other: "CodeSurface") -> None:
        for k in ("registry", "lanes", "env", "statusz"):
            getattr(self, k).extend(getattr(other, k))


# ---------------------------------------------------------------------------
# code-side extraction


def _fstring_pattern(node: ast.JoinedStr) -> Optional[str]:
    parts: List[str] = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value)
        elif isinstance(v, ast.FormattedValue):
            parts.append("*")
        else:
            return None
    return "".join(parts)


def _docstring_nodes(tree: ast.Module) -> Set[int]:
    """ids of Constant nodes that are docstrings (skipped by the env
    scan — prose mentions are documentation, not publishes)."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if body and isinstance(body[0], ast.Expr) and isinstance(
                    body[0].value, ast.Constant) and isinstance(
                    body[0].value.value, str):
                out.add(id(body[0].value))
    return out


_METRIC_FACTORIES = {"counter", "gauge", "reservoir"}


class _SurfaceVisitor(ast.NodeVisitor):
    def __init__(self, path: str, tree: ast.Module,
                 surface: CodeSurface):
        self.path = path
        self.surface = surface
        self._doc_ids = _docstring_nodes(tree)
        self._fn_stack: List[ast.AST] = []

    def visit_FunctionDef(self, node):
        self._fn_stack.append(node)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Constant(self, node: ast.Constant):
        if isinstance(node.value, str) and id(node) not in self._doc_ids:
            for m in _ENV_RE.finditer(node.value):
                self.surface.env.append(
                    Publish(m.group(0), self.path, node.lineno))

    def visit_Call(self, node: ast.Call):
        # registry keys
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _METRIC_FACTORIES:
            name_arg = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords if kw.arg == "name"),
                None)
            self._record_metric_name(name_arg, node)
        # span lanes: span(..., lane="x") and _record(name, "lane", ..)
        fn_name = None
        if isinstance(node.func, ast.Name):
            fn_name = node.func.id
        elif isinstance(node.func, ast.Attribute):
            fn_name = node.func.attr
        if fn_name == "span":
            for kw in node.keywords:
                if kw.arg == "lane" and isinstance(
                        kw.value, ast.Constant) and isinstance(
                        kw.value.value, str):
                    self._lane(kw.value.value, node.lineno)
        elif fn_name == "_record" and len(node.args) >= 2 and \
                isinstance(node.args[1], ast.Constant) and \
                isinstance(node.args[1].value, str):
            self._lane(node.args[1].value, node.lineno)
        self.generic_visit(node)

    def _lane(self, lane: str, line: int):
        if lane not in _IGNORED_LANES:
            self.surface.lanes.append(Publish(lane, self.path, line))

    def _record_metric_name(self, name_arg, call: ast.Call):
        if name_arg is None:
            return
        if isinstance(name_arg, ast.Constant) and isinstance(
                name_arg.value, str):
            self.surface.registry.append(
                Publish(name_arg.value, self.path, call.lineno))
            return
        if isinstance(name_arg, ast.JoinedStr):
            pat = _fstring_pattern(name_arg)
            if pat is not None:
                self.surface.registry.append(
                    Publish(pat, self.path, call.lineno))
                return
        # dynamic name (publish-loop idiom): fall back to the dotted
        # string constants of the enclosing function — the key tables
        # those loops iterate are module-local literals in this repo
        if self._fn_stack:
            for node in ast.walk(self._fn_stack[-1]):
                if isinstance(node, ast.Constant) and isinstance(
                        node.value, str) and _KEYISH.match(node.value):
                    self.surface.registry.append(Publish(
                        node.value, self.path, node.lineno))
                elif isinstance(node, ast.JoinedStr):
                    pat = _fstring_pattern(node)
                    if pat and _KEYISH.match(pat):
                        self.surface.registry.append(Publish(
                            pat, self.path, node.lineno))


def _extract_statusz(tree: ast.Module, path: str,
                     surface: CodeSurface) -> None:
    """Top-level keys of the dict `_statusz` returns (obs/export.py)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "_statusz":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Return) and isinstance(
                        sub.value, ast.Dict):
                    for k in sub.value.keys:
                        if isinstance(k, ast.Constant) and isinstance(
                                k.value, str):
                            surface.statusz.append(
                                Publish(k.value, path, k.lineno))


def extract_file_surface(path: str, tree: ast.Module) -> CodeSurface:
    """One module's published surface (cache-serializable)."""
    surface = CodeSurface()
    _SurfaceVisitor(path, tree, surface).visit(tree)
    if path.replace("\\", "/").endswith("obs/export.py"):
        _extract_statusz(tree, path, surface)
    return surface


def extract_surface(files: List[Tuple[str, ast.Module]]) -> CodeSurface:
    surface = CodeSurface()
    for path, tree in files:
        surface.merge(extract_file_surface(path, tree))
    return surface


# ---------------------------------------------------------------------------
# docs-side extraction


@dataclass
class DocName:
    name: str               # normalized pattern
    path: str
    line: int


@dataclass
class DocSurface:
    registry: List[DocName] = field(default_factory=list)
    lanes: List[DocName] = field(default_factory=list)
    env: List[DocName] = field(default_factory=list)
    statusz: List[DocName] = field(default_factory=list)


def _expand_cell_tokens(cell: str, prev: Optional[str]) -> List[str]:
    """Backticked tokens of one table cell, with `{a,b}` brace sets
    expanded, `<x>` placeholders → `*`, and a leading-dot token
    continuing the previous token's prefix (`slo.<o>.burn_rate` /
    `.budget_remaining`)."""
    out: List[str] = []
    for raw in _BACKTICK.findall(cell):
        tok = raw.strip()
        if not tok or " " in tok:
            continue
        if tok.startswith("."):
            base = out[-1] if out else prev
            if base is None:
                continue
            tok = base.rsplit(".", 1)[0] + tok
        # brace expansion: a.{x,y}.z -> a.x.z, a.y.z
        m = re.search(r"\{([^{}]+)\}", tok)
        variants = ([tok.replace(m.group(0), alt.strip())
                     for alt in m.group(1).split(",")] if m else [tok])
        for v in variants:
            v = re.sub(r"<[^<>]+>", "*", v)
            out.append(v)
    return out


def _iter_tables(path: str):
    """(header_cells, [(line_no, row_cells), ...]) per markdown table."""
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        if line.startswith("|") and i + 1 < len(lines) and \
                set(lines[i + 1].strip()) <= set("|-: "):
            header = [c.strip().lower()
                      for c in line.strip("|").split("|")]
            rows = []
            j = i + 2
            while j < len(lines) and lines[j].strip().startswith("|"):
                cells = [c.strip()
                         for c in lines[j].strip().strip("|").split("|")]
                rows.append((j + 1, cells))
                j += 1
            yield header, rows
            i = j
        else:
            i += 1


def extract_docs(docs_files: List[str]) -> DocSurface:
    docs = DocSurface()
    for path in docs_files:
        with open(path, encoding="utf-8") as f:
            text = f.read()
        for idx, line in enumerate(text.splitlines(), start=1):
            for m in _ENV_RE.finditer(line):
                docs.env.append(DocName(m.group(0), path, idx))
        for header, rows in _iter_tables(path):
            first = header[0] if header else ""
            lane_cols = [k for k, h in enumerate(header) if h == "lane"]
            for line_no, cells in rows:
                prev = None
                if first == "key" and cells:
                    for tok in _expand_cell_tokens(cells[0], prev):
                        docs.registry.append(DocName(tok, path, line_no))
                        prev = tok
                if first == "field" and cells:
                    # a dotted first token makes the rest of the cell
                    # sub-paths of it (`servers[].models.<n>.collective`
                    # / `chunk` / `runner`); an undotted first token
                    # makes the cell a list of sibling top-level
                    # fields (`uptime_s`, `pid`, `platform`)
                    toks = _expand_cell_tokens(cells[0], prev)
                    if toks:
                        anchor = ([toks[0]] if "." in toks[0]
                                  else [t for t in toks
                                        if "." not in t])
                        for tok in anchor:
                            root = tok.split(".")[0].replace("[]", "")
                            docs.statusz.append(
                                DocName(root, path, line_no))
                for k in lane_cols:
                    if k < len(cells):
                        for tok in _expand_cell_tokens(cells[k], None):
                            docs.lanes.append(
                                DocName(tok, path, line_no))
    return docs


# ---------------------------------------------------------------------------
# pattern matching


def _overlap(a: List[str], b: List[str]) -> bool:
    """Can some concrete dotted name match both patterns? `*` matches
    one segment, a TRAILING `*` one-or-more."""
    if not a and not b:
        return True
    if not a or not b:
        return False
    a0, b0 = a[0], b[0]
    if a0 == "*" and len(a) == 1:
        return len(b) >= 1
    if b0 == "*" and len(b) == 1:
        return len(a) >= 1
    if a0 == "*" or b0 == "*" or a0 == b0 or \
            _seg_overlap(a0, b0):
        return _overlap(a[1:], b[1:])
    return False


def _seg_overlap(a: str, b: str) -> bool:
    """Within-segment wildcards (`inflight*`)."""
    if "*" not in a and "*" not in b:
        return a == b
    ra = re.escape(a).replace(r"\*", ".*")
    rb = re.escape(b).replace(r"\*", ".*")
    return bool(re.fullmatch(ra, b.replace("*", "x"))
                or re.fullmatch(rb, a.replace("*", "x")))


def names_overlap(a: str, b: str) -> bool:
    return _overlap(a.split("."), b.split("."))


# ---------------------------------------------------------------------------
# the rule


def find_docs(start: str) -> Optional[str]:
    """The repo docs dir governing ``start``: walk up for a directory
    holding docs/OBSERVABILITY.md + docs/SERVING.md +
    docs/PERFORMANCE.md. None → H9 is skipped (fixture trees)."""
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    for _ in range(8):
        docs = os.path.join(cur, "docs")
        if all(os.path.isfile(os.path.join(docs, n)) for n in
               ("OBSERVABILITY.md", "SERVING.md", "PERFORMANCE.md")):
            return cur
        nxt = os.path.dirname(cur)
        if nxt == cur:
            break
        cur = nxt
    return None


def _doc_corpus(root: str) -> List[str]:
    out = sorted(glob.glob(os.path.join(root, "docs", "*.md")))
    readme = os.path.join(root, "README.md")
    if os.path.isfile(readme):
        out.append(readme)
    return out


def _script_env_tokens(root: str) -> Set[str]:
    """Env vars read by the repo's driver scripts (bench.py, tools/*,
    examples/*) — text scan only; they are part of the env contract's
    CODE side even when the lint targets don't include them."""
    tokens: Set[str] = set()
    paths = [os.path.join(root, "bench.py")]
    paths += glob.glob(os.path.join(root, "tools", "*"))
    paths += glob.glob(os.path.join(root, "examples", "*"))
    for path in paths:
        try:
            with open(path, encoding="utf-8", errors="ignore") as f:
                tokens.update(_ENV_RE.findall(f.read()))
        except OSError:
            continue
    return tokens


def check_h9(files: List[Tuple[str, ast.Module]],
             docs_root: Optional[str] = None) -> List[Finding]:
    """Cross-check the analyzed files' published surface against the
    doc tables under ``docs_root`` (auto-detected from the first file
    when None)."""
    if not files:
        return []
    return check_surface(extract_surface(files),
                         [p for p, _ in files], docs_root)


def check_surface(surface: CodeSurface, file_paths: List[str],
                  docs_root: Optional[str] = None) -> List[Finding]:
    """The H9 verdict over an (already-extracted, possibly cached)
    published surface. Doc-side ("documented but gone") checks only
    run when the analyzed set includes the package's obs layer — a
    partial lint (one file, tools/ only) must not misread the docs as
    stale."""
    if not file_paths:
        return []
    if docs_root is None:
        docs_root = find_docs(file_paths[0])
    if docs_root is None:
        return []
    docs = extract_docs(_doc_corpus(docs_root))
    findings: List[Finding] = []
    full_view = any(p.replace("\\", "/").endswith("obs/registry.py")
                    for p in file_paths)

    def gate(published: List[Publish], documented: List[DocName],
             table: str, kind: str, match=names_overlap,
             doc_side: bool = True):
        for pub in published:
            if not any(match(pub.name, d.name) for d in documented):
                findings.append(Finding(
                    rule="H9", path=pub.path, line=pub.line, col=0,
                    message=(
                        f"{kind} `{pub.name}` is published here but "
                        f"missing from the {table} — document it "
                        "there (the docs tables are the operator "
                        "contract), or suppress with `# sparkdl-lint: "
                        "allow[H9] -- <why it is not part of the "
                        "contract>`")))
        if not (doc_side and full_view):
            return
        pub_names = [p.name for p in published]
        for d in documented:
            if not any(match(n, d.name) for n in pub_names):
                findings.append(Finding(
                    rule="H9", path=d.path, line=d.line, col=0,
                    message=(
                        f"documented {kind} `{d.name}` is no longer "
                        f"published by the code — remove or update "
                        f"this row of the {table} (stale docs send an "
                        "operator grepping for a name that does not "
                        "exist)")))

    gate(surface.registry, docs.registry, REGISTRY_TABLE,
         "registry key")
    gate(surface.lanes, docs.lanes, LANE_TABLE, "span lane",
         match=lambda a, b: a == b)
    gate(surface.statusz, docs.statusz, STATUSZ_TABLE,
         "/statusz field", match=lambda a, b: a == b)
    # env vars: docs corpus is ALL prose (not just tables); the code
    # corpus adds the driver scripts' reads
    script_tokens = _script_env_tokens(docs_root)
    doc_env = {d.name for d in docs.env}
    seen_env: Set[str] = set()
    for pub in surface.env:
        if pub.name in seen_env:
            continue
        seen_env.add(pub.name)
        if pub.name not in doc_env:
            findings.append(Finding(
                rule="H9", path=pub.path, line=pub.line, col=0,
                message=(
                    f"env var `{pub.name}` is read here but "
                    "documented nowhere under docs/ or README.md — "
                    "add it to the relevant doc (env vars are the ops "
                    "interface), or suppress with `# sparkdl-lint: "
                    "allow[H9] -- <why>`")))
    if full_view:
        code_env = {p.name for p in surface.env} | script_tokens
        reported: Set[str] = set()
        for d in docs.env:
            if d.name in code_env or d.name in reported:
                continue
            reported.add(d.name)
            findings.append(Finding(
                rule="H9", path=d.path, line=d.line, col=0,
                message=(
                    f"documented env var `{d.name}` is read by "
                    "nothing in the package or driver scripts — "
                    "remove or update the mention (a documented knob "
                    "that does nothing is an operator trap)")))
    findings.sort(key=lambda f: (f.path, f.line))
    return findings
