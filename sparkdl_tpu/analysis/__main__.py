"""CLI: ``python -m sparkdl_tpu.analysis [paths...]``.

Exit 0 when every finding is suppressed (inline annotation or
allowlist), 1 when any unsuppressed finding remains, 2 on usage
errors — the contract tools/ci.sh's static-analysis gate keys off.

With no paths the default target set is the installed package PLUS the
repo's ``tools/`` and ``examples/`` trees when they sit next to it —
the CLI scripts hold no locks but they do call the hot paths, and a
deadlock witness that starts in an example is still a deadlock.

``--json`` emits the machine schema CI gates: findings, counts,
per-rule totals, and the per-file cache's hit/miss accounting (the
cache is on by default — ``SPARKDL_TPU_LINT_CACHE`` names the file,
``--no-cache`` disables it). ``--sarif out.sarif`` additionally writes
SARIF 2.1.0 for CI review annotation; ``--changed-only`` restricts the
run to files ``git status --porcelain`` reports dirty (the
``tools/lint.sh --fast`` pre-commit loop), falling back to a full run
outside a checkout.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from sparkdl_tpu.analysis.cache import default_cache_path
from sparkdl_tpu.analysis.findings import format_findings
from sparkdl_tpu.analysis.rules import rule_doc
from sparkdl_tpu.analysis.sarif import write_sarif
from sparkdl_tpu.analysis.walker import ALL_RULES, analyze_paths


def _package_dir() -> str:
    import sparkdl_tpu
    return os.path.dirname(os.path.abspath(sparkdl_tpu.__file__))


def _default_targets() -> list:
    """The installed package, plus the repo's tools/ and examples/
    when present — `python -m sparkdl_tpu.analysis` with no args lints
    everything the repo actually ships and drives. The extra dirs are
    only taken when the package parent IS the repo checkout (marker:
    docs/OBSERVABILITY.md) — a site-packages install must not sweep a
    neighboring distribution's stray tools/ directory."""
    pkg = _package_dir()
    targets = [pkg]
    root = os.path.dirname(pkg)
    if os.path.isfile(os.path.join(root, "docs", "OBSERVABILITY.md")):
        for extra in ("tools", "examples"):
            d = os.path.join(root, extra)
            if os.path.isdir(d):
                targets.append(d)
    return targets


def _git_dirty_files(root: str):
    """Paths ``git status --porcelain`` reports dirty/changed in the
    checkout governing ``root``, or None when there is none (no git,
    not a repo, timeout) — the caller falls back to a full run.
    Porcelain paths are TOPLEVEL-relative (the package may sit in a
    subdirectory of a larger repo), so the toplevel is resolved first;
    ``-z`` keeps unusual filenames un-quoted."""
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"], cwd=root,
            capture_output=True, text=True, timeout=30)
        if top.returncode != 0:
            return None
        toplevel = top.stdout.strip()
        proc = subprocess.run(
            ["git", "status", "--porcelain", "-z"], cwd=root,
            capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    out = []
    records = proc.stdout.split("\0")
    i = 0
    while i < len(records):
        rec = records[i]
        i += 1
        if len(rec) < 4:
            continue
        status, path = rec[:2], rec[3:]
        if "R" in status or "C" in status:
            # -z rename/copy: "XY new\0old" — the NEW path is in this
            # record; the following record is the original, skip it
            i += 1
        if path.endswith(".py"):
            out.append(os.path.join(toplevel, path))
    return out


def _changed_only_targets(targets: list) -> list:
    """The dirty ``.py`` files inside ``targets``, for the fast
    pre-commit loop. Returns ``targets`` unchanged (full run) when no
    git checkout governs them. NOTE: the whole-program passes
    (H7/H8/H10/H11) then see only the changed modules — cross-module
    witnesses that START in an unchanged file wait for the full run
    (docs/LINT.md)."""
    root = os.path.dirname(_package_dir())
    dirty = _git_dirty_files(root)
    if dirty is None:
        print("sparkdl-lint: --changed-only outside a git checkout; "
              "running the full target set", file=sys.stderr)
        return targets
    abs_targets = [os.path.abspath(t) for t in targets]
    picked = []
    for path in dirty:
        ap = os.path.abspath(path)
        if not os.path.isfile(ap):
            continue        # deleted files have nothing to lint
        for t in abs_targets:
            if ap == t or ap.startswith(t.rstrip(os.sep) + os.sep):
                picked.append(ap)
                break
    return picked


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sparkdl_tpu.analysis",
        description="sparkdl-lint: enforce the hot-path invariants "
                    "(H1 transfers, H2 retrace, H3 locks, H4 quiesce, "
                    "H5 clocks, H6 cardinality, H12 exception-flow "
                    "accounting, H13 unbounded retry loops) plus the "
                    "whole-program passes (H7 lock-order cycles, H8 "
                    "blocking under a lock, H9 docs contract drift, "
                    "H10 jit-purity closure, H11 resource lifecycle, "
                    "H14 hot-path host syncs, H15 missing buffer "
                    "donation, H16 dtype widening, and the static "
                    "race rules: H17 unguarded access, H18 unsafe "
                    "publication, H19 atomicity split). "
                    "Rule reference: docs/LINT.md")
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: the sparkdl_tpu "
             "package + the repo's tools/ and examples/)")
    parser.add_argument(
        "--rule", action="append", choices=sorted(ALL_RULES),
        dest="rules",
        help="run only this rule (repeatable; default: all)")
    parser.add_argument(
        "--format", choices=("text", "json"), default="text")
    parser.add_argument(
        "--json", action="store_true",
        help="shorthand for --format json (the CI gate's schema)")
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="also print suppressed findings with their justifications")
    parser.add_argument(
        "--no-cache", action="store_true",
        help="disable the per-file mtime+hash result cache")
    parser.add_argument(
        "--cache", metavar="PATH", default=None,
        help="cache file (default: SPARKDL_TPU_LINT_CACHE or a "
             "per-user temp file)")
    parser.add_argument(
        "--sarif", metavar="PATH", default=None,
        help="additionally write findings as SARIF 2.1.0 (CI forges "
             "annotate them at file:line in review)")
    parser.add_argument(
        "--changed-only", action="store_true",
        help="lint only files `git status --porcelain` reports "
             "dirty/changed (the fast pre-commit loop, "
             "tools/lint.sh --fast); falls back to a full run "
             "outside a checkout")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalogue and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in sorted(ALL_RULES):
            print(f"{rule}: {rule_doc(rule)}")
        return 0

    targets = args.paths or _default_targets()
    for t in targets:
        if not os.path.exists(t):
            print(f"sparkdl-lint: no such path: {t}", file=sys.stderr)
            return 2
    if args.changed_only:
        targets = _changed_only_targets(targets)
        if not targets:
            print("sparkdl-lint: --changed-only: nothing changed, "
                  "nothing to lint", file=sys.stderr)
            if args.sarif:
                write_sarif(args.sarif, [],
                            args.rules or list(ALL_RULES))
            if args.json or args.format == "json":
                # the machine contract holds on the empty run too — a
                # consumer json.loads()ing stdout must never crash
                print(json.dumps({
                    "findings": [], "unsuppressed": 0, "suppressed": 0,
                    "rules": sorted(args.rules) if args.rules
                    else sorted(ALL_RULES),
                    "by_rule": {}, "targets": [],
                    "cache": {"enabled": not args.no_cache,
                              "path": None, "hits": 0, "misses": 0},
                    "timing": {"per_rule_s": {}, "total_s": 0.0},
                }, indent=2))
            return 0

    cache_path = None if args.no_cache else \
        (args.cache or default_cache_path())
    cache_stats: dict = {}
    rule_stats: dict = {}
    findings = analyze_paths(targets, rules=args.rules,
                             cache_path=cache_path,
                             cache_stats=cache_stats,
                             rule_stats=rule_stats)
    unsuppressed = [f for f in findings if not f.suppressed]
    if args.sarif:
        n = write_sarif(args.sarif, findings,
                        args.rules or list(ALL_RULES))
        print(f"sparkdl-lint: wrote {n} SARIF result(s) to "
              f"{args.sarif}", file=sys.stderr)
    fmt = "json" if args.json else args.format
    if fmt == "json":
        shown = [f for f in findings
                 if args.show_suppressed or not f.suppressed]
        by_rule: dict = {}
        for f in findings:
            entry = by_rule.setdefault(
                f.rule, {"unsuppressed": 0, "suppressed": 0})
            entry["suppressed" if f.suppressed else "unsuppressed"] += 1
        print(json.dumps({
            "findings": [f.__dict__ for f in shown],
            "unsuppressed": len(unsuppressed),
            "suppressed": len(findings) - len(unsuppressed),
            "rules": sorted(args.rules) if args.rules
            else sorted(ALL_RULES),
            "by_rule": by_rule,
            "targets": [os.path.relpath(t) if not
                        os.path.relpath(t).startswith("..") else t
                        for t in targets],
            "cache": cache_stats,
            # the analyzer's own cost accounting: per-rule elapsed
            # seconds (per-file rules summed over files; "scan" is the
            # cached fact extraction) + total wall — CI pins that the
            # H14-H16 dataflow closure stays cheap enough for the
            # --changed-only fast loop
            "timing": rule_stats,
        }, indent=2))
    else:
        out = format_findings(findings,
                              show_suppressed=args.show_suppressed,
                              fmt="text")
        if out:
            print(out)
        suppressed = len(findings) - len(unsuppressed)
        print(f"sparkdl-lint: {len(unsuppressed)} finding(s), "
              f"{suppressed} suppressed", file=sys.stderr)
    return 1 if unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
