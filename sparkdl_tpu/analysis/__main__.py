"""CLI: ``python -m sparkdl_tpu.analysis [paths...]``.

Exit 0 when every finding is suppressed (inline annotation or
allowlist), 1 when any unsuppressed finding remains, 2 on usage
errors — the contract tools/ci.sh's static-analysis gate keys off.

With no paths the default target set is the installed package PLUS the
repo's ``tools/`` and ``examples/`` trees when they sit next to it —
the CLI scripts hold no locks but they do call the hot paths, and a
deadlock witness that starts in an example is still a deadlock.

``--json`` emits the machine schema CI gates: findings, counts,
per-rule totals, and the per-file cache's hit/miss accounting (the
cache is on by default — ``SPARKDL_TPU_LINT_CACHE`` names the file,
``--no-cache`` disables it).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from sparkdl_tpu.analysis.cache import default_cache_path
from sparkdl_tpu.analysis.findings import format_findings
from sparkdl_tpu.analysis.rules import rule_doc
from sparkdl_tpu.analysis.walker import ALL_RULES, analyze_paths


def _package_dir() -> str:
    import sparkdl_tpu
    return os.path.dirname(os.path.abspath(sparkdl_tpu.__file__))


def _default_targets() -> list:
    """The installed package, plus the repo's tools/ and examples/
    when present — `python -m sparkdl_tpu.analysis` with no args lints
    everything the repo actually ships and drives. The extra dirs are
    only taken when the package parent IS the repo checkout (marker:
    docs/OBSERVABILITY.md) — a site-packages install must not sweep a
    neighboring distribution's stray tools/ directory."""
    pkg = _package_dir()
    targets = [pkg]
    root = os.path.dirname(pkg)
    if os.path.isfile(os.path.join(root, "docs", "OBSERVABILITY.md")):
        for extra in ("tools", "examples"):
            d = os.path.join(root, extra)
            if os.path.isdir(d):
                targets.append(d)
    return targets


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sparkdl_tpu.analysis",
        description="sparkdl-lint: enforce the hot-path invariants "
                    "(H1 transfers, H2 retrace, H3 locks, H4 quiesce, "
                    "H5 clocks, H6 cardinality) plus the whole-program "
                    "concurrency passes (H7 lock-order cycles, H8 "
                    "blocking under a lock, H9 docs contract drift). "
                    "Rule reference: docs/LINT.md")
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: the sparkdl_tpu "
             "package + the repo's tools/ and examples/)")
    parser.add_argument(
        "--rule", action="append", choices=sorted(ALL_RULES),
        dest="rules",
        help="run only this rule (repeatable; default: all)")
    parser.add_argument(
        "--format", choices=("text", "json"), default="text")
    parser.add_argument(
        "--json", action="store_true",
        help="shorthand for --format json (the CI gate's schema)")
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="also print suppressed findings with their justifications")
    parser.add_argument(
        "--no-cache", action="store_true",
        help="disable the per-file mtime+hash result cache")
    parser.add_argument(
        "--cache", metavar="PATH", default=None,
        help="cache file (default: SPARKDL_TPU_LINT_CACHE or a "
             "per-user temp file)")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalogue and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in sorted(ALL_RULES):
            print(f"{rule}: {rule_doc(rule)}")
        return 0

    targets = args.paths or _default_targets()
    for t in targets:
        if not os.path.exists(t):
            print(f"sparkdl-lint: no such path: {t}", file=sys.stderr)
            return 2

    cache_path = None if args.no_cache else \
        (args.cache or default_cache_path())
    cache_stats: dict = {}
    findings = analyze_paths(targets, rules=args.rules,
                             cache_path=cache_path,
                             cache_stats=cache_stats)
    unsuppressed = [f for f in findings if not f.suppressed]
    fmt = "json" if args.json else args.format
    if fmt == "json":
        shown = [f for f in findings
                 if args.show_suppressed or not f.suppressed]
        by_rule: dict = {}
        for f in findings:
            entry = by_rule.setdefault(
                f.rule, {"unsuppressed": 0, "suppressed": 0})
            entry["suppressed" if f.suppressed else "unsuppressed"] += 1
        print(json.dumps({
            "findings": [f.__dict__ for f in shown],
            "unsuppressed": len(unsuppressed),
            "suppressed": len(findings) - len(unsuppressed),
            "rules": sorted(args.rules) if args.rules
            else sorted(ALL_RULES),
            "by_rule": by_rule,
            "targets": [os.path.relpath(t) if not
                        os.path.relpath(t).startswith("..") else t
                        for t in targets],
            "cache": cache_stats,
        }, indent=2))
    else:
        out = format_findings(findings,
                              show_suppressed=args.show_suppressed,
                              fmt="text")
        if out:
            print(out)
        suppressed = len(findings) - len(unsuppressed)
        print(f"sparkdl-lint: {len(unsuppressed)} finding(s), "
              f"{suppressed} suppressed", file=sys.stderr)
    return 1 if unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
