"""CLI: ``python -m sparkdl_tpu.analysis [paths...]``.

Exit 0 when every finding is suppressed (inline annotation or
allowlist), 1 when any unsuppressed finding remains, 2 on usage
errors — the contract tools/ci.sh's static-analysis gate keys off.
"""

from __future__ import annotations

import argparse
import os
import sys

from sparkdl_tpu.analysis.findings import format_findings
from sparkdl_tpu.analysis.rules import RULES, rule_doc
from sparkdl_tpu.analysis.walker import analyze_paths


def _default_target() -> str:
    """The installed package itself — `python -m sparkdl_tpu.analysis`
    with no args lints the code that is actually importable."""
    import sparkdl_tpu
    return os.path.dirname(os.path.abspath(sparkdl_tpu.__file__))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sparkdl_tpu.analysis",
        description="sparkdl-lint: enforce the hot-path invariants "
                    "(H1 transfers, H2 retrace, H3 locks, H4 quiesce). "
                    "Rule reference: docs/LINT.md")
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: the sparkdl_tpu "
             "package)")
    parser.add_argument(
        "--rule", action="append", choices=sorted(RULES), dest="rules",
        help="run only this rule (repeatable; default: all)")
    parser.add_argument(
        "--format", choices=("text", "json"), default="text")
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="also print suppressed findings with their justifications")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalogue and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in sorted(RULES):
            print(f"{rule}: {rule_doc(rule)}")
        return 0

    targets = args.paths or [_default_target()]
    for t in targets:
        if not os.path.exists(t):
            print(f"sparkdl-lint: no such path: {t}", file=sys.stderr)
            return 2

    findings = analyze_paths(targets, rules=args.rules)
    unsuppressed = [f for f in findings if not f.suppressed]
    out = format_findings(findings,
                          show_suppressed=args.show_suppressed,
                          fmt=args.format)
    if out:
        print(out)
    if args.format == "text":
        suppressed = len(findings) - len(unsuppressed)
        print(f"sparkdl-lint: {len(unsuppressed)} finding(s), "
              f"{suppressed} suppressed", file=sys.stderr)
    return 1 if unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
