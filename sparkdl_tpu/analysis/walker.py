"""File walking + rule orchestration for sparkdl-lint.

Two layers of rules run per invocation:

* **per-file** (H1–H6 + H12, :data:`~sparkdl_tpu.analysis.rules.RULES`)
  — one AST pass each over each module; results (and the
  callgraph/lock/effect facts + published-surface extraction the
  program layer needs) are cacheable per file by mtime+hash
  (:mod:`.cache`).
* **whole-program** (H7/H8 over the
  :class:`~sparkdl_tpu.analysis.callgraph.CallGraph`, H10/H11 over
  the effect facts riding it, H9 over the merged published surface vs
  the repo docs) — always re-run, over the cheap per-file facts;
  their verdicts depend on every analyzed module at once.

Suppression is uniform: every finding — per-file or program — that
lands on a line of an analyzed python file honors the inline
``# sparkdl-lint: allow[..] -- why`` grammar, and the allowlist
applies everywhere. Doc-side H9 findings (a stale table row) anchor in
the ``.md`` file and therefore only suppress via the allowlist.
"""

from __future__ import annotations

import ast
import os
import time
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

from sparkdl_tpu.analysis import contracts
from sparkdl_tpu.analysis.cache import ResultCache
from sparkdl_tpu.analysis.callgraph import (
    CallGraph,
    ModuleFacts,
    scan_module,
)
from sparkdl_tpu.analysis.findings import Finding
from sparkdl_tpu.analysis.program import PROGRAM_RULES
from sparkdl_tpu.analysis.rules import RULES
from sparkdl_tpu.analysis.suppress import (
    AllowEntry,
    SuppressionIndex,
    allowlisted,
)

_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules",
              "artifacts"}

#: every rule the CLI's --rule accepts (per-file + whole-program)
ALL_RULES = tuple(sorted(list(RULES) + list(PROGRAM_RULES) + ["H9"]))


def iter_python_files(target: str) -> Iterator[str]:
    """Yield ``.py`` files under ``target`` (or ``target`` itself),
    skipping caches/VCS dirs, in sorted order for stable output."""
    if os.path.isfile(target):
        yield target
        return
    for dirpath, dirnames, filenames in os.walk(target):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in _SKIP_DIRS
                             and not d.startswith("."))
        for name in sorted(filenames):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def _file_findings(tree: ast.AST, path: str, wanted: List[str],
                   timing: Optional[Dict[str, float]] = None
                   ) -> List[Finding]:
    findings: List[Finding] = []
    for rule in wanted:
        if rule in RULES:
            t0 = time.perf_counter()
            findings.extend(RULES[rule](tree, path))
            if timing is not None:
                timing[rule] = timing.get(rule, 0.0) + \
                    (time.perf_counter() - t0)
    return findings


def _apply_suppressions(findings: List[Finding],
                        indexes: Dict[str, SuppressionIndex],
                        allowlist) -> None:
    for f in findings:
        f.suppressed = False
        f.suppression = ""
        index = indexes.get(f.path)
        if index is not None:
            inline = index.lookup(f.rule, f.line)
            if inline is not None:
                f.suppressed = True
                f.suppression = f"inline -- {inline}"
                continue
        listed = allowlisted(f.rule, f.path, f.qualname, allowlist)
        if listed is not None:
            f.suppressed = True
            f.suppression = listed


def analyze_source(source: str, path: str,
                   rules: Optional[Iterable[str]] = None,
                   allowlist: Optional[Dict[str, Tuple[AllowEntry, ...]]]
                   = None) -> List[Finding]:
    """Run the PER-FILE rule set over one module's source (plus the
    program rules when the module alone exhibits the hazard — a
    single-module lock cycle or blocking hold is still whole-program
    shaped, just with a one-module program). Findings covered by an
    inline ``# sparkdl-lint: allow[..]`` annotation or the allowlist
    come back with ``suppressed=True`` and the justification attached
    — they are reported, not hidden."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(
            rule="PARSE", path=path, line=e.lineno or 1,
            col=(e.offset or 1) - 1,
            message=f"file does not parse: {e.msg} (sparkdl-lint "
                    "cannot vouch for a module it cannot read)")]
    wanted = ([r.upper() for r in rules] if rules is not None
              else list(ALL_RULES))
    findings = _file_findings(tree, path, wanted)
    if any(r in PROGRAM_RULES for r in wanted):
        graph = CallGraph([scan_module(tree, path)])
        for rule in wanted:
            if rule in PROGRAM_RULES:
                findings.extend(PROGRAM_RULES[rule](graph))
    _apply_suppressions(findings, {path: SuppressionIndex(source)},
                        allowlist)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def analyze_paths(targets: Sequence[str],
                  rules: Optional[Iterable[str]] = None,
                  allowlist: Optional[Dict[str, Tuple[AllowEntry, ...]]]
                  = None,
                  cache_path: Optional[str] = None,
                  docs_root: Optional[str] = None,
                  cache_stats: Optional[dict] = None,
                  rule_stats: Optional[dict] = None) -> List[Finding]:
    """Analyze every python file under each target path: per-file
    rules (cached by mtime+hash when ``cache_path`` is given), then
    the whole-program passes (H7/H8 lock analysis over the combined
    call graph; H9 contract drift against the repo docs when a
    ``docs/`` tree governs the targets). ``cache_stats`` (a dict, when
    given) receives the cache hit/miss accounting for CI gating;
    ``rule_stats`` receives the analyzer's own cost accounting —
    ``per_rule_s`` (elapsed seconds per rule, per-file rules summed
    across files; ``scan`` is the fact-extraction pass the program
    rules run on) and ``total_s`` — so CI can pin that the dataflow
    closure does not blow up the fast loop (cache hits skip the scan
    entirely: cached facts replay, nothing recomputes)."""
    t_start = time.perf_counter()
    timing: Dict[str, float] = {}
    wanted = ([r.upper() for r in rules] if rules is not None
              else list(ALL_RULES))
    rules_key = ",".join(sorted(r for r in wanted if r in RULES))
    cache = ResultCache(cache_path)

    findings: List[Finding] = []
    indexes: Dict[str, SuppressionIndex] = {}
    modules: List[ModuleFacts] = []
    surface = contracts.CodeSurface()
    file_paths: List[str] = []

    for target in targets:
        for path in iter_python_files(target):
            with open(path, encoding="utf-8") as f:
                source = f.read()
            # report paths relative to the invocation dir when possible
            # (editor-clickable, stable across machines)
            rel = os.path.relpath(path)
            display = path if rel.startswith("..") else rel
            file_paths.append(display)
            indexes[display] = SuppressionIndex(source)
            cached = cache.lookup(display, path, source, rules_key)
            if cached is not None:
                file_f, facts, file_surface = cached
                findings.extend(file_f)
                modules.append(facts)
                surface.merge(file_surface)
                continue
            try:
                tree = ast.parse(source, filename=path)
            except SyntaxError as e:
                findings.append(Finding(
                    rule="PARSE", path=display, line=e.lineno or 1,
                    col=(e.offset or 1) - 1,
                    message=f"file does not parse: {e.msg} "
                            "(sparkdl-lint cannot vouch for a module "
                            "it cannot read)"))
                continue
            file_f = _file_findings(tree, display, wanted, timing)
            t0 = time.perf_counter()
            facts = scan_module(tree, display)
            file_surface = contracts.extract_file_surface(display, tree)
            timing["scan"] = timing.get("scan", 0.0) + \
                (time.perf_counter() - t0)
            findings.extend(file_f)
            modules.append(facts)
            surface.merge(file_surface)
            cache.store(display, path, source, rules_key, file_f,
                        facts, file_surface)

    if any(r in PROGRAM_RULES for r in wanted) and modules:
        graph = CallGraph(modules)
        if any(r in ("H14", "H15", "H16") for r in wanted):
            # build the shared device-dataflow state (replay rounds +
            # hot-path closure) under its OWN timing key — otherwise
            # whichever consumer runs first (H14, alphabetically)
            # books the whole construction and H15/H16 read as free
            from sparkdl_tpu.analysis.dataflow import _flow_state
            t0 = time.perf_counter()
            _flow_state(graph)
            timing["dataflow-closure"] = timing.get(
                "dataflow-closure", 0.0) + (time.perf_counter() - t0)
        if any(r in ("H17", "H18", "H19") for r in wanted):
            # same discipline for the thread topology + guarded-by
            # model H17–H19 share: built once, timed under its own
            # key (sorted(PROGRAM_RULES) would book it to H17)
            from sparkdl_tpu.analysis.races import _guard_model
            from sparkdl_tpu.analysis.threads import thread_topology
            t0 = time.perf_counter()
            thread_topology(graph)
            _guard_model(graph)
            timing["threads-topology"] = timing.get(
                "threads-topology", 0.0) + (time.perf_counter() - t0)
        for rule in sorted(PROGRAM_RULES):
            if rule in wanted:
                t0 = time.perf_counter()
                findings.extend(PROGRAM_RULES[rule](graph))
                timing[rule] = timing.get(rule, 0.0) + \
                    (time.perf_counter() - t0)
    if "H9" in wanted and file_paths:
        t0 = time.perf_counter()
        findings.extend(contracts.check_surface(
            surface, file_paths, docs_root=docs_root))
        timing["H9"] = timing.get("H9", 0.0) + \
            (time.perf_counter() - t0)

    _apply_suppressions(findings, indexes, allowlist)
    cache.save()
    if cache_stats is not None:
        cache_stats.update(cache.stats())
    if rule_stats is not None:
        rule_stats["per_rule_s"] = {
            k: round(v, 6) for k, v in sorted(timing.items())}
        rule_stats["total_s"] = round(
            time.perf_counter() - t_start, 6)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
