"""File walking + rule orchestration for sparkdl-lint."""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

from sparkdl_tpu.analysis.findings import Finding
from sparkdl_tpu.analysis.rules import RULES
from sparkdl_tpu.analysis.suppress import (
    AllowEntry,
    SuppressionIndex,
    allowlisted,
)

_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules",
              "artifacts"}


def iter_python_files(target: str) -> Iterator[str]:
    """Yield ``.py`` files under ``target`` (or ``target`` itself),
    skipping caches/VCS dirs, in sorted order for stable output."""
    if os.path.isfile(target):
        yield target
        return
    for dirpath, dirnames, filenames in os.walk(target):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in _SKIP_DIRS
                             and not d.startswith("."))
        for name in sorted(filenames):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def analyze_source(source: str, path: str,
                   rules: Optional[Iterable[str]] = None,
                   allowlist: Optional[Dict[str, Tuple[AllowEntry, ...]]]
                   = None) -> List[Finding]:
    """Run the rule set over one module's source. Findings covered by
    an inline ``# sparkdl-lint: allow[..]`` annotation or the
    allowlist come back with ``suppressed=True`` and the justification
    attached — they are reported, not hidden."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(
            rule="PARSE", path=path, line=e.lineno or 1,
            col=(e.offset or 1) - 1,
            message=f"file does not parse: {e.msg} (sparkdl-lint "
                    "cannot vouch for a module it cannot read)")]
    wanted = ([r.upper() for r in rules] if rules is not None
              else list(RULES))
    findings: List[Finding] = []
    for rule in wanted:
        findings.extend(RULES[rule](tree, path))
    index = SuppressionIndex(source)
    for f in findings:
        inline = index.lookup(f.rule, f.line)
        if inline is not None:
            f.suppressed = True
            f.suppression = f"inline -- {inline}"
            continue
        listed = allowlisted(f.rule, f.path, f.qualname, allowlist)
        if listed is not None:
            f.suppressed = True
            f.suppression = listed
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def analyze_paths(targets: Sequence[str],
                  rules: Optional[Iterable[str]] = None,
                  allowlist: Optional[Dict[str, Tuple[AllowEntry, ...]]]
                  = None) -> List[Finding]:
    """Analyze every python file under each target path."""
    findings: List[Finding] = []
    for target in targets:
        for path in iter_python_files(target):
            with open(path, encoding="utf-8") as f:
                source = f.read()
            # report paths relative to the invocation dir when possible
            # (editor-clickable, stable across machines)
            rel = os.path.relpath(path)
            display = path if rel.startswith("..") else rel
            findings.extend(analyze_source(source, display,
                                           rules=rules,
                                           allowlist=allowlist))
    return findings
