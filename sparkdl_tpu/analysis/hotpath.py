"""Hot-path classification for the H14–H16 throughput rules.

"Hot" is not a vibe here — it is a reachability fact over the PR-8
call graph. The roots are the loops the repo already treats as its
steady-state inner loops, identified by the same instrumentation the
runtime uses: any function that opens a stall-watchdog activity
window or beats it (``obs.watchdog.watch`` / ``obs.watchdog.pulse``
call sites — the runner dispatch/drain state machine, the serve
dispatcher, and every estimator epoch/step loop already do), plus a
short explicit table for the engine's consumer-thread stream/re-chunk
path and the runner entry points, which are hot by construction but
beat the watchdog one frame further down.

Everything transitively reachable from a root through RESOLVED call
edges (the same ``self.m`` / bare-name / ``mod.f`` / unique-method
contract ``may_block`` uses, plus lexically-nested defs of the
caller) is hot, and every hot function carries a recorded witness
chain back to its root so an H14/H16 finding can print module-by-
module WHY the analyzer considers the site hot — a throughput verdict
an operator cannot retrace is a number, not a diagnosis.

Cold by construction: ``tools/`` and ``examples/`` CLIs (they *call*
the hot paths — hotness flows down the call graph from the roots, not
up into callers), config/constructor paths, and anything only
reachable through an edge the resolver refuses (ambiguous methods
resolve to "no edge": a guessed hot edge would manufacture false
throughput findings, while a missed one costs recall the fixtures
pin).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

# NOTE: no import of callgraph here — callgraph imports dataflow,
# which imports this module; the CallGraph is always passed in (the
# same no-cycle discipline effects.py keeps).

#: import sources whose call marks the calling function as a hot-loop
#: root (the watchdog contract: watch() opens an activity window
#: around a hot loop, pulse() beats it per unit of work)
WATCHDOG_MARKERS = ("obs.watchdog.watch", "obs.watchdog.pulse")

#: (module suffix, qualname, label): hot roots that do not beat the
#: watchdog themselves but ARE the steady-state inner loop — the
#: engine's consumer-thread stream/re-chunk path and the runner run()
#: entries (their dispatch_chunks callee beats the watchdog one frame
#: down; the entry's own body is equally per-partition hot)
EXTRA_HOT_ROOTS: Tuple[Tuple[str, str, str], ...] = (
    ("data.engine", "LocalEngine._stream_rechunk",
     "the engine stream/re-chunk path"),
    ("data.engine", "LocalEngine._stream_plain",
     "the engine stream/re-chunk path"),
    ("data.engine", "LocalEngine._run_once",
     "the engine per-partition path"),
    ("runtime.runner", "BatchRunner.run",
     "the runner dispatch entry"),
    ("runtime.runner", "SlabSink.write",
     "the runner drain path (`write` is ambiguous across classes, so "
     "the resolver refuses the drain_bounded edge)"),
    ("parallel.inference", "ShardedBatchRunner.run",
     "the sharded runner dispatch entry"),
)

#: default label for watchdog-marked roots
WATCHDOG_LABEL = "opens/beats a stall-watchdog window (a hot loop)"


def _short(key: str) -> str:
    """`module::Qual` → the human `module:Qual` form, package prefix
    trimmed (mirrors CallGraph.short without importing callgraph)."""
    mod, _, qual = key.partition("::")
    mod = mod[len("sparkdl_tpu."):] if mod.startswith("sparkdl_tpu.") \
        else mod
    return f"{mod}:{qual}" if qual else mod


def _resolve(graph, caller, call) -> Optional[str]:
    """graph.resolve plus the lexical nested-def rule: a bare name
    that matches a def nested inside the caller binds there first
    (the estimator's ``place()`` / ``run_step()`` idiom)."""
    if call.kind == "name":
        nested = f"{caller.module}::{caller.qualname}.{call.name}"
        if nested in graph.functions:
            return nested
    return graph.resolve(caller, call)


class HotPaths:
    """The hot set + per-function witness chains over one CallGraph.

    ``flows`` maps function key → the dataflow layer's per-function
    facts (``dataflow.DeviceFlow``), whose ``hot_root`` flag records
    the scan-time watchdog-marker detection.
    """

    def __init__(self, graph, flows: Dict[str, object]):
        self.graph = graph
        #: key -> witness chain (keys, root first, self last)
        self.chains: Dict[str, Tuple[str, ...]] = {}
        #: root key -> human label (why it is a root)
        self.roots: Dict[str, str] = {}
        for key, flow in flows.items():
            if getattr(flow, "hot_root", False) and \
                    key in graph.functions:
                self.roots[key] = (getattr(flow, "root_label", "")
                                   or WATCHDOG_LABEL)
        for key, f in graph.functions.items():
            for suffix, qual, label in EXTRA_HOT_ROOTS:
                if f.qualname == qual and (
                        f.module == suffix
                        or f.module.endswith("." + suffix)):
                    self.roots.setdefault(key, label)
        self._close()

    def _close(self) -> None:
        """BFS the resolved call edges from every root: hotness flows
        DOWN the call graph (a hot loop makes its callees hot; calling
        a hot function does not heat the caller)."""
        work = []
        for root in sorted(self.roots):
            self.chains[root] = (root,)
            work.append(root)
        while work:
            key = work.pop(0)
            f = self.graph.functions.get(key)
            if f is None:
                continue
            for call in f.calls:
                target = _resolve(self.graph, f, call)
                if target is None or target in self.chains:
                    continue
                self.chains[target] = self.chains[key] + (target,)
                work.append(target)

    def is_hot(self, key: str) -> bool:
        return key in self.chains

    def chain(self, key: str) -> Tuple[str, ...]:
        return self.chains.get(key, ())

    def why(self, key: str) -> str:
        """The printable module-by-module hot witness for ``key``:
        ``root (label) -> hop -> ... -> key``."""
        chain = self.chains.get(key)
        if not chain:
            return ""
        root = chain[0]
        label = self.roots.get(root, WATCHDOG_LABEL)
        path = " -> ".join(_short(k) for k in chain)
        return f"{path} (root {_short(root)}: {label})"
