"""Lock-scope inference for the whole-program rules (H7/H8).

Per function, this module answers three questions the per-file rules
(H1–H6) cannot:

* **which locks does this function acquire**, and which locks were
  already held at each acquire site (the raw material of the
  acquired-while-holding graph H7 builds);
* **which statements run while a lock is held** — `with self._lock:`
  blocks exactly (lexical nesting), `acquire()`..`release()` pairs by
  source-line region (a deliberate heuristic: from the acquire
  statement to the first later `release()` of the same lock in the
  same function, else function end — the repo's own acquire/release
  idioms are all function-scoped);
* **which calls may block directly** — the device drain
  (`jax.device_get` / `timed_device_get` / `.block_until_ready()`),
  `Condition`/`Event.wait`, `queue.get`, `time.sleep`, file/socket
  I/O, thread joins — classified lexically by the same name rules the
  per-file passes use.

Lock **identity** is class- or module-scoped, not instance-scoped:
``self._lock`` inside ``ModelSession`` becomes
``sparkdl_tpu.serve.server::ModelSession._lock``. Two instances of one
class therefore share an identity — a deliberate over-approximation
(the repo's lock-holding classes are singletons or per-pipeline
objects, and a false cycle is cheap to suppress inline, which is
itself documentation). A ``threading.Condition(self._lock)`` aliases
to the mutex it wraps, so ``with self._cond`` and ``with self._lock``
name ONE lock. ``collective_launch(...)`` — the process-wide launch
lock from parallel/mesh.py — canonicalizes to the single global id
``collective_launch`` wherever it is imported from.

Non-blocking try-acquires (``acquire(blocking=False)``) are neither
acquire events nor block events: a try-lock cannot deadlock (it fails
instead of waiting), which conveniently models the runner's
``checkout_staging`` fallback and the autotune ``poll()`` discipline
as the non-hazards they are.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock",
               "threading.Condition", "Condition",
               "threading.Semaphore", "Semaphore",
               "threading.BoundedSemaphore"}

#: module-level names accepted as locks even without a visible ctor
#: (imported from a module outside the analyzed set)
_LOCKISH_NAME = re.compile(r"lock|mutex|cond|sem", re.IGNORECASE)

#: THE process-wide collective launch lock (parallel/mesh.py): every
#: spelling (`collective_launch(mesh)`, an imported alias, the
#: `_CollectiveLaunch` wrapper) canonicalizes to one global identity —
#: the PR-2 deadlock class is about this one ordering point.
COLLECTIVE_LOCK_ID = "collective_launch"


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# ---------------------------------------------------------------------------
# events


@dataclass
class LockEvent:
    """One lock acquisition: ``held`` is what was already held."""

    lock: str
    line: int
    held: Tuple[str, ...]
    blocking: bool = True      # acquire(blocking=False) -> False


@dataclass
class BlockEvent:
    """One direct may-block operation."""

    what: str                  # human-readable op, e.g. "time.sleep()"
    kind: str                  # "sleep" | "wait" | "device" | "io" | ...
    line: int
    held: Tuple[str, ...]


@dataclass
class CallEvent:
    """One call site, with enough shape for cross-module resolution."""

    kind: str                  # "self" | "name" | "dotted" | "method"
    name: str                  # method/function name (last segment)
    display: str               # what the source says, for messages
    line: int
    held: Tuple[str, ...]
    qualifier: str = ""        # "self" kind: enclosing class;
    #                            "dotted": the leading name


@dataclass
class FunctionFacts:
    """The serializable per-function summary the program rules run on."""

    key: str                   # "module::Qual"
    module: str
    path: str
    qualname: str
    line: int
    acquires: List[LockEvent] = field(default_factory=list)
    blocks: List[BlockEvent] = field(default_factory=list)
    calls: List[CallEvent] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "key": self.key, "module": self.module, "path": self.path,
            "qualname": self.qualname, "line": self.line,
            "acquires": [[e.lock, e.line, list(e.held), e.blocking]
                         for e in self.acquires],
            "blocks": [[e.what, e.kind, e.line, list(e.held)]
                       for e in self.blocks],
            "calls": [[e.kind, e.name, e.display, e.line,
                       list(e.held), e.qualifier] for e in self.calls],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FunctionFacts":
        f = cls(key=d["key"], module=d["module"], path=d["path"],
                qualname=d["qualname"], line=d["line"])
        f.acquires = [LockEvent(a[0], a[1], tuple(a[2]), a[3])
                      for a in d["acquires"]]
        f.blocks = [BlockEvent(b[0], b[1], b[2], tuple(b[3]))
                    for b in d["blocks"]]
        f.calls = [CallEvent(c[0], c[1], c[2], c[3], tuple(c[4]), c[5])
                   for c in d["calls"]]
        return f


# ---------------------------------------------------------------------------
# per-module lock discovery


@dataclass
class ModuleLocks:
    """What the module pre-pass learned about lock identity."""

    module: str
    #: class -> instance lock attrs (``self.X = threading.Lock()``)
    class_locks: Dict[str, Set[str]] = field(default_factory=dict)
    #: class -> {alias attr -> canonical attr}; e.g. a
    #: ``threading.Condition(self._lock)`` makes ``_cond`` -> ``_lock``
    aliases: Dict[str, Dict[str, str]] = field(default_factory=dict)
    #: module-level lock names (``_LOCK = threading.Lock()``)
    module_locks: Set[str] = field(default_factory=set)

    def canonical_attr(self, cls: str, attr: str) -> str:
        return self.aliases.get(cls, {}).get(attr, attr)


def discover_locks(tree: ast.Module, module: str) -> ModuleLocks:
    ml = ModuleLocks(module=module)
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call) and \
                _dotted(node.value.func) in _LOCK_CTORS:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    ml.module_locks.add(tgt.id)
        if isinstance(node, ast.ClassDef):
            locks: Set[str] = set()
            aliases: Dict[str, str] = {}
            # class-body locks are per-CLASS state and behave exactly
            # like module locks for ordering purposes
            for item in node.body:
                if isinstance(item, ast.Assign) and isinstance(
                        item.value, ast.Call) and \
                        _dotted(item.value.func) in _LOCK_CTORS:
                    for tgt in item.targets:
                        if isinstance(tgt, ast.Name):
                            locks.add(tgt.id)
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Assign):
                    continue
                val = sub.value
                if not (isinstance(val, ast.Call)
                        and _dotted(val.func) in _LOCK_CTORS):
                    continue
                for tgt in sub.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        locks.add(tgt.attr)
                        # Condition(self._lock) wraps an EXISTING
                        # mutex: the alias and the mutex are one lock
                        if val.args:
                            inner = val.args[0]
                            if (isinstance(inner, ast.Attribute)
                                    and isinstance(inner.value, ast.Name)
                                    and inner.value.id == "self"):
                                aliases[tgt.attr] = inner.attr
            if locks:
                ml.class_locks[node.name] = locks
            if aliases:
                ml.aliases[node.name] = aliases
    return ml


# ---------------------------------------------------------------------------
# blocking-call classification

_BLOCK_DOTTED = {
    "time.sleep": ("time.sleep()", "sleep"),
    "sleep": ("sleep()", "sleep"),
    "jax.device_get": ("jax.device_get()", "device"),
    "timed_device_get": ("timed_device_get()", "device"),
    "input": ("input()", "io"),
    "socket.create_connection": ("socket connect", "io"),
    "urllib.request.urlopen": ("urlopen()", "io"),
    "subprocess.run": ("subprocess.run()", "io"),
    "subprocess.check_output": ("subprocess.check_output()", "io"),
    "subprocess.check_call": ("subprocess.check_call()", "io"),
}
_BLOCK_ATTRS = {
    "block_until_ready": ("`.block_until_ready()` device sync",
                          "device"),
    "timed_device_get": ("timed_device_get()", "device"),
    "recv": ("socket `.recv()`", "io"),
    "accept": ("socket `.accept()`", "io"),
    "communicate": ("`.communicate()` on a subprocess", "io"),
}
_QUEUEISH = re.compile(r"queue|^_?q$", re.IGNORECASE)
_THREADISH = re.compile(r"thread|worker|proc", re.IGNORECASE)
_FUTUREISH = re.compile(r"fut", re.IGNORECASE)


def classify_blocking(call: ast.Call) -> Optional[Tuple[str, str]]:
    """(description, kind) when this call can block the thread."""
    name = _dotted(call.func)
    if name in _BLOCK_DOTTED:
        return _BLOCK_DOTTED[name]
    if name == "open" or (name and name.endswith(".open")):
        return ("`open()` file I/O", "io")
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    if attr in _BLOCK_ATTRS:
        return _BLOCK_ATTRS[attr]
    recv = call.func.value
    recv_name = (_dotted(recv) or "").rsplit(".", 1)[-1]
    if attr == "wait":
        return (f"`{recv_name or '<expr>'}.wait()` "
                "(Condition/Event wait)", "wait")
    if attr == "get" and _QUEUEISH.search(recv_name or ""):
        return (f"`{recv_name}.get()` queue wait", "wait")
    if attr == "join" and _THREADISH.search(recv_name or ""):
        return (f"`{recv_name}.join()` thread join", "wait")
    if attr == "result" and _FUTUREISH.search(recv_name or ""):
        return (f"`{recv_name}.result()` future wait", "wait")
    return None


# ---------------------------------------------------------------------------
# the per-function scan


class FunctionScanner:
    """Walks ONE function body tracking the held-lock set, emitting
    acquire/block/call events. ``with`` items scope lexically;
    ``acquire()``/``release()`` pairs are resolved afterwards by
    source-line region."""

    def __init__(self, module: str, path: str, cls: Optional[str],
                 qualname: str, locks: ModuleLocks,
                 imports: Dict[str, str]):
        self.module = module
        self.path = path
        self.cls = cls
        self.qualname = qualname
        self.locks = locks
        self.imports = imports
        self.acquires: List[LockEvent] = []
        self.blocks: List[BlockEvent] = []
        self.calls: List[CallEvent] = []
        #: flat acquire()/release() regions: lock id -> [(lo, hi)]
        self._flat: List[Tuple[str, int, int]] = []

    # -- lock identity -------------------------------------------------------

    def lock_id(self, expr: ast.AST) -> Optional[str]:
        """The canonical lock identity of ``expr``, or None when it is
        not recognizably a lock."""
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name) and expr.value.id == "self":
            attr = expr.attr
            cls = self.cls or ""
            attr = self.locks.canonical_attr(cls, attr)
            if cls and attr in self.locks.class_locks.get(cls, ()):
                return f"{self.module}::{cls}.{attr}"
            # unknown self attr: accept lock-shaped names (a base
            # class may own the ctor)
            if _LOCKISH_NAME.search(attr):
                return f"{self.module}::{cls or '?'}.{attr}"
            return None
        name = _dotted(expr)
        if name is None:
            return None
        if name in self.locks.module_locks:
            return f"{self.module}::{name}"
        if "." not in name:
            src = self.imports.get(name)
            if src is not None:
                # imported module-level name: identity follows the
                # DEFINING module — but whether it IS a lock is only
                # knowable there, so this is a CANDIDATE ("?" prefix)
                # the CallGraph confirms against that module's lock
                # table (or by lock-shaped name when the module is
                # outside the analyzed set) and drops otherwise
                mod, _, attr = src.rpartition(".")
                if mod:
                    return f"?{mod}::{attr}"
                return (f"{src}::{name}"
                        if _LOCKISH_NAME.search(name) else None)
            if _LOCKISH_NAME.search(name):
                # a parameter or local named like a lock (the
                # checkout_staging idiom): function-scoped identity
                return f"{self.module}::{self.qualname}.<{name}>"
        return None

    def _with_item_lock(self, ctx: ast.AST) -> Optional[str]:
        if isinstance(ctx, ast.Call):
            name = _dotted(ctx.func) or ""
            if name.split(".")[-1] == "collective_launch":
                return COLLECTIVE_LOCK_ID
            return None
        return self.lock_id(ctx)

    # -- the walk ------------------------------------------------------------

    def scan(self, fn: ast.AST) -> None:
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        self._walk(body, ())
        self._apply_flat_regions()

    def _walk(self, stmts: List[ast.stmt], held: Tuple[str, ...]):
        for stmt in stmts:
            self._visit_stmt(stmt, held)

    def _visit_stmt(self, stmt: ast.stmt, held: Tuple[str, ...]):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return      # nested defs are scanned as their own functions
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            new = tuple(held)
            for item in stmt.items:
                lock = self._with_item_lock(item.context_expr)
                self._scan_expr(item.context_expr, held)
                if lock is not None and lock not in new:
                    self.acquires.append(LockEvent(
                        lock, stmt.lineno, tuple(new)))
                    new = new + (lock,)
            self._walk(stmt.body, new)
            return
        # acquire()/release() statements: flat regions
        expr = stmt.value if isinstance(stmt, ast.Expr) else None
        asn = stmt.value if isinstance(stmt, ast.Assign) else None
        for val in (expr, asn):
            if isinstance(val, ast.Call) and isinstance(
                    val.func, ast.Attribute):
                if val.func.attr == "acquire":
                    lock = self.lock_id(val.func.value)
                    if lock is not None:
                        blocking = not self._is_try_acquire(val)
                        if blocking:
                            self.acquires.append(LockEvent(
                                lock, val.lineno, held))
                            self._flat.append(
                                (lock, val.lineno, 1 << 30))
                        break
                if val.func.attr == "release":
                    lock = self.lock_id(val.func.value)
                    if lock is not None:
                        for i, (lk, lo, hi) in enumerate(self._flat):
                            if lk == lock and hi == 1 << 30 \
                                    and lo < val.lineno:
                                self._flat[i] = (lk, lo, val.lineno)
                                break
                        break
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                self._visit_stmt(child, held)
            elif isinstance(child, ast.expr):
                self._scan_expr(child, held)
            elif isinstance(child, ast.ExceptHandler):
                self._walk(child.body, held)
            elif isinstance(child, (ast.arguments, ast.keyword)):
                self._scan_expr(child, held)  # generic below
        # statement bodies reached above; nothing else to do

    @staticmethod
    def _is_try_acquire(call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "blocking" and isinstance(
                    kw.value, ast.Constant) and kw.value.value is False:
                return True
        if call.args and isinstance(call.args[0], ast.Constant) \
                and call.args[0].value is False:
            return True
        return False

    def _scan_expr(self, expr: ast.AST, held: Tuple[str, ...]):
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._record_call(node, held)

    def _record_call(self, call: ast.Call, held: Tuple[str, ...]):
        hazard = classify_blocking(call)
        if hazard is not None:
            # try-acquires and lock bookkeeping are handled as lock
            # events, never as blocking ops
            self.blocks.append(BlockEvent(
                hazard[0], hazard[1], call.lineno, held))
        name = _dotted(call.func)
        if name is None:
            return
        parts = name.split(".")
        if parts[0] == "self" and len(parts) == 2:
            self.calls.append(CallEvent(
                "self", parts[1], name, call.lineno, held,
                qualifier=self.cls or ""))
        elif len(parts) == 1:
            self.calls.append(CallEvent(
                "name", parts[0], name, call.lineno, held))
        elif len(parts) == 2 and parts[0] in self.imports:
            self.calls.append(CallEvent(
                "dotted", parts[1], name, call.lineno, held,
                qualifier=self.imports[parts[0]]))
        else:
            # obj.method(...): resolved later by the unique-method
            # heuristic
            self.calls.append(CallEvent(
                "method", parts[-1], name, call.lineno, held))

    def _apply_flat_regions(self):
        """Fold acquire()..release() line regions into every event's
        held set (the lexical `with` sets were exact already)."""
        if not self._flat:
            return

        def fold(line: int, held: Tuple[str, ...]) -> Tuple[str, ...]:
            out = list(held)
            for lk, lo, hi in self._flat:
                if lo < line <= hi and lk not in out:
                    out.append(lk)
            return tuple(out)

        for ev in self.blocks:
            ev.held = fold(ev.line, ev.held)
        for ev in self.calls:
            ev.held = fold(ev.line, ev.held)
        for ev in self.acquires:
            # an acquire's own region must not mark it as held-before
            ev.held = tuple(lk for lk in fold(ev.line, ev.held)
                            if lk != ev.lock)
