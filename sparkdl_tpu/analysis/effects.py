"""Whole-program effect inference for the H10–H12 rules.

The paper's ``TFInputGraph``/``IsolatedSession`` design exists because
graph-boundary violations — hidden side effects crossing into a
compiled graph — are the dominant failure class in pipeline
frameworks, and tf.data (PAPERS.md) makes the same argument for input
pipelines: correctness tooling must see *through* the call graph, not
just at each call site. The per-file H2 rule is lexical (it flags a
``time.time()`` written literally inside a jit body); this module
closes the gap by computing, over the PR-8 call graph, a
bounded-depth **transitive effect set** per function, with recorded
witness chains like ``may_block`` has:

* **direct effects** (:class:`EffectEvent`, one AST pass per
  function): registry writes (``counter``/``gauge``/``reservoir``
  factories), tracer spans + watchdog beats, logging, wall-clock
  reads and ``time.sleep``, stateful host RNG, host↔device transfers,
  file/socket/subprocess I/O, and Python-object mutation of captured
  state (``self.X`` writes, mutating method calls on ``self``-rooted
  receivers, writes to ``global``/``nonlocal`` names). Lock acquires
  already live in :class:`~sparkdl_tpu.analysis.locks.FunctionFacts`
  and join the closure from there.
* **jit roots**: functions compiled by ``jax.jit``/``pjit`` —
  decorator, ``partial(jax.jit, ...)``, or ``jax.jit(name)`` call
  forms, same resolution contract as H2 — marked at scan time so the
  program pass knows where a compiled-graph boundary starts.
* **mutable captures** (:class:`CaptureEvent`): a jitted function
  reading ``self.X`` where the class binds ``X`` to a list/dict/set,
  or a closure variable its *enclosing* function binds to a mutable
  literal — the stale-value/silent-retrace hazard H2 cannot see
  (tracing bakes the captured value in; later mutation either goes
  unseen or forces a retrace, depending on how it enters the trace).
* **resource events** (:class:`ResourceEvent`): ``x = Ctor(...)``
  where ``Ctor`` resolves (cross-module, through the symbol table) to
  a class defining a terminator (``close``/``quiesce``/``shutdown``/
  ``disarm``), plus builtin handle ctors (``open``,
  ``tempfile.NamedTemporaryFile``, ``socket.socket``) and obs-singleton
  ``.arm()`` calls — each with lexical *terminated* / *escaped*
  verdicts (returned, stored on ``self`` or a global, subscripted into
  a container, yielded, or passed to another function all count as
  escapes: ownership moved, some other scope terminates it).

Three rules consume the facts:

* **H10 — effectful call reachable from jit**: any effect reachable
  from a jit root through resolved call edges (``self.m()``, bare
  names, ``mod.f()`` — the unique-method heuristic is deliberately
  NOT followed here: a jit body calling ``opt.update(...)`` usually
  targets a class *outside* the analyzed set, and a guessed in-repo
  edge would manufacture false impurity), plus direct in-body effects
  of the kinds H2's lexical pass does not cover (registry, mutation,
  transfer, I/O, lock acquires), plus mutable captures. The witness
  chain prints module-by-module.
* **H11 — resource lifecycle**: a tracked resource constructed in a
  scope must reach its terminator on the scope's normal paths or
  escape; otherwise the finding names the terminator to call (or the
  ``with`` form to use).
* **H12 — exception-flow accounting** lives in ``rules.py`` (it is a
  per-file pass) but is documented with these two because the three
  ship as one effect-system PR.

Everything here is plain-data serializable: the per-function effect
facts ride the PR-8 per-file result cache (``ModuleFacts.effects``;
the facts schema version in ``cache.py`` is bumped whenever this
shape changes, which forces the cold re-analysis the cache tests pin).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from sparkdl_tpu.analysis.findings import Finding

#: transitive effect-closure depth bound — same rationale as
#: callgraph.MAX_DEPTH (deep enough for every real chain, bounded so a
#: pathological cycle costs nothing)
MAX_DEPTH = 8

# ---------------------------------------------------------------------------
# shared helpers (kept local: effects must stay importable from
# callgraph.scan_module without a cycle)


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit",
              "jax.experimental.pjit.pjit"}
_PARTIAL_NAMES = {"partial", "functools.partial"}


def _jit_call(call: ast.Call) -> bool:
    name = _dotted(call.func)
    if name in _JIT_NAMES:
        return True
    if name in _PARTIAL_NAMES and call.args:
        return _dotted(call.args[0]) in _JIT_NAMES
    return False


def _is_jit_decorator(dec: ast.AST) -> bool:
    if _dotted(dec) in _JIT_NAMES:
        return True
    return isinstance(dec, ast.Call) and _jit_call(dec)


# ---------------------------------------------------------------------------
# events


@dataclass
class EffectEvent:
    """One direct effect: ``what`` is the human description."""

    what: str
    kind: str                  # one of EFFECT_KINDS
    line: int


@dataclass
class CaptureEvent:
    """Mutable state captured into a jit-traced body."""

    name: str                  # "self.history" / "accum"
    kind: str                  # "instance-attr" | "closure"
    line: int


@dataclass
class ResourceEvent:
    """One tracked resource construction (or singleton arm) with the
    scanner's lexical lifecycle verdict."""

    var: str
    ctor: str                  # display name ("ModelServer", "open")
    line: int
    kind: str                  # "ctor" | "open" | "arm"
    terminated: bool = False
    escaped: bool = False
    #: resolved dotted import source for "ctor" kind ("" when local)
    import_src: str = ""


@dataclass
class FunctionEffects:
    """The serializable per-function effect summary."""

    key: str                   # "module::Qual" (same key as facts)
    jitted: bool = False
    jit_line: int = 0
    effects: List[EffectEvent] = field(default_factory=list)
    captures: List[CaptureEvent] = field(default_factory=list)
    resources: List[ResourceEvent] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "key": self.key, "jitted": self.jitted,
            "jit_line": self.jit_line,
            "effects": [[e.what, e.kind, e.line] for e in self.effects],
            "captures": [[c.name, c.kind, c.line]
                         for c in self.captures],
            "resources": [[r.var, r.ctor, r.line, r.kind,
                           r.terminated, r.escaped, r.import_src]
                          for r in self.resources],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FunctionEffects":
        fe = cls(key=d["key"], jitted=d["jitted"],
                 jit_line=d.get("jit_line", 0))
        fe.effects = [EffectEvent(e[0], e[1], e[2])
                      for e in d["effects"]]
        fe.captures = [CaptureEvent(c[0], c[1], c[2])
                       for c in d["captures"]]
        fe.resources = [ResourceEvent(r[0], r[1], r[2], r[3], r[4],
                                      r[5], r[6])
                        for r in d["resources"]]
        return fe


#: every effect kind the closure tracks, with the one-line reading the
#: H10 message leans on
EFFECT_KINDS = {
    "registry": "metrics-registry write",
    "trace": "tracer span / watchdog beat",
    "log": "logging",
    "clock": "wall-clock read / sleep",
    "rng": "stateful host RNG",
    "transfer": "host<->device transfer",
    "io": "file/socket/subprocess I/O",
    "mutation": "mutation of captured Python state",
    "lock": "lock acquisition",
}


# ---------------------------------------------------------------------------
# direct-effect classification

_REGISTRY_FACTORIES = {"counter", "gauge", "reservoir"}
_TRACE_NAMES = {"span", "watchdog_watch"}
_TRACE_ATTRS = {"span", "pulse"}
_LOG_RECEIVERS = {"logger", "log", "logging"}
_LOG_METHODS = {"debug", "info", "warning", "error", "exception",
                "critical", "log"}
_LOG_NAMES = {"print", "warn_once"}
_CLOCK_DOTTED = {"time.time", "time.perf_counter", "time.monotonic",
                 "time.sleep", "datetime.now", "datetime.utcnow",
                 "datetime.datetime.now", "datetime.datetime.utcnow"}
_RNG_PREFIXES = ("random.", "np.random.", "numpy.random.")
_RNG_DOTTED = {"os.urandom"}
_TRANSFER_DOTTED = {"jax.device_get", "jax.device_put",
                    "jax.block_until_ready", "timed_device_get"}
_TRANSFER_ATTRS = {"block_until_ready", "timed_device_get",
                   "device_put", "device_get"}
_IO_DOTTED = {"open", "input", "socket.create_connection",
              "urllib.request.urlopen", "subprocess.run",
              "subprocess.check_output", "subprocess.check_call",
              "subprocess.Popen", "os.remove", "os.replace",
              "os.unlink", "os.makedirs", "shutil.rmtree",
              "shutil.copy", "shutil.move"}
_IO_ATTRS = {"recv", "accept", "communicate", "sendall"}
_MUTATORS = {"append", "extend", "insert", "update", "setdefault",
             "clear", "pop", "popleft", "add", "discard", "remove",
             "appendleft"}

#: literal / ctor forms that bind a MUTABLE value (the capture
#: analysis and the class mutable-attr table share this test)
_MUTABLE_CTORS = {"list", "dict", "set", "bytearray", "deque",
                  "collections.deque", "defaultdict",
                  "collections.defaultdict", "OrderedDict",
                  "collections.OrderedDict", "Counter",
                  "collections.Counter"}


def _is_mutable_value(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        return _dotted(node.func) in _MUTABLE_CTORS
    return False


def classify_effect(call: ast.Call) -> Optional[Tuple[str, str]]:
    """(description, kind) when this call IS a direct effect."""
    name = _dotted(call.func)
    attr = call.func.attr if isinstance(call.func, ast.Attribute) \
        else None
    if attr in _REGISTRY_FACTORIES:
        return (f"registry `{attr}(...)` write", "registry")
    if name in _TRACE_NAMES or attr in _TRACE_ATTRS:
        return (f"`{name or attr}(...)` tracer/watchdog effect",
                "trace")
    if name in _LOG_NAMES:
        return (f"`{name}(...)`", "log")
    if attr in _LOG_METHODS and isinstance(call.func.value,
                                           (ast.Name, ast.Attribute)):
        recv = (_dotted(call.func.value) or "").rsplit(".", 1)[-1]
        if recv.lower() in _LOG_RECEIVERS or "logger" in recv.lower():
            return (f"`{recv}.{attr}(...)` logging", "log")
    if name in _CLOCK_DOTTED:
        return (f"`{name}()`", "clock")
    if name in _RNG_DOTTED or (name and
                               name.startswith(_RNG_PREFIXES)):
        return (f"`{name}(...)` stateful host RNG", "rng")
    if name in _TRANSFER_DOTTED or attr in _TRANSFER_ATTRS:
        return (f"`{name or attr}(...)` host<->device transfer",
                "transfer")
    if name in _IO_DOTTED or (name and name.endswith(".open")):
        return (f"`{name}(...)` I/O", "io")
    if attr in _IO_ATTRS:
        return (f"`.{attr}(...)` I/O", "io")
    if name == "warnings.warn":
        return ("`warnings.warn(...)`", "log")
    return None


# ---------------------------------------------------------------------------
# class / scope pre-passes


def mutable_class_attrs(cls: ast.ClassDef) -> Set[str]:
    """Instance attrs the class binds to a mutable container
    (``self.X = []`` / ``{}`` / ``deque()`` anywhere in a method)."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and _is_mutable_value(
                node.value):
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    out.add(tgt.attr)
    return out


def _local_mutable_bindings(fn: ast.AST) -> Dict[str, int]:
    """``name -> line`` for names this function binds to a mutable
    literal/ctor OUTSIDE its nested defs — what a nested jitted def
    would capture by closure."""
    out: Dict[str, int] = {}

    def walk(stmts):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(stmt, ast.Assign) and _is_mutable_value(
                    stmt.value):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        out.setdefault(tgt.id, stmt.lineno)
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.stmt):
                    walk([child])
                elif isinstance(child, (ast.ExceptHandler,)):
                    walk(child.body)

    body = fn.body if isinstance(fn.body, list) else [fn.body]
    walk(body)
    return out


def _walk_scope(stmts):
    """Yield nodes WITHOUT descending into nested def/class bodies
    (``ast.walk`` has no pruning): the scope's own statements only.
    The nested def node itself IS yielded — the escape checks need to
    see it — but what happens inside it belongs to that function's
    own scan, not this one's."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _param_names(fn: ast.AST) -> Set[str]:
    args = getattr(fn, "args", None)
    if args is None:
        return set()
    names = {a.arg for a in args.args + args.kwonlyargs
             + args.posonlyargs}
    for special in (args.vararg, args.kwarg):
        if special is not None:
            names.add(special.arg)
    return names


# ---------------------------------------------------------------------------
# resource lifecycle bookkeeping

#: methods whose presence makes a class a tracked resource, and whose
#: call on the variable counts as reaching the terminator
TERMINATORS = ("close", "quiesce", "shutdown", "disarm")

#: builtin handle constructors tracked even without an analyzed class
_HANDLE_CTORS = {"open", "tempfile.NamedTemporaryFile",
                 "tempfile.TemporaryFile", "socket.socket"}

#: obs singleton factories whose ``.arm()`` opens a disarm lifecycle
ARM_FACTORIES = {"tracer", "watchdog", "recorder", "request_log",
                 "controller"}

#: context managers that adopt the resource (``with closing(x):``)
_ADOPTING_CMS = {"closing", "contextlib.closing", "ExitStack"}


class _ResourceTracker:
    """Per-function lexical lifecycle analysis: candidate constructions
    first, then a termination/escape sweep over the same body."""

    def __init__(self, fn: ast.AST, qualname: str):
        self.fn = fn
        self.qualname = qualname
        self.events: List[ResourceEvent] = []
        self._by_var: Dict[str, ResourceEvent] = {}
        self._globals: Set[str] = set()
        #: local var -> arm-factory name (``wd = watchdog()``)
        self._arm_vars: Dict[str, str] = {}

    def run(self, imports: Dict[str, str]) -> List[ResourceEvent]:
        body = self.fn.body if isinstance(self.fn.body, list) \
            else [self.fn.body]
        self._collect(body, imports)
        self._collect_arms(body)
        if self._by_var:
            self._sweep(body)
        return self.events

    # -- candidate collection ------------------------------------------------

    def _collect(self, stmts, imports: Dict[str, str]):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(stmt, ast.Global):
                self._globals.update(stmt.names)
            if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, ast.Call) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                self._candidate(stmt.targets[0].id, stmt.value,
                                stmt.lineno, imports)
            # `with Ctor() as x:` is its own termination — never a
            # candidate; `with open(..) as f` likewise
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.stmt):
                    self._collect([child], imports)
                elif isinstance(child, ast.ExceptHandler):
                    self._collect(child.body, imports)
                elif isinstance(child, ast.match_case):
                    self._collect(child.body, imports)

    def _candidate(self, var: str, call: ast.Call, line: int,
                   imports: Dict[str, str]):
        name = _dotted(call.func)
        if name is None:
            return
        if name.rsplit(".", 1)[-1] in ARM_FACTORIES:
            self._arm_vars[var] = name.rsplit(".", 1)[-1]
            return
        if name in _HANDLE_CTORS:
            ev = ResourceEvent(var, name, line, "open")
        else:
            last = name.rsplit(".", 1)[-1]
            if not last[:1].isupper():
                return      # ctor heuristic: classes are CapWords
            src = imports.get(name.split(".")[0], "")
            if "." in name and src:
                src = f"{src}.{last}"
            elif src:
                pass        # from-import: src is already pkg.mod.Class
            ev = ResourceEvent(var, last, line, "ctor",
                               import_src=src)
        # a rebound name tracks its LAST construction (the earlier one
        # is a separate leak this lexical pass does not chase)
        self._by_var[var] = ev
        self.events.append(ev)

    def _collect_arms(self, body):
        """``wd.arm(...)`` on an arm-factory var, or the direct
        ``watchdog().arm(...)`` form, opens a disarm lifecycle. An arm
        inside a NESTED def belongs to that function's own scan — this
        walk prunes def bodies."""
        for node in _walk_scope(body):
            if not (isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute)
                    and node.func.attr == "arm"):
                continue
            recv = node.func.value
            var = factory = None
            if isinstance(recv, ast.Name) and \
                    recv.id in self._arm_vars:
                var, factory = recv.id, self._arm_vars[recv.id]
            elif isinstance(recv, ast.Call):
                name = (_dotted(recv.func) or "").rsplit(".", 1)[-1]
                if name in ARM_FACTORIES:
                    var, factory = f"{name}()", name
            if var is None:
                continue
            ev = ResourceEvent(var, factory, node.lineno, "arm")
            self._by_var.setdefault(var, ev)
            self.events.append(ev)

    # -- termination / escape sweep ------------------------------------------

    def _names_in(self, node: ast.AST) -> Set[str]:
        """Names in ``node`` EXCLUDING method-call receivers:
        ``return s.submit(x)`` returns submit's result, not ``s`` —
        the receiver position is use, never escape."""
        receivers = {id(n.func.value) for n in ast.walk(node)
                     if isinstance(n, ast.Call)
                     and isinstance(n.func, ast.Attribute)
                     and isinstance(n.func.value, ast.Name)}
        return {n.id for n in ast.walk(node)
                if isinstance(n, ast.Name) and id(n) not in receivers}

    def _sweep(self, stmts):
        tracked = set(self._by_var)
        for ev in self._by_var.values():
            if ev.var in self._globals:
                ev.escaped = True   # stored in module state
        for node in _walk_scope(stmts):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                # a nested def capturing the var keeps it alive in
                # a scope this pass cannot see — treat as escape;
                # _walk_scope does NOT descend into it, so a
                # terminator inside a (maybe never-called) nested
                # def cannot silence the outer scope's verdict
                for name in self._names_in(node) & tracked:
                    self._by_var[name].escaped = True
                continue
            if isinstance(node, ast.Return) and node.value:
                for name in self._names_in(node.value) & tracked:
                    self._by_var[name].escaped = True
            elif isinstance(node, (ast.Yield, ast.YieldFrom)) \
                    and node.value:
                for name in self._names_in(node.value) & tracked:
                    self._by_var[name].escaped = True
            elif isinstance(node, ast.Assign):
                value_names = self._names_in(node.value) & tracked
                if not value_names:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, (ast.Attribute,
                                        ast.Subscript)):
                        # self.x = srv / registry[k] = srv:
                        # ownership moved to longer-lived state
                        for name in value_names:
                            self._by_var[name].escaped = True
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Name) and \
                            ctx.id in tracked:
                        self._by_var[ctx.id].terminated = True
            elif isinstance(node, ast.Call):
                self._sweep_call(node, tracked)

    def _sweep_call(self, call: ast.Call, tracked: Set[str]):
        func = call.func
        if isinstance(func, ast.Attribute) and isinstance(
                func.value, ast.Name) and func.value.id in tracked:
            if func.attr in TERMINATORS or func.attr in (
                    "stop", "cancel", "terminate", "__exit__"):
                self._by_var[func.value.id].terminated = True
            return      # receiver position is use, not escape
        if isinstance(func, ast.Attribute) and isinstance(
                func.value, ast.Call):
            # `watchdog().disarm()` closes the `watchdog().arm()` form
            name = (_dotted(func.value.func) or "").rsplit(".", 1)[-1]
            key = f"{name}()"
            if key in tracked and func.attr in TERMINATORS:
                self._by_var[key].terminated = True
                return
        name = _dotted(func)
        if name and name.rsplit(".", 1)[-1] in _ADOPTING_CMS:
            for arg in call.args:
                if isinstance(arg, ast.Name) and arg.id in tracked:
                    self._by_var[arg.id].terminated = True
            return
        # the var passed as an ARGUMENT anywhere (weakref.finalize,
        # atexit.register, container.append, helper(x)) → ownership
        # shared with a scope this lexical pass cannot see: escape
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            for name in self._names_in(arg) & tracked:
                self._by_var[name].escaped = True


# ---------------------------------------------------------------------------
# the per-function effect scan


class EffectScanner:
    """One function body → direct effects + resource events. Nested
    defs are skipped (they are scanned as their own functions);
    lambdas are walked in place (they run in this frame)."""

    def __init__(self, qualname: str, imports: Dict[str, str],
                 cls_mutable_attrs: Set[str]):
        self.qualname = qualname
        self.imports = imports
        self.cls_mutable_attrs = cls_mutable_attrs
        self.effects: List[EffectEvent] = []

    def scan(self, fn: ast.AST) -> List[EffectEvent]:
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        self._globals: Set[str] = set()
        self._nonlocals: Set[str] = set()
        self._walk(body)
        return self.effects

    def _walk(self, stmts):
        for stmt in stmts:
            self._visit(stmt)

    def _visit(self, stmt: ast.stmt):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, ast.Global):
            self._globals.update(stmt.names)
        elif isinstance(stmt, ast.Nonlocal):
            self._nonlocals.update(stmt.names)
        elif isinstance(stmt, (ast.Assign, ast.AugAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for tgt in targets:
                self._check_mutation_target(tgt)
        if isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                self._check_mutation_target(tgt)
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                self._visit(child)
            elif isinstance(child, ast.ExceptHandler):
                self._walk(child.body)
            elif isinstance(child, ast.match_case):
                self._walk(child.body)
            elif isinstance(child, ast.expr):
                self._scan_expr(child)

    def _check_mutation_target(self, tgt: ast.AST):
        root = tgt
        while isinstance(root, (ast.Attribute, ast.Subscript)):
            root = root.value
        if isinstance(tgt, (ast.Attribute, ast.Subscript)) and \
                isinstance(root, ast.Name) and root.id == "self":
            self.effects.append(EffectEvent(
                f"write to `{_display(tgt)}`", "mutation",
                tgt.lineno))
        elif isinstance(tgt, ast.Name) and (
                tgt.id in self._globals or tgt.id in self._nonlocals):
            self.effects.append(EffectEvent(
                f"write to {'global' if tgt.id in self._globals else 'nonlocal'} "
                f"`{tgt.id}`", "mutation", tgt.lineno))

    def _scan_expr(self, expr: ast.AST):
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            hit = classify_effect(node)
            if hit is not None:
                self.effects.append(EffectEvent(
                    hit[0], hit[1], node.lineno))
                continue
            # mutating method call on self-rooted state
            func = node.func
            if isinstance(func, ast.Attribute) and \
                    func.attr in _MUTATORS:
                root = func.value
                while isinstance(root, (ast.Attribute, ast.Subscript)):
                    root = root.value
                if isinstance(root, ast.Name) and root.id == "self":
                    self.effects.append(EffectEvent(
                        f"`{_display(func)}(...)` mutates instance "
                        "state", "mutation", node.lineno))


def _display(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:       # pragma: no cover - unparse is py3.9+
        return _dotted(node) or "<expr>"


def scan_captures(fn: ast.AST, cls_mutable_attrs: Set[str],
                  enclosing_mutables: Dict[str, int]
                  ) -> List[CaptureEvent]:
    """Mutable state a (jitted) function body captures: ``self.X``
    loads where the class binds ``X`` mutably, and free-variable loads
    of names the ENCLOSING function binds to a mutable literal."""
    out: List[CaptureEvent] = []
    params = _param_names(fn)
    locals_: Set[str] = set(params)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    # first pass: local bindings shadow enclosing names — scope-pruned
    # (a NESTED def's local `accum = ...` must not shadow this
    # function's genuine capture of the enclosing `accum`; nested defs
    # run their own capture scan)
    for node in _walk_scope(body):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    locals_.add(tgt.id)
        elif isinstance(node, (ast.For, ast.comprehension)):
            target = node.target
            for n in ast.walk(target):
                if isinstance(n, ast.Name):
                    locals_.add(n.id)
    seen: Set[str] = set()
    for node in _walk_scope(body):
        if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name) and node.value.id == "self" \
                and isinstance(node.ctx, ast.Load) \
                and node.attr in cls_mutable_attrs:
            name = f"self.{node.attr}"
            if name not in seen:
                seen.add(name)
                out.append(CaptureEvent(name, "instance-attr",
                                        node.lineno))
        elif isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Load) and node.id not in locals_ \
                and node.id in enclosing_mutables:
            if node.id not in seen:
                seen.add(node.id)
                out.append(CaptureEvent(node.id, "closure",
                                        node.lineno))
    return out


# ---------------------------------------------------------------------------
# the transitive closure (mirrors CallGraph.may_block / may_acquire)


def _short_lock(lock: str) -> str:
    mod, _, attr = lock.partition("::")
    mod = mod[len("sparkdl_tpu."):] if mod.startswith("sparkdl_tpu.") \
        else mod
    return f"{mod}:{attr}" if attr else mod


def _effects_index(graph) -> Dict[str, FunctionEffects]:
    idx: Dict[str, FunctionEffects] = {}
    for m in graph.modules.values():
        idx.update(m.effects)
    return idx


def may_effect(graph, key: str,
               idx: Optional[Dict[str, FunctionEffects]] = None,
               depth: int = MAX_DEPTH,
               _memo: Optional[dict] = None,
               _seen: Optional[Set[str]] = None
               ) -> Dict[Tuple[str, str], Tuple[str, ...]]:
    """``(kind, what) -> witness chain`` for every effect a call into
    ``key`` may perform — its own direct effects plus everything
    reachable through resolved call edges (unique-method guesses
    excluded; see the module docstring). The chain is a tuple of
    qualified names ending at the function holding the effect."""
    idx = _effects_index(graph) if idx is None else idx
    memo = {} if _memo is None else _memo
    if key in memo:
        return memo[key]
    f = graph.functions.get(key)
    if f is None or depth <= 0:
        return {}
    seen = _seen if _seen is not None else set()
    if key in seen:
        return {}
    seen.add(key)
    out: Dict[Tuple[str, str], Tuple[str, ...]] = {}
    fe = idx.get(key)
    if fe is not None:
        for e in fe.effects:
            out.setdefault((e.kind, e.what), (graph.short(key),))
    for acq in f.acquires:
        out.setdefault(("lock", f"acquires {_short_lock(acq.lock)}"),
                       (graph.short(key),))
    for call in f.calls:
        if call.kind == "method":
            continue    # no unique-method guessing in the closure
        target = graph.resolve(f, call)
        if target is None or target == key:
            continue
        for ek, chain in may_effect(graph, target, idx, depth - 1,
                                    memo, seen).items():
            out.setdefault(ek, (graph.short(key),) + chain)
    seen.discard(key)
    if _seen is None or depth == MAX_DEPTH:
        memo[key] = out
    return out


# ---------------------------------------------------------------------------
# H10 — effectful call reachable from a jit-traced body


#: direct in-body effect kinds H10 reports — the others (clock, rng,
#: log/print, trace spans) are H2's lexical beat; double-flagging one
#: line under two rules would demand two suppressions for one decision
_H10_DIRECT_KINDS = {"registry", "mutation", "transfer", "io"}


def check_h10(graph) -> List[Finding]:
    idx = _effects_index(graph)
    memo: dict = {}
    findings: List[Finding] = []
    for key, fe in sorted(idx.items()):
        if not fe.jitted:
            continue
        f = graph.functions.get(key)
        if f is None:
            continue
        # direct effects of the kinds H2 cannot or does not flag
        seen_kinds: Set[str] = set()
        for e in fe.effects:
            if e.kind not in _H10_DIRECT_KINDS or e.kind in seen_kinds:
                continue
            seen_kinds.add(e.kind)
            findings.append(Finding(
                rule="H10", path=f.path, line=e.line, col=0,
                qualname=f.qualname,
                message=(
                    f"{e.what} inside jit-traced "
                    f"`{f.qualname}`: {EFFECT_KINDS[e.kind]} runs at "
                    "TRACE time only — once per compilation, never "
                    "per step — so the compiled graph silently drops "
                    "it; hoist the effect outside the traced body "
                    "(suppress: `# sparkdl-lint: allow[H10] -- "
                    "<why>`)")))
        # transitive effects through resolved calls
        for call in f.calls:
            if call.kind == "method":
                continue
            target = graph.resolve(f, call)
            if target is None or target == key:
                continue
            for (kind, what), chain in sorted(
                    may_effect(graph, target, idx,
                               _memo=memo).items()):
                if kind in seen_kinds:
                    continue
                seen_kinds.add(kind)
                full = " -> ".join((graph.short(key),) + chain)
                findings.append(Finding(
                    rule="H10", path=f.path, line=call.line, col=0,
                    qualname=f.qualname,
                    message=(
                        f"jit-traced `{f.qualname}` reaches "
                        f"{EFFECT_KINDS[kind]} ({what}) through the "
                        f"call chain {full} — the effect executes at "
                        "TRACE time only and the compiled program "
                        "carries none of it per step (graph-boundary "
                        "violation, the TFInputGraph failure class); "
                        "make the callee pure or move the effect "
                        "outside the jit (suppress: `# sparkdl-lint: "
                        "allow[H10] -- <why>`)")))
        # mutable captures: the stale-value / retrace hazard
        for cap in fe.captures:
            what = ("mutable instance attribute"
                    if cap.kind == "instance-attr"
                    else "mutable closure variable")
            findings.append(Finding(
                rule="H10", path=f.path, line=cap.line, col=0,
                qualname=f.qualname,
                message=(
                    f"jit-traced `{f.qualname}` captures {what} "
                    f"`{cap.name}`: tracing bakes the captured value "
                    "into the compiled program — later mutation is "
                    "either silently ignored (stale value) or forces "
                    "a retrace per mutation; pass it as an argument "
                    "or freeze it to a tuple/scalar (suppress: "
                    "`# sparkdl-lint: allow[H10] -- <why this value "
                    "is effectively constant>`)")))
    findings.sort(key=lambda x: (x.path, x.line))
    return findings


# ---------------------------------------------------------------------------
# H11 — resource lifecycle


def _class_index(graph) -> Dict[str, List[List[str]]]:
    """class name -> method lists across the analyzed set (for the
    unique-class fallback: package ``__init__`` re-exports hide the
    defining module from the import table)."""
    idx: Dict[str, List[List[str]]] = {}
    for m in graph.modules.values():
        for cls, methods in m.classes.items():
            idx.setdefault(cls, []).append(methods)
    return idx


def _resolve_resource_class(graph, ev: ResourceEvent, module: str,
                            classes: Dict[str, List[List[str]]]
                            ) -> Optional[str]:
    """The terminator method name when ``ev``'s ctor resolves to a
    tracked resource class, else None."""
    if ev.kind == "open":
        return "close"
    if ev.kind == "arm":
        return "disarm"
    candidates = []
    mf = graph.modules.get(module)
    if mf is not None and ev.ctor in mf.classes:
        candidates.append(mf.classes[ev.ctor])
    if not candidates and ev.import_src:
        mod, _, cls = ev.import_src.rpartition(".")
        src = graph._match_module(mod) if mod else None
        if src is not None:
            methods = graph.modules[src].classes.get(cls)
            if methods is not None:
                candidates.append(methods)
    if not candidates:
        # unique-class fallback (the H7/H8 unique-method spirit):
        # exactly one analyzed class with this name, else no verdict
        defs = classes.get(ev.ctor, [])
        if len(defs) == 1:
            candidates.append(defs[0])
    for methods in candidates:
        for term in TERMINATORS:
            if term in methods:
                return term
    return None


def check_h11(graph) -> List[Finding]:
    idx = _effects_index(graph)
    classes = _class_index(graph)
    findings: List[Finding] = []
    for key, fe in sorted(idx.items()):
        f = graph.functions.get(key)
        if f is None:
            continue
        low = f.qualname.rsplit(".", 1)[-1].lower()
        if "arm" == low or low in ("autoarm", "disarm"):
            continue    # an arm method IS the lifecycle implementation
        for ev in fe.resources:
            if ev.terminated or ev.escaped:
                continue
            module = key.partition("::")[0]
            term = _resolve_resource_class(graph, ev, module, classes)
            if term is None:
                continue
            if ev.kind == "arm":
                what = (f"`{ev.var}.arm(...)` arms the {ev.ctor} "
                        "singleton")
                fix = (f"pair it with `{ev.var}.disarm()` (a "
                       "try/finally), or arm process-wide at entry "
                       "and suppress")
            else:
                what = (f"`{ev.var} = {ev.ctor}(...)` constructs a "
                        "resource")
                fix = (f"call `{ev.var}.{term}()` on every normal "
                       f"path (a `with`/`try-finally`), return it, "
                       "or store it on longer-lived state")
            findings.append(Finding(
                rule="H11", path=f.path, line=ev.line, col=0,
                qualname=f.qualname,
                message=(
                    f"{what} whose terminator `{term}()` is never "
                    "reached in this scope and the object does not "
                    "escape (not returned / stored / registered) — "
                    "a leaked lifecycle keeps threads, sockets, or "
                    f"arm state alive past the scope; {fix} "
                    "(suppress: `# sparkdl-lint: allow[H11] -- "
                    "<who terminates it>`)")))
    findings.sort(key=lambda x: (x.path, x.line))
    return findings
