"""Suppression config for sparkdl-lint: inline annotations + the
built-in drain-path allowlist.

Two ways to accept a finding, both carrying a justification so no
suppression is ever silent:

* **inline** — a ``# sparkdl-lint: allow[H1]`` comment, either trailing
  on the flagged line or standalone on the line directly above it.
  Multiple rules separate with commas (``allow[H1,H4]``); ``allow[*]``
  accepts every rule on that line. Everything after ``--`` is the
  justification, echoed in ``--show-suppressed`` output::

      jax.device_get(losses)  # sparkdl-lint: allow[H1] -- epoch drain

* **allowlist** — :data:`DEFAULT_ALLOWLIST` entries naming a
  ``(path suffix, qualname prefix)`` pair per rule: code whose entire
  JOB is the thing the rule bans (SlabSink's drain IS the device_get
  the rest of the ship path must not do; the measure tools exist to
  time transfers). Keep this list short — anything not structurally a
  drain should suppress inline, at the use site, where review sees it.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, Optional, Set, Tuple

_SUPPRESS_RE = re.compile(
    r"#\s*sparkdl-lint:\s*allow\[([A-Za-z0-9*,\s]+)\]"
    r"(?:\s*--\s*(?P<why>.*?))?\s*$")


@dataclass(frozen=True)
class AllowEntry:
    """One allowlisted region: a path suffix plus an optional dotted
    qualname prefix (empty = the whole file). ``why`` is mandatory —
    an allowlist entry without a reason is a convention, and the whole
    point of this package is that conventions drift."""

    path_suffix: str
    qualname: str
    why: str

    def matches(self, path: str, qualname: str) -> bool:
        norm = path.replace("\\", "/")
        if not norm.endswith(self.path_suffix):
            return False
        if not self.qualname:
            return True
        return (qualname == self.qualname
                or qualname.startswith(self.qualname + "."))


#: The drain-path set: the ONLY places allowed to synchronize
#: device→host without an inline justification — plus the two
#: structurally-intentional holds the whole-program rules would
#: otherwise flag (H8: the dispatcher's coalescing wait IS the
#: batching window) and the measurement CLIs whose entire job is the
#: banned operation.
DEFAULT_ALLOWLIST: Dict[str, Tuple[AllowEntry, ...]] = {
    "H1": (
        AllowEntry(
            "sparkdl_tpu/obs/trace.py", "timed_device_get",
            "THE drain, relocated from SlabSink.write so the sync is "
            "observable: every strategy funnels results to host "
            "through this one device_get, spanned on the 'device' "
            "lane and timed into transfer_wait_seconds"),
        AllowEntry(
            "sparkdl_tpu/utils/measure.py", "",
            "measurement tools: forcing + timing transfers is their "
            "entire job (forced-sync methodology, VERDICT r1 weak #3)"),
        AllowEntry(
            "tools/measure_transfer.py", "",
            "the (strategy x depth) sweep CLI: forcing + timing the "
            "drain per configuration is its entire job — the "
            "utils/measure precedent, in script form"),
        AllowEntry(
            "tools/train_testnet_artifact.py", "main",
            "one-shot artifact trainer: the end-of-fit parameter "
            "drain IS the artifact write (nothing downstream to "
            "overlap with)"),
    ),
    "H14": (
        AllowEntry(
            "sparkdl_tpu/obs/trace.py", "timed_device_get",
            "THE sanctioned hot-path drain (the H1 entry's "
            "whole-program twin): every strategy funnels device "
            "results to host through this one sync, spanned and "
            "timed — a hot path may materialize HERE and nowhere "
            "else"),
    ),
    "H17": (
        AllowEntry(
            "sparkdl_tpu/obs/registry.py", "Reservoir._offer_exemplar",
            "caller-holds contract: observe() wraps every call in "
            "self._lock (the same decision the method's inline H3 "
            "suppressions document, lifted to one entry instead of "
            "five line annotations); the private-helper shape is "
            "runtime-asserted elsewhere under SPARKDL_TPU_SANITIZE=1"),
    ),
    "H8": (
        AllowEntry(
            "sparkdl_tpu/serve/batching.py", "RequestQueue.collect",
            "the dispatcher's intentional Condition.wait: the "
            "coalescing window IS the product (latency deliberately "
            "traded for batch fill, docs/SERVING.md) — wait() "
            "RELEASES the queue mutex while blocked, so producers "
            "keep admitting; deadline clipping bounds the sleep"),
    ),
}


class SuppressionIndex:
    """Per-file map of line → (rules, justification) built from the
    raw source, consulted once per finding.

    A trailing annotation binds to its own line; a standalone
    annotation (the line holds nothing but the comment) binds to the
    next non-blank, non-comment line below — the first line of the
    statement it precedes.
    """

    def __init__(self, source: str):
        self._by_line: Dict[int, Tuple[Set[str], str]] = {}
        lines = source.splitlines()
        for i, raw in enumerate(lines, start=1):
            m = _SUPPRESS_RE.search(raw)
            if not m:
                continue
            rules = {r.strip().upper() for r in m.group(1).split(",")
                     if r.strip()}
            why = (m.group("why") or "").strip() or "annotated, no reason"
            stripped = raw.strip()
            target = i
            if stripped.startswith("#"):
                # standalone: walk down to the code line it precedes
                j = i + 1
                while j <= len(lines) and (
                        not lines[j - 1].strip()
                        or lines[j - 1].strip().startswith("#")):
                    j += 1
                target = j
            have = self._by_line.get(target)
            if have:
                rules = rules | have[0]
                why = have[1] if have[1] != "annotated, no reason" else why
            self._by_line[target] = (rules, why)

    def lookup(self, rule: str, line: int) -> Optional[str]:
        """The justification if ``rule`` is suppressed at ``line``."""
        hit = self._by_line.get(line)
        if hit is None:
            return None
        rules, why = hit
        if rule.upper() in rules or "*" in rules:
            return why
        return None


def allowlisted(rule: str, path: str, qualname: str,
                allowlist: Optional[Dict[str, Tuple[AllowEntry, ...]]]
                = None) -> Optional[str]:
    """The allowlist justification for (rule, location), or None."""
    table = DEFAULT_ALLOWLIST if allowlist is None else allowlist
    for entry in table.get(rule.upper(), ()):
        if entry.matches(path, qualname):
            where = entry.path_suffix
            if entry.qualname:
                where += f"::{entry.qualname}"
            return f"allowlist[{where}] -- {entry.why}"
    return None
