"""SARIF 2.1.0 output for sparkdl-lint.

SARIF (Static Analysis Results Interchange Format) is what CI forges
ingest to annotate findings at ``file:line`` in a PR diff view —
``python -m sparkdl_tpu.analysis --sarif out.sarif`` makes the lint's
verdicts land in review instead of in a build log someone has to open.

Mapping choices, pinned by ``tests/test_effects.py``:

* one ``run`` per invocation; the tool driver lists every rule with
  its ``docs/LINT.md`` one-liner so the forge can render rule help;
* every finding becomes a ``result`` with ``level: warning``
  (sparkdl-lint rules are all the same severity class: the CLI's exit
  code, not a per-rule level, is the gate) at its physical location;
* suppressed findings are NOT dropped — they carry a SARIF
  ``suppressions`` entry (``kind: inSource``) with the justification,
  the same "reported, never hidden" contract the text output keeps;
* paths are emitted with forward slashes relative to the invocation
  dir, which is what ``artifactLocation.uri`` wants.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional

from sparkdl_tpu.analysis.findings import Finding

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")
_INFO_URI = "https://github.com/databricks/spark-deep-learning"


def _rule_descriptor(rule: str) -> Dict:
    from sparkdl_tpu.analysis.rules import rule_doc
    try:
        doc = rule_doc(rule)
    except KeyError:
        doc = "sparkdl-lint rule"
    return {
        "id": rule,
        "shortDescription": {"text": doc},
        "helpUri": _INFO_URI,
    }


def to_sarif(findings: Iterable[Finding],
             rules: Optional[Iterable[str]] = None) -> Dict:
    """The SARIF 2.1.0 document for ``findings``. ``rules`` names the
    rule set that RAN (defaults to every rule any finding carries —
    the driver must list a rule before a result may reference it)."""
    findings = list(findings)
    # the driver must list every rule a result references — union the
    # declared run set with whatever the findings carry (PARSE, say)
    rule_ids = sorted((set(rules) if rules is not None else set())
                      | {f.rule for f in findings})
    results: List[Dict] = []
    for f in findings:
        result: Dict = {
            "ruleId": f.rule,
            "level": "warning",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace("\\", "/"),
                    },
                    "region": {
                        "startLine": max(1, f.line),
                        "startColumn": f.col + 1,
                    },
                },
            }],
        }
        if f.qualname:
            result["partialFingerprints"] = {
                "sparkdlQualname": f.qualname}
        if f.suppressed:
            result["suppressions"] = [{
                "kind": "inSource",
                "justification": f.suppression,
            }]
        results.append(result)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "sparkdl-lint",
                    "informationUri": _INFO_URI,
                    "rules": [_rule_descriptor(r) for r in rule_ids],
                },
            },
            "results": results,
        }],
    }


def write_sarif(path: str, findings: Iterable[Finding],
                rules: Optional[Iterable[str]] = None) -> int:
    """Write the SARIF document to ``path``; returns the result count."""
    doc = to_sarif(findings, rules)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    return len(doc["runs"][0]["results"])
