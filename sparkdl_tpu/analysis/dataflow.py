"""Device-dataflow tracking + the H14–H16 throughput-hazard rules.

ROADMAP's own verdict on rounds 6–10 is "safety and visibility, not
speed": the pipeline is still link/host-bound while the analyzer
polices only correctness. This module points the same whole-program
machinery (the PR-8 call graph, the PR-9 effect facts' scan shape) at
the *throughput* bugs that pipeline work keeps reintroducing —
implicit host syncs on hot loops, undonated dead device buffers,
silent dtype widening on a link that is already the wall.

Per function, one scan records a serializable, replayable **event
stream** (``DeviceFlow``): device-value seeds (``jnp.*`` producers,
``jax.device_put``, results of jitted callables), propagation
(assignments, tuple unpacks, calls whose resolved callee returns a
device value), jit-callable bindings (``jax.jit(f)`` /
``ModelFunction.jitted()`` — with or without ``donate_argnums``),
materialization candidates, widening candidates, and the
liveness/escape facts donation analysis needs. The stream rides the
per-file result cache inside ``ModuleFacts`` exactly like the lock
and effect facts (ANALYZER_VERSION bumps force the cold re-analysis
the cache tests pin).

At program time the stream is **replayed** against the resolved call
graph (memoized, cycle-guarded — the same discipline as ``may_block``
/ ``may_effect``), which is what lets device-ness cross function
boundaries: ``gx, gy = place(xb, yb)`` tracks because ``place``'s own
replay proves its return is device-resident, and ``jitted, _, _ =
est._compile_step(step, bs)`` binds a jit callable because
``_compile_step``'s replay proves tuple index 0 is a ``jax.jit``
result (and whether it donates).

Three rules consume the facts, gated by
:class:`~sparkdl_tpu.analysis.hotpath.HotPaths` where noted:

* **H14 — hot-path host sync**: a device→host materialization of a
  tracked value on a HOT function — ``np.asarray``/``np.array`` over
  it, ``.item()``/``.tolist()``, ``float()``/``int()``/``bool()``/
  ``len()``, truthiness, iteration — anywhere except the sanctioned
  ``timed_device_get`` drain (allowlisted). Each finding prints the
  hot witness chain module-by-module. Explicit ``jax.device_get`` /
  ``.block_until_ready()`` stay H1's per-file beat (flagged
  everywhere, hot or cold) — one decision must never need two
  suppressions, the H10-vs-H2 division contract.
* **H15 — missing buffer donation**: a call of a jit-compiled
  callable whose device-tracked positional argument is DEAD after
  the call (locally assigned, last lexical load is the call, never
  escapes, not loop-carried from outside the call's loop) while the
  compile site carries no ``donate_argnums`` — the buffer's HBM
  could be reused for the outputs and instead a second copy is live
  across every step. Not hot-gated: a cold undonated step still
  wastes HBM at pod scale, where state is replicated N ways.
* **H16 — dtype widening**: a Python float literal, ``np.float64``
  scalar, or dtype-less ``np.zeros``/``ones``/``arange``/``asarray``
  mixed into arithmetic with a device-tracked value on a HOT
  function — under x64 (and on the host staging side uniformly)
  that promotes the payload to float64, a silent 2× byte tax on a
  link-bound pipeline. Pin the dtype at the producer.

Deliberate blind spots (documented in docs/LINT.md's limitations
section): resolution is lexical — values flowing through containers,
``**kwargs``, attributes, or unresolved callees are untracked (a
missed sync costs recall the fixtures pin; a guessed edge would
manufacture false findings), and deadness is per-function (an
argument whose caller retains a reference is excluded by the
params-are-never-dead rule, not by interprocedural escape analysis).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from sparkdl_tpu.analysis.findings import Finding
from sparkdl_tpu.analysis.hotpath import (
    WATCHDOG_MARKERS,
    HotPaths,
    _resolve as _hot_resolve,
)
from sparkdl_tpu.analysis.locks import CallEvent

#: replay recursion bound (same rationale as callgraph.MAX_DEPTH)
MAX_DEPTH = 8

# ---------------------------------------------------------------------------
# classification tables

# ONE copy of the dotted-name walk and the jit/partial name tables:
# the H2/H10/H15 rules must agree on what "a jit" is (one decision,
# one suppression), so the tables live in effects.py and are shared —
# a new jit alias added there covers every consumer at once.
from sparkdl_tpu.analysis.effects import (  # noqa: E402
    _JIT_NAMES,
    _PARTIAL_NAMES,
    _dotted,
)

#: dotted-call prefixes/names whose RESULT lives on device
_PRODUCER_PREFIXES = ("jnp.", "jax.numpy.")
_PRODUCER_NAMES = {
    "jax.device_put", "jax.device_put_replicated",
    "jax.device_put_sharded", "jax.make_array_from_process_local_data",
}

_DONATE_KWARGS = {"donate_argnums", "donate_argnames", "donate_inputs"}

#: host materialization forms H14 owns (explicit jax.device_get /
#: .block_until_ready are H1's per-file beat — see module docstring)
_NP_WRAPS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
             "np.ascontiguousarray", "numpy.ascontiguousarray",
             "np.float64", "numpy.float64", "np.float32",
             "numpy.float32"}
_SCALAR_BUILTINS = {"float", "int", "bool", "len"}
_ITEM_ATTRS = {"item", "tolist"}

#: dtype-less numpy ctors that default to float64/int64 (H16)
_DTYPELESS_CTORS = {"np.zeros", "np.ones", "np.empty", "np.full",
                    "np.arange", "np.linspace", "np.asarray",
                    "np.array", "numpy.zeros", "numpy.ones",
                    "numpy.empty", "numpy.full", "numpy.arange",
                    "numpy.linspace", "numpy.asarray", "numpy.array"}
_F64_CTORS = {"np.float64", "numpy.float64"}


def _is_producer(call: ast.Call) -> Optional[str]:
    name = _dotted(call.func)
    if name is None:
        return None
    if name in _PRODUCER_NAMES or name.startswith(_PRODUCER_PREFIXES):
        return name
    return None


def _jit_value(call: ast.Call) -> Optional[bool]:
    """``donated`` when ``call`` *produces* a jit-compiled callable:
    ``jax.jit(f, ...)``, ``partial(jax.jit, ...)``, or the repo's
    ``<model_fn>.jitted(...)`` form. None when it is not one."""
    name = _dotted(call.func)
    if name in _JIT_NAMES or (
            name in _PARTIAL_NAMES and call.args
            and _dotted(call.args[0]) in _JIT_NAMES):
        donated = any(kw.arg in _DONATE_KWARGS and not (
            isinstance(kw.value, ast.Constant)
            and kw.value.value in (False, None))
            for kw in call.keywords)
        return donated
    if isinstance(call.func, ast.Attribute) and \
            call.func.attr == "jitted":
        donated = any(kw.arg in _DONATE_KWARGS and not (
            isinstance(kw.value, ast.Constant)
            and kw.value.value in (False, None))
            for kw in call.keywords)
        if not donated and call.args:
            donated = not (isinstance(call.args[0], ast.Constant)
                           and call.args[0].value in (False, None))
        return donated
    return None


def _jit_decorated(fn: ast.AST) -> Optional[bool]:
    """``donated`` when ``fn`` carries a jit decorator, else None."""
    for dec in getattr(fn, "decorator_list", ()):
        if _dotted(dec) in _JIT_NAMES:
            return False
        if isinstance(dec, ast.Call):
            d = _jit_value(dec)
            if d is not None:
                return d
    return None


def _widen_source(node: ast.AST) -> Optional[str]:
    """A human description when ``node`` is an H16 widening operand."""
    if isinstance(node, ast.Constant) and isinstance(node.value, float):
        return f"Python float literal `{node.value}`"
    if isinstance(node, ast.Call):
        name = _dotted(node.func)
        if name in _F64_CTORS:
            return f"`{name}(...)` float64 scalar"
        if name in _DTYPELESS_CTORS:
            has_dtype = any(kw.arg == "dtype" for kw in node.keywords)
            last = name.rsplit(".", 1)[-1]
            if last in ("asarray", "array"):
                has_dtype = has_dtype or len(node.args) >= 2
            elif last == "full":
                # np.full(shape, fill_value[, dtype]) — dtype is the
                # THIRD positional; two args is the dtype-less form
                has_dtype = has_dtype or len(node.args) >= 3
            if not has_dtype:
                return f"dtype-less `{name}(...)` (defaults float64/" \
                       "int64)"
    return None


def _param_names(fn: ast.AST) -> List[str]:
    """POSITIONAL-ORDERED parameter names (posonly, then regular),
    with keyword-only/vararg/kwarg appended — order matters: the
    arg→param device-ness propagation maps call-site positions onto
    the callee's positional slots."""
    args = getattr(fn, "args", None)
    if args is None:
        return []
    names = [a.arg for a in args.posonlyargs + args.args]
    names.extend(a.arg for a in args.kwonlyargs)
    for special in (args.vararg, args.kwarg):
        if special is not None:
            names.append(special.arg)
    return names


# ---------------------------------------------------------------------------
# the serializable per-function facts


def _loops_of(ctx: Tuple[int, ...]) -> Tuple[int, ...]:
    """The loop components of an event context: positive ids are
    loops, negative ids are conditional branches (if/except/match
    arms) — see :class:`FlowScanner`."""
    return tuple(i for i in ctx if i > 0)


@dataclass
class FlowEvent:
    """One replayable event. ``data`` is a JSON-able dict whose shape
    depends on ``kind``:

    * ``assign`` — ``targets`` (names), ``value`` (descriptor:
      ``{"v": "producer"|"name"|"jit"|"call"|"other", ...}``)
    * ``call`` — ``ckind``/``cname``/``qual``/``display`` (the
      CallEvent shape) + ``args`` (positional bare-Name args) +
      optional ``jit``/``donated`` for direct ``jax.jit(f)(x)`` calls
    * ``sync`` — ``form``, ``name``, ``what``
    * ``widen`` — ``name``, ``other``
    * ``defjit`` — ``name``, ``donated`` (a jit-decorated nested def)
    * ``return`` — ``elts``: list of value descriptors
    * ``escape`` — ``name``, ``how``
    """

    kind: str
    line: int
    #: enclosing control context, outermost first: positive ids are
    #: loops, negative ids conditional branches (if/except/match
    #: arms). H15's deadness check needs both: an argument's latest
    #: assignment must sit in the SAME loop chain as the call (else
    #: it is loop-carried) and on a path that DOMINATES the call
    #: (else iterations skipping the assigning branch reuse the
    #: previous iteration's buffer across the back-edge).
    loops: Tuple[int, ...]
    data: dict


@dataclass
class DeviceFlow:
    """The per-function device-dataflow summary (serializable)."""

    key: str
    hot_root: bool = False
    root_label: str = ""
    #: POSITIONAL-ordered parameter names (the arg→param propagation
    #: maps call-site positions onto these slots)
    params: List[str] = field(default_factory=list)
    #: name -> last source line holding a Load of it (this scope only)
    last_load: Dict[str, int] = field(default_factory=dict)
    #: name -> EVERY source line holding a Load of it — deadness needs
    #: the full set: a read lexically ABOVE the reaching assignment
    #: but inside the call's loop is a back-edge read of the previous
    #: iteration's buffer, so donating it would be use-after-donate
    loads: Dict[str, List[int]] = field(default_factory=dict)
    #: loop id -> (first, last) source line of the loop statement
    loop_spans: Dict[int, Tuple[int, int]] = field(default_factory=dict)
    events: List[FlowEvent] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"key": self.key, "hot_root": self.hot_root,
                "root_label": self.root_label, "params": self.params,
                "last_load": self.last_load,
                "loads": self.loads,
                "loop_spans": {str(k): list(v)
                               for k, v in self.loop_spans.items()},
                "events": [[e.kind, e.line, list(e.loops), e.data]
                           for e in self.events]}

    @classmethod
    def from_dict(cls, d: dict) -> "DeviceFlow":
        df = cls(key=d["key"], hot_root=d["hot_root"],
                 root_label=d.get("root_label", ""),
                 params=list(d["params"]),
                 last_load={k: int(v)
                            for k, v in d["last_load"].items()},
                 loads={k: [int(v) for v in vs]
                        for k, vs in d["loads"].items()},
                 loop_spans={int(k): (v[0], v[1])
                             for k, v in d["loop_spans"].items()})
        df.events = [FlowEvent(e[0], e[1], tuple(e[2]), e[3])
                     for e in d["events"]]
        return df


# ---------------------------------------------------------------------------
# the per-function scan


class FlowScanner:
    """One function body → its ordered :class:`DeviceFlow` event
    stream. Nested defs are NOT descended into (they are scanned as
    their own functions) — but their jit decoration is recorded
    (``defjit``) and the local names they capture become escapes."""

    def __init__(self, key: str, imports: Dict[str, str],
                 cls: Optional[str] = None):
        self.flow = DeviceFlow(key=key)
        self.imports = imports
        self.cls = cls
        self._loops: Tuple[int, ...] = ()
        self._loop_counter = 0
        self._branch_counter = 0

    # -- helpers -------------------------------------------------------------

    def _emit(self, kind: str, line: int, data: dict) -> None:
        self.flow.events.append(FlowEvent(kind, line, self._loops,
                                          data))

    def _load(self, name: str, line: int) -> None:
        prev = self.flow.last_load.get(name, 0)
        if line > prev:
            self.flow.last_load[name] = line
        self.flow.loads.setdefault(name, []).append(line)

    @staticmethod
    def _root_name(node: ast.AST) -> Optional[str]:
        """The base Name of ``x`` / ``x[...]`` / ``x.attr`` chains —
        what the tracked set is keyed by."""
        while isinstance(node, (ast.Subscript, ast.Attribute)):
            node = node.value
        return node.id if isinstance(node, ast.Name) else None

    def _import_source(self, dotted: str) -> str:
        head, _, rest = dotted.partition(".")
        src = self.imports.get(head)
        if src is None:
            return dotted
        return f"{src}.{rest}" if rest else src

    def _value_descriptor(self, node: ast.AST) -> dict:
        """Classify an assigned/returned expression."""
        if isinstance(node, ast.Name):
            return {"v": "name", "name": node.id}
        if isinstance(node, (ast.BinOp, ast.UnaryOp)):
            # arithmetic PROPAGATES device-ness: `y = dev * dev` is a
            # device array, and the per-step `y.item()` downstream is
            # exactly the sync H14 exists to catch
            names = sorted({n.id for n in ast.walk(node)
                            if isinstance(n, ast.Name)})
            if names:
                return {"v": "binop", "names": names}
            return {"v": "other"}
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            elt = node.value if isinstance(node, ast.DictComp) \
                else node.elt
            if isinstance(elt, ast.Call) and _is_producer(elt):
                # a host CONTAINER of device arrays: len()/iteration
                # over it are free host-list ops (no H14), but handing
                # it to a jit call is a pytree whose buffers donation
                # analysis (H15) still covers
                return {"v": "producer", "container": True,
                        "what": _is_producer(elt) or ""}
            return {"v": "other"}
        if isinstance(node, ast.Call):
            donated = _jit_value(node)
            if donated is not None:
                return {"v": "jit", "donated": donated,
                        "what": _dotted(node.func) or "jax.jit"}
            producer = _is_producer(node)
            if producer is not None:
                return {"v": "producer", "what": producer}
            call = self._call_shape(node)
            if call is not None:
                return {"v": "call", **call}
        return {"v": "other"}

    def _call_shape(self, node: ast.Call) -> Optional[dict]:
        """The resolvable CallEvent shape of a call, or None — the
        SAME qualifier contract as locks.FunctionScanner._record_call:
        ``self`` calls carry the enclosing class, dotted calls the
        IMPORT SOURCE (not the local alias), so CallGraph.resolve sees
        identical events from both layers."""
        name = _dotted(node.func)
        if name is None:
            return None
        parts = name.split(".")
        # positional slots, None where the arg is not a bare name —
        # position is what the arg→param propagation and the H15
        # donate index key on
        args = [a.id if isinstance(a, ast.Name) else None
                for a in node.args]
        if parts[0] == "self" and len(parts) == 2:
            return {"ckind": "self", "cname": parts[1],
                    "qual": self.cls or "",
                    "display": name, "args": args}
        if len(parts) == 1:
            return {"ckind": "name", "cname": parts[0], "qual": "",
                    "display": name, "args": args}
        if len(parts) == 2 and parts[0] in self.imports:
            return {"ckind": "dotted", "cname": parts[1],
                    "qual": self.imports[parts[0]],
                    "display": name, "args": args}
        return {"ckind": "method", "cname": parts[-1], "qual": "",
                "display": name, "args": args}

    # -- entry ---------------------------------------------------------------

    def scan(self, fn: ast.AST) -> DeviceFlow:
        self.flow.params = _param_names(fn)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        self._walk(body)
        return self.flow

    # -- statements ----------------------------------------------------------

    def _walk(self, stmts) -> None:
        for stmt in stmts:
            self._visit(stmt)

    def _visit(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            donated = _jit_decorated(stmt)
            if donated is not None:
                self._emit("defjit", stmt.lineno,
                           {"name": stmt.name, "donated": donated})
            self._escape_captures(stmt, "captured by nested def")
            return
        if isinstance(stmt, ast.ClassDef):
            self._escape_captures(stmt, "captured by nested class")
            return
        if isinstance(stmt, (ast.Global, ast.Nonlocal)):
            for name in stmt.names:
                self._emit("escape", stmt.lineno,
                           {"name": name, "how": "global/nonlocal "
                                                 "state"})
            return
        if isinstance(stmt, ast.Assign):
            self._scan_expr(stmt.value)
            targets: List[str] = []
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    targets.append(tgt.id)
                elif isinstance(tgt, (ast.Tuple, ast.List)):
                    targets.extend(e.id for e in tgt.elts
                                   if isinstance(e, ast.Name))
                elif isinstance(tgt, (ast.Attribute, ast.Subscript)):
                    # ownership moved to longer-lived state
                    self._scan_expr(tgt)
                    for node in ast.walk(stmt.value):
                        if isinstance(node, ast.Name):
                            self._emit("escape", stmt.lineno,
                                       {"name": node.id,
                                        "how": "stored on attr/"
                                               "container"})
            if targets:
                self._emit("assign", stmt.lineno,
                           {"targets": targets,
                            "value":
                                self._value_descriptor(stmt.value)})
            return
        if isinstance(stmt, ast.AugAssign):
            self._scan_expr(stmt.value)
            if isinstance(stmt.target, ast.Name):
                other = _widen_source(stmt.value)
                if other is not None:
                    self._emit("widen", stmt.lineno,
                               {"name": stmt.target.id,
                                "other": other})
                self._load(stmt.target.id, stmt.lineno)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._scan_expr(stmt.value)
                if isinstance(stmt.target, ast.Name):
                    self._emit("assign", stmt.lineno,
                               {"targets": [stmt.target.id],
                                "value":
                                    self._value_descriptor(stmt.value)})
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._scan_expr(stmt.value)
                elts = (stmt.value.elts
                        if isinstance(stmt.value, ast.Tuple)
                        else [stmt.value])
                self._emit("return", stmt.lineno,
                           {"elts": [self._value_descriptor(e)
                                     for e in elts]})
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            if isinstance(stmt.iter, ast.Name):
                self._emit("sync", stmt.lineno,
                           {"form": "iteration", "name": stmt.iter.id,
                            "what": f"`for ... in {stmt.iter.id}:`"})
            self._scan_expr(stmt.iter)
            self._in_loop(stmt.body, stmt)
            self._in_branch(stmt.orelse)
            return
        if isinstance(stmt, ast.While):
            self._truth_test(stmt.test)
            self._scan_expr(stmt.test)
            self._in_loop(stmt.body, stmt)
            self._in_branch(stmt.orelse)
            return
        if isinstance(stmt, ast.If):
            self._truth_test(stmt.test)
            self._scan_expr(stmt.test)
            self._in_branch(stmt.body)
            self._in_branch(stmt.orelse)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_expr(item.context_expr)
            self._walk(stmt.body)
            return
        if isinstance(stmt, ast.Try):
            self._in_branch(stmt.body)
            for handler in stmt.handlers:
                self._in_branch(handler.body)
            self._in_branch(stmt.orelse)
            # finalbody is unconditional — no branch context
            self._walk(stmt.finalbody)
            return
        if isinstance(stmt, ast.Match):
            self._scan_expr(stmt.subject)
            for case in stmt.cases:
                self._in_branch(case.body)
            return
        if isinstance(stmt, ast.Expr):
            self._scan_expr(stmt.value)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                self._visit(child)
            elif isinstance(child, ast.expr):
                self._scan_expr(child)

    def _in_loop(self, body, stmt: ast.stmt) -> None:
        self._loop_counter += 1
        self.flow.loop_spans[self._loop_counter] = (
            stmt.lineno,
            getattr(stmt, "end_lineno", stmt.lineno) or stmt.lineno)
        outer = self._loops
        self._loops = outer + (self._loop_counter,)
        self._walk(body)
        self._loops = outer

    def _in_branch(self, body) -> None:
        """A conditionally-executed arm (if/except/match/loop-else):
        negative context id, so deadness analysis can tell a
        dominating assignment from a maybe-skipped one."""
        if not body:
            return
        self._branch_counter += 1
        outer = self._loops
        self._loops = outer + (-self._branch_counter,)
        self._walk(body)
        self._loops = outer

    def _truth_test(self, test: ast.AST) -> None:
        node = test
        if isinstance(node, ast.UnaryOp) and isinstance(node.op,
                                                        ast.Not):
            node = node.operand
        if isinstance(node, ast.Name):
            self._emit("sync", node.lineno,
                       {"form": "truthiness", "name": node.id,
                        "what": f"`if {node.id}:` truth test"})

    def _escape_captures(self, fn: ast.AST, how: str) -> None:
        """FREE names a nested def/class/lambda body loads become
        escapes: the capture keeps the value alive in a scope this
        per-function pass cannot see. Names the nested scope binds
        itself (params, assignment/loop targets) are its own locals,
        not captures — EXCEPT names it declares ``nonlocal``/
        ``global``: a Store to those rebinds the OUTER binding, so
        both their loads and stores are captures."""
        declared: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                declared.update(node.names)
        bound: Set[str] = set(_param_names(fn)) - declared
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store, ast.Del)) \
                    and node.id not in declared:
                bound.add(node.id)
        seen: Set[str] = set()
        for name in sorted(declared):
            seen.add(name)
            self._emit("escape", getattr(fn, "lineno", 1),
                       {"name": name, "how": how + " (nonlocal)"})
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load) and node.id not in bound \
                    and node.id not in seen:
                seen.add(node.id)
                self._emit("escape", getattr(fn, "lineno", 1),
                           {"name": node.id, "how": how})

    # -- expressions ---------------------------------------------------------

    def _scan_expr(self, expr: ast.AST) -> None:
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Lambda):
                self._escape_captures(node, "captured by lambda")
                continue
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load):
                self._load(node.id, node.lineno)
            elif isinstance(node, ast.Call):
                self._scan_call(node)
            elif isinstance(node, ast.BinOp):
                self._scan_binop(node)
            elif isinstance(node, (ast.Yield, ast.YieldFrom)) \
                    and node.value is not None:
                for n in ast.walk(node.value):
                    if isinstance(n, ast.Name):
                        self._emit("escape", node.lineno,
                                   {"name": n.id, "how": "yielded"})
            stack.extend(ast.iter_child_nodes(node))

    def _scan_call(self, node: ast.Call) -> None:
        name = _dotted(node.func)
        # hot-root markers: a call whose import source is the watchdog
        # watch/pulse marks this function as a hot-loop root
        if name is not None:
            src = self._import_source(name)
            if any(src.endswith(m) for m in WATCHDOG_MARKERS):
                self.flow.hot_root = True
        # direct invocation of a jit expression: jax.jit(f)(x) /
        # model_fn.jitted()(x)
        if isinstance(node.func, ast.Call):
            donated = _jit_value(node.func)
            if donated is not None:
                self._emit("call", node.lineno, {
                    "ckind": "direct-jit", "cname": "<jit>",
                    "qual": "",
                    "display": _dotted(node.func.func) or "jax.jit",
                    "args": [a.id if isinstance(a, ast.Name) else None
                             for a in node.args],
                    "end": getattr(node, "end_lineno", node.lineno)
                    or node.lineno,
                    "jit": True, "donated": donated})
                return
        if name is None:
            return
        if _jit_value(node) is not None or (
                name in _PARTIAL_NAMES and node.args
                and _dotted(node.args[0]) in _JIT_NAMES):
            return      # a compile, not a call — handled as a value
        # H14 materialization candidates
        attr = node.func.attr if isinstance(node.func, ast.Attribute) \
            else None
        if name in _NP_WRAPS and node.args:
            root = self._root_name(node.args[0])
            if root is not None:
                self._emit("sync", node.lineno,
                           {"form": "np-wrap", "name": root,
                            "what": f"`{name}(...)`"})
                return
        if name in _SCALAR_BUILTINS and len(node.args) == 1:
            root = self._root_name(node.args[0])
            if root is not None:
                self._emit("sync", node.lineno,
                           {"form": name, "name": root,
                            "what": f"`{name}(...)`"})
            return      # a scalar builtin retains nothing: not a call
        if attr in _ITEM_ATTRS and not node.args:
            root = self._root_name(node.func.value)
            if root is not None:
                self._emit("sync", node.lineno,
                           {"form": attr, "name": root,
                            "what": f"`.{attr}()`"})
                return
        call = self._call_shape(node)
        if call is not None:
            call["end"] = getattr(node, "end_lineno",
                                  node.lineno) or node.lineno
            self._emit("call", node.lineno, call)

    def _scan_binop(self, node: ast.BinOp) -> None:
        for side, other in ((node.left, node.right),
                            (node.right, node.left)):
            if not isinstance(side, ast.Name):
                continue
            desc = _widen_source(other)
            if desc is not None:
                self._emit("widen", node.lineno,
                           {"name": side.id, "other": desc})


def scan_flow(fn: ast.AST, key: str, imports: Dict[str, str],
              cls: Optional[str] = None) -> DeviceFlow:
    """One function body → its :class:`DeviceFlow` facts. ``cls`` is
    the enclosing class (``self.m()`` resolution needs it)."""
    return FlowScanner(key, imports, cls).scan(fn)


# ---------------------------------------------------------------------------
# program-time replay


@dataclass
class _SyncHit:
    line: int
    form: str
    name: str
    what: str


@dataclass
class _WidenHit:
    line: int
    name: str
    other: str


@dataclass
class _DonateHit:
    line: int
    callee: str              # display name of the jit callable
    arg: str
    index: int
    compile_note: str        # where/how it was compiled


@dataclass
class _Result:
    """One function's replay outcome."""

    ret_device: bool = False
    #: the returned device value is a host CONTAINER of device arrays
    #: (a comprehension result): H15-relevant, H14-exempt
    ret_container: bool = False
    #: tuple index -> donated for returned jit callables
    ret_jit: Dict[int, bool] = field(default_factory=dict)
    syncs: List[_SyncHit] = field(default_factory=list)
    widens: List[_WidenHit] = field(default_factory=list)
    donates: List[_DonateHit] = field(default_factory=list)


_EMPTY = _Result()


def _flows_index(graph) -> Dict[str, DeviceFlow]:
    idx: Dict[str, DeviceFlow] = {}
    for m in graph.modules.values():
        idx.update(getattr(m, "flows", {}))
    return idx


class _FlowState:
    """Cached per-CallGraph analysis state shared by H14/H15/H16.

    Replays run in bounded ROUNDS: each round re-replays every
    function with the previous round's arg→param device seeds (a
    caller passing a tracked value into a resolved callee makes the
    callee's positional parameter device-tracked), so device-ness
    crosses call edges as arguments as well as returns. Three rounds
    cover every real chain (depth-2 argument hand-offs); the loop
    stops early once the seed set stops growing."""

    _ROUNDS = 3

    def __init__(self, graph):
        self.graph = graph
        self.idx = _flows_index(graph)
        self.hot = HotPaths(graph, self.idx)
        self.memo: Dict[str, _Result] = {}
        self.param_seeds: Dict[str, Set[str]] = {}
        self._next_seeds: Dict[str, Set[str]] = {}
        for round_no in range(self._ROUNDS):
            self.memo = {}
            self._next_seeds = {}
            for key in self.idx:
                self.result(key)
            grew = any(n - self.param_seeds.get(k, set())
                       for k, n in self._next_seeds.items())
            if not grew or round_no == self._ROUNDS - 1:
                # converged — or the bounded-depth cutoff: growth on
                # the final round is dropped by design (a deeper
                # argument chain waits for the bound, exactly like
                # MAX_DEPTH), never merged into seeds the memoized
                # results were not computed with
                break
            for k, n in self._next_seeds.items():
                self.param_seeds.setdefault(k, set()).update(n)

    def result(self, key: str, _stack: Optional[Set[str]] = None,
               depth: int = MAX_DEPTH) -> _Result:
        if key in self.memo:
            return self.memo[key]
        flow = self.idx.get(key)
        f = self.graph.functions.get(key)
        if flow is None or f is None or depth <= 0:
            return _EMPTY
        stack = _stack if _stack is not None else set()
        if key in stack:
            return _EMPTY
        stack.add(key)
        res = self._replay(flow, f, stack, depth)
        stack.discard(key)
        if _stack is None or depth == MAX_DEPTH:
            self.memo[key] = res
        return res

    # -- the replay ----------------------------------------------------------

    def _callee(self, f, data: dict) -> Optional[str]:
        if data.get("ckind") == "direct-jit":
            return None
        ev = CallEvent(kind=data["ckind"], name=data["cname"],
                       display=data.get("display", data["cname"]),
                       line=0, held=(), qualifier=data.get("qual", ""))
        return _hot_resolve(self.graph, f, ev)

    def _seed_params(self, target: str, data: dict,
                     tracked: Set[str]) -> None:
        """A tracked value handed positionally into a resolved callee
        seeds the matching parameter for the next replay round."""
        callee = self.idx.get(target)
        if callee is None:
            return
        params = callee.params
        offset = 1 if params and params[0] in ("self", "cls") \
            and data.get("ckind") in ("self", "method") else 0
        for i, arg in enumerate(data.get("args", [])):
            if arg is None or arg not in tracked:
                continue
            slot = i + offset
            if slot < len(params):
                self._next_seeds.setdefault(
                    target, set()).add(params[slot])

    def _replay(self, flow: DeviceFlow, f, stack: Set[str],
                depth: int) -> _Result:
        res = _Result()
        tracked: Set[str] = set(self.param_seeds.get(flow.key, ()))
        #: host containers of device arrays (H15-relevant, H14-exempt)
        containers: Set[str] = set()
        jitvars: Dict[str, Tuple[bool, str]] = {}   # name -> (donated, note)
        escapes: Set[str] = set()
        assigned: Dict[str, Tuple[int, Tuple[int, ...]]] = {}
        #: (line, end line, loops, callee display, args, donated,
        #: note, tracked-set snapshot, assigned-map snapshot) — both
        #: snapshots taken AT the call: a reassignment after the call
        #: must not change the verdict about the buffer fed INTO it
        jit_calls: List[Tuple[int, int, Tuple[int, ...], str,
                              List[str], bool, str, Set[str],
                              Dict[str, Tuple[int,
                                              Tuple[int, ...]]]]] = []

        def classify(value: dict
                     ) -> Tuple[Optional[str],
                                Optional[Tuple[bool, str]]]:
            """(device kind — None/"array"/"container", jit_info) for
            a value descriptor."""
            v = value.get("v")
            if v == "producer":
                return ("container" if value.get("container")
                        else "array"), None
            if v == "name":
                name = value["name"]
                kind = ("array" if name in tracked
                        else "container" if name in containers
                        else None)
                return kind, jitvars.get(name)
            if v == "binop":
                if any(n in tracked for n in value.get("names", ())):
                    return "array", None
                return None, None
            if v == "jit":
                return None, (bool(value.get("donated")),
                              f"`{value.get('what', 'jax.jit')}(...)`")
            if v == "call":
                callee = self._callee(f, value)
                if callee is None:
                    return None, None
                sub = self.result(callee, stack, depth - 1)
                jit0 = sub.ret_jit.get(0)
                info = None
                if jit0 is not None:
                    info = (jit0,
                            f"compiled inside "
                            f"`{value.get('display', '?')}(...)`")
                kind = ("array" if sub.ret_device
                        else "container" if sub.ret_container
                        else None)
                return kind, info
            return None, None

        for ev in flow.events:
            data = ev.data
            if ev.kind == "defjit":
                jitvars[data["name"]] = (
                    bool(data["donated"]),
                    f"`@jax.jit def {data['name']}` at line {ev.line}")
            elif ev.kind == "assign":
                targets = data["targets"]
                value = data["value"]
                for t in targets:
                    assigned[t] = (ev.line, ev.loops)
                v = value.get("v")
                if v == "call":
                    local_jit = jitvars.get(value["cname"]) \
                        if value.get("ckind") == "name" else None
                    if local_jit is not None:
                        # calling a locally-bound jit callable:
                        # results are device arrays
                        for t in targets:
                            tracked.add(t)
                            containers.discard(t)
                        continue
                    callee = self._callee(f, value)
                    if callee is not None:
                        sub = self.result(callee, stack, depth - 1)
                        for t in targets:
                            (tracked.add if sub.ret_device
                             else tracked.discard)(t)
                            (containers.add if sub.ret_container
                             else containers.discard)(t)
                        for idx, donated in sub.ret_jit.items():
                            if idx < len(targets):
                                jitvars[targets[idx]] = (
                                    donated,
                                    f"compiled inside "
                                    f"`{value.get('display', '?')}"
                                    "(...)`")
                        continue
                    for t in targets:
                        tracked.discard(t)
                        containers.discard(t)
                        jitvars.pop(t, None)
                    continue
                kind, jit_info = classify(value)
                for t in targets:
                    (tracked.add if kind == "array"
                     else tracked.discard)(t)
                    (containers.add if kind == "container"
                     else containers.discard)(t)
                    if jit_info is not None:
                        jitvars[t] = jit_info
                    else:
                        jitvars.pop(t, None)
            elif ev.kind == "call":
                args = data.get("args", [])
                end = int(data.get("end", ev.line))
                if data.get("ckind") == "direct-jit":
                    jit_calls.append((ev.line, end, ev.loops,
                                      data.get("display", "<jit>"),
                                      args, bool(data.get("donated")),
                                      "compiled at the call site",
                                      tracked | containers,
                                      dict(assigned)))
                    continue
                local_jit = jitvars.get(data["cname"]) \
                    if data.get("ckind") == "name" else None
                if local_jit is not None:
                    donated, note = local_jit
                    jit_calls.append((ev.line, end, ev.loops,
                                      data["cname"], args, donated,
                                      note, tracked | containers,
                                      dict(assigned)))
                    continue
                # an argument handed to any other call may be retained
                # by the callee — alive for donation purposes; a
                # TRACKED argument into a resolved callee also seeds
                # that callee's parameter as device-resident for the
                # next propagation round
                target = self._callee(f, data)
                if target is not None:
                    self._seed_params(target, data, tracked)
                for a in args:
                    if a is not None:
                        escapes.add(a)
            elif ev.kind == "sync":
                if data["name"] in tracked:
                    res.syncs.append(_SyncHit(ev.line, data["form"],
                                              data["name"],
                                              data["what"]))
            elif ev.kind == "widen":
                if data["name"] in tracked:
                    res.widens.append(_WidenHit(ev.line, data["name"],
                                                data["other"]))
            elif ev.kind == "escape":
                escapes.add(data["name"])
            elif ev.kind == "return":
                for i, elt in enumerate(data["elts"]):
                    kind, jit_info = classify(elt)
                    if kind == "array":
                        res.ret_device = True
                    elif kind == "container":
                        res.ret_container = True
                    if jit_info is not None:
                        donated = jit_info[0]
                        # any undonated return path wins (conservative)
                        res.ret_jit[i] = (res.ret_jit.get(i, True)
                                          and donated)
                    if elt.get("v") == "name":
                        escapes.add(elt["name"])

        # H15: dead-after-call device args of undonated jit calls
        for line, end, loops, callee, args, donated, note, snap, \
                asn_at_call in jit_calls:
            if donated:
                continue
            for idx, arg in enumerate(args):
                if arg is None or arg not in snap:
                    continue            # not a (named) device value
                if arg in flow.params or arg in escapes:
                    continue            # lifetime extends past here
                info = asn_at_call.get(arg)
                if info is None:
                    continue            # never locally assigned
                if flow.last_load.get(arg, 0) > end:
                    continue            # read again later: alive
                a_line, a_ctx = info
                if _loops_of(a_ctx) != _loops_of(loops):
                    continue    # assigned in a different loop chain:
                    #             loop-carried, next iteration reads it
                if a_ctx != loops[:len(a_ctx)]:
                    continue    # assigned on a maybe-skipped branch
                    #             (if/except arm) the call does not sit
                    #             under: an iteration skipping the
                    #             branch would reuse the previous
                    #             buffer across the back-edge
                loop_ids = _loops_of(loops)
                if loop_ids:
                    # a read inside the call's loop but lexically
                    # ABOVE the reaching assignment runs on the NEXT
                    # iteration against this iteration's (donated)
                    # buffer — a back-edge read, alive
                    span = flow.loop_spans.get(loop_ids[-1])
                    if span is not None and any(
                            span[0] <= ln < a_line
                            for ln in flow.loads.get(arg, ())):
                        continue
                res.donates.append(_DonateHit(
                    line, callee, arg, idx, note))
        return res


def _flow_state(graph) -> _FlowState:
    state = getattr(graph, "_sparkdl_flow_state", None)
    if state is None or state.graph is not graph:
        state = _FlowState(graph)
        graph._sparkdl_flow_state = state
    return state


# ---------------------------------------------------------------------------
# the rules


#: per-form consequence clauses. Most forms BLOCK the calling thread
#: until the device catches up; len() is honest about being shape
#: metadata (it never blocks on jax arrays) — it is still flagged on
#: hot paths because per-batch length branching is the precursor of
#: the row-wise host iteration the rule exists to stop.
_BLOCKING_TAIL = ("— the calling thread blocks until the device "
                  "catches up, serializing the overlap the "
                  "deferred/host_async/prefetch strategies exist to "
                  "hide")
_SYNC_READING = {
    "np-wrap": f"copies the device buffer to host {_BLOCKING_TAIL}",
    "float": f"materializes the device scalar on host {_BLOCKING_TAIL}",
    "int": f"materializes the device scalar on host {_BLOCKING_TAIL}",
    "bool": f"materializes the device scalar on host {_BLOCKING_TAIL}",
    "len": ("probes the device shape in host control flow — len() "
            "itself reads static metadata (no device wait on jax "
            "arrays), but hot-loop code branching per batch on it is "
            "the precursor of row-wise host iteration; restructure "
            "to whole-batch ops"),
    "item": f"materializes the device scalar on host {_BLOCKING_TAIL}",
    "tolist": ("copies the device buffer to host, element-wise "
               f"{_BLOCKING_TAIL}"),
    "iteration": ("iterates the device array on host, row by row — "
                  "every element pays its own device→host round-trip "
                  "and the loop serializes behind the slowest one"),
    "truthiness": ("materializes the device value to branch on it "
                   f"{_BLOCKING_TAIL}"),
}


def check_h14(graph) -> List[Finding]:
    state = _flow_state(graph)
    findings: List[Finding] = []
    for key in sorted(state.idx):
        if not state.hot.is_hot(key):
            continue
        f = graph.functions.get(key)
        if f is None:
            continue
        res = state.result(key)
        for hit in res.syncs:
            findings.append(Finding(
                rule="H14", path=f.path, line=hit.line, col=0,
                qualname=f.qualname,
                message=(
                    f"{hit.what} over device-resident `{hit.name}` on "
                    f"a HOT path: "
                    f"{_SYNC_READING.get(hit.form, 'syncs on host')}; "
                    f"hot witness: {state.hot.why(key)}. Accumulate "
                    "device values and drain once per epoch/run "
                    "through the sanctioned timed_device_get path "
                    "instead (suppress: `# sparkdl-lint: allow[H14] "
                    "-- <why this sync must sit on the hot path>`)")))
    findings.sort(key=lambda x: (x.path, x.line))
    return findings


def check_h15(graph) -> List[Finding]:
    state = _flow_state(graph)
    findings: List[Finding] = []
    for key in sorted(state.idx):
        f = graph.functions.get(key)
        if f is None:
            continue
        res = state.result(key)
        for hit in res.donates:
            findings.append(Finding(
                rule="H15", path=f.path, line=hit.line, col=0,
                qualname=f.qualname,
                message=(
                    f"`{hit.callee}(...)` consumes device array "
                    f"`{hit.arg}` (positional {hit.index}) that is "
                    "DEAD after this call — last lexical use, no "
                    f"escape — but the jit ({hit.compile_note}) "
                    "declares no donate_argnums: XLA keeps the input "
                    "buffer alive across the call instead of reusing "
                    "its HBM for the outputs, double-buffering every "
                    "step (at pod scale, N replicas each pay it). "
                    f"Compile with `donate_argnums=({hit.index},)` "
                    "(the parallel/train.py precedent), or suppress "
                    "with `# sparkdl-lint: allow[H15] -- <who reads "
                    "the buffer after the call>`")))
    findings.sort(key=lambda x: (x.path, x.line))
    return findings


def check_h16(graph) -> List[Finding]:
    state = _flow_state(graph)
    findings: List[Finding] = []
    for key in sorted(state.idx):
        if not state.hot.is_hot(key):
            continue
        f = graph.functions.get(key)
        if f is None:
            continue
        res = state.result(key)
        for hit in res.widens:
            findings.append(Finding(
                rule="H16", path=f.path, line=hit.line, col=0,
                qualname=f.qualname,
                message=(
                    f"{hit.other} mixed into arithmetic with "
                    f"device-resident `{hit.name}` on a HOT path: "
                    "dtype-less numpy defaults are float64/int64, so "
                    "the promoted result doubles every payload byte "
                    "on a pipeline that is already link-bound "
                    "(BENCH_r05: pipeline_bound_by=link); hot "
                    f"witness: {state.hot.why(key)}. Pin the dtype at "
                    "the producer (np.float32 / the model dtype) or "
                    "suppress with `# sparkdl-lint: allow[H16] -- "
                    "<why the promotion is intended>`")))
    findings.sort(key=lambda x: (x.path, x.line))
    return findings
