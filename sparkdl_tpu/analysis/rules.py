"""The six sparkdl-lint rules (H1–H6), each an AST pass.

Every rule is a function ``(tree, path) -> list[Finding]`` registered
in :data:`RULES`; the walker runs all of them per file and then applies
suppressions. Rules track the dotted ``Class.method`` qualname of each
hit so the allowlist can scope to a single function.

These are HEURISTIC checks tuned to this repo's idioms — they resolve
names lexically, not by type inference. The contract is: zero false
negatives on the patterns the repo actually writes (the fixtures in
``tests/test_analysis.py`` pin them), and any false positive is cheap
to suppress inline WITH a justification, which is itself documentation.
"""

from __future__ import annotations

import ast
import os
from typing import Callable, Dict, List, Optional, Set, Tuple

from sparkdl_tpu.analysis.findings import Finding

# ---------------------------------------------------------------------------
# shared helpers


def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _ScopedVisitor(ast.NodeVisitor):
    """NodeVisitor that maintains the dotted Class.method qualname."""

    def __init__(self, path: str):
        self.path = path
        self.findings: List[Finding] = []
        self._stack: List[str] = []

    @property
    def qualname(self) -> str:
        return ".".join(self._stack)

    def _push(self, name: str, node: ast.AST):
        self._stack.append(name)
        self.generic_visit(node)
        self._stack.pop()

    def visit_ClassDef(self, node: ast.ClassDef):
        self._push(node.name, node)

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._push(node.name, node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._push(node.name, node)

    def flag(self, rule: str, node: ast.AST, message: str):
        self.findings.append(Finding(
            rule=rule, path=self.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message, qualname=self.qualname))


# ---------------------------------------------------------------------------
# H1 — implicit host transfers on the ship path

_H1_DEVICE_GET = {"jax.device_get", "jax.block_until_ready"}
_H1_NP_WRAP = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_H1_DEVICE_PRODUCERS = ("jnp.", "jax.numpy.", "jax.")


class _H1Transfers(_ScopedVisitor):
    """Host-transfer syncs outside the drain path. Each of these blocks
    the calling thread until the device catches up — on the tunneled
    link that is the exact stall the overlap strategies (deferred /
    host_async / prefetch) exist to hide, and round 1 measured it as a
    ~0.2 MB/s collapse when it hit a long-enqueued buffer."""

    def visit_Call(self, node: ast.Call):
        name = _dotted(node.func)
        if name in _H1_DEVICE_GET:
            self.flag(
                "H1", node,
                f"`{name}` forces a device→host sync; only the "
                "allowlisted drain path (SlabSink.write, measure "
                "tools) may block on the device — route results "
                "through the runner's sink, or suppress with "
                "`# sparkdl-lint: allow[H1] -- <why this drain is "
                "legitimate>`")
        elif (isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready"):
            self.flag(
                "H1", node,
                "`.block_until_ready()` forces a device sync (and on "
                "the tunneled link returns at enqueue — it doesn't even "
                "measure what it claims; use "
                "utils.measure.sync_readback); suppress with "
                "`# sparkdl-lint: allow[H1] -- <why>` if this drain "
                "is deliberate")
        elif name in _H1_NP_WRAP and node.args:
            inner = node.args[0]
            if isinstance(inner, ast.Call):
                producer = _dotted(inner.func)
                if producer and producer.startswith(_H1_DEVICE_PRODUCERS):
                    self.flag(
                        "H1", node,
                        f"`{name}(...)` over a `{producer}` result "
                        "implicitly copies device memory to host; "
                        "keep device values device-resident or drain "
                        "them through the runner sink (suppress: "
                        "`# sparkdl-lint: allow[H1] -- <why>`)")
        self.generic_visit(node)


def check_h1(tree: ast.AST, path: str) -> List[Finding]:
    v = _H1Transfers(path)
    v.visit(tree)
    return v.findings


# ---------------------------------------------------------------------------
# H2 — jit / retrace hazards

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit",
              "jax.experimental.pjit.pjit"}
_PARTIAL_NAMES = {"partial", "functools.partial"}
_H2_SIDE_EFFECT_PREFIXES = ("time.", "np.random.", "numpy.random.",
                            "random.")
_H2_SIDE_EFFECT_CALLS = {"print", "input"}
# obs tracing spans read the host wall clock (time.perf_counter) on
# enter/exit — inside a traced function that happens ONCE, at trace
# time, freezing compile-time timestamps into the program and recording
# nothing per step. Matches `span(...)` and any `<obj>.span(...)`.
_H2_TRACE_SPAN = "span"
_STATIC_KWARGS = {"static_argnums", "static_argnames"}


def _jit_target_of(call: ast.Call) -> Optional[ast.Call]:
    """The jit-ish Call, unwrapping ``partial(jax.jit, ...)``."""
    name = _dotted(call.func)
    if name in _JIT_NAMES:
        return call
    if name in _PARTIAL_NAMES and call.args:
        inner = _dotted(call.args[0])
        if inner in _JIT_NAMES:
            return call
    return None


def _is_jit_decorator(dec: ast.AST) -> bool:
    if _dotted(dec) in _JIT_NAMES:
        return True
    return isinstance(dec, ast.Call) and _jit_target_of(dec) is not None


class _H2SideEffects(ast.NodeVisitor):
    """Scans the BODY of a traced function: anything here runs at trace
    time, once per compilation — wall-clock reads read compile time,
    prints fire once then vanish, stateful RNG freezes one sample into
    the compiled program."""

    def __init__(self, outer: "_H2Retrace", qualname: str):
        self.outer = outer
        self.qualname = qualname

    def _flag(self, node: ast.AST, message: str):
        self.outer.findings.append(Finding(
            rule="H2", path=self.outer.path, line=node.lineno,
            col=node.col_offset, message=message,
            qualname=self.qualname))

    def visit_Call(self, node: ast.Call):
        name = _dotted(node.func)
        if name in _H2_SIDE_EFFECT_CALLS:
            self._flag(node, (
                f"`{name}(...)` inside a jit-traced function executes "
                "at TRACE time only (use jax.debug.print for per-step "
                "output); suppress: `# sparkdl-lint: allow[H2] -- "
                "<why>`"))
        elif name and (name == _H2_TRACE_SPAN
                       or name.endswith("." + _H2_TRACE_SPAN)):
            self._flag(node, (
                f"`{name}(...)` inside a jit-traced function: obs "
                "spans read the host wall clock at TRACE time — the "
                "compiled program would carry one frozen timestamp "
                "and record nothing per step; trace around the jit "
                "call, not inside it (suppress: `# sparkdl-lint: "
                "allow[H2] -- <why>`)"))
        elif name and name.startswith(_H2_SIDE_EFFECT_PREFIXES):
            if name.startswith("time."):
                why = ("reads trace-time wall clock, frozen into the "
                       "compiled program — time OUTSIDE the jit")
            else:
                why = ("stateful host RNG samples ONCE at trace time; "
                       "thread a jax.random key instead")
            self._flag(node, (
                f"`{name}(...)` inside a jit-traced function: {why} "
                "(suppress: `# sparkdl-lint: allow[H2] -- <why>`)"))
        self.generic_visit(node)

    # a nested def/lambda inside a jitted fn is traced too — keep
    # walking (generic_visit covers them)


class _H2Retrace(_ScopedVisitor):
    def __init__(self, path: str, module_defs: Dict[str, ast.AST]):
        super().__init__(path)
        self._module_defs = module_defs
        self._checked: Set[int] = set()

    def _scan_traced(self, fn_node: ast.AST, qualname: str):
        if id(fn_node) in self._checked:
            return
        self._checked.add(id(fn_node))
        body = (fn_node.body if isinstance(fn_node.body, list)
                else [fn_node.body])  # Lambda body is a single expr
        scanner = _H2SideEffects(self, qualname)
        for stmt in body:
            scanner.visit(stmt)

    def _check_static_kwargs(self, call: ast.Call):
        for kw in call.keywords:
            if kw.arg in _STATIC_KWARGS and isinstance(
                    kw.value, (ast.List, ast.Set, ast.Dict,
                               ast.ListComp, ast.SetComp, ast.DictComp)):
                self.flag(
                    "H2", kw.value,
                    f"`{kw.arg}` given a mutable literal: static args "
                    "are compilation-cache KEYS — spell it as an int "
                    "or tuple literal so hashability is visible at the "
                    "call site (suppress: `# sparkdl-lint: allow[H2] "
                    "-- <why>`)")

    def visit_FunctionDef(self, node: ast.FunctionDef):
        if any(_is_jit_decorator(d) for d in node.decorator_list):
            self._scan_traced(node, ".".join(self._stack + [node.name]))
        self._push(node.name, node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Call(self, node: ast.Call):
        jit_call = _jit_target_of(node)
        if jit_call is not None:
            self._check_static_kwargs(node)
            # jax.jit(f) / partial(jax.jit, ...)(f): resolve f when it
            # is a lambda or a same-module def
            args = node.args
            if _dotted(node.func) in _PARTIAL_NAMES:
                args = args[1:]
            for arg in args:
                if isinstance(arg, ast.Lambda):
                    self._scan_traced(arg, self.qualname or "<lambda>")
                elif isinstance(arg, ast.Name):
                    target = self._module_defs.get(arg.id)
                    if target is not None:
                        self._scan_traced(target, arg.id)
        self.generic_visit(node)


def check_h2(tree: ast.AST, path: str) -> List[Finding]:
    # name → def map for resolving jax.jit(fn_name); last def wins,
    # names defined more than once with different nodes still resolve
    # (both get scanned only if both are passed to jit)
    defs: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)
    v = _H2Retrace(path, defs)
    v.visit(tree)
    return v.findings


# ---------------------------------------------------------------------------
# H3 — concurrency discipline

# Condition counts: it wraps (or owns) a mutex, so a class keeping one
# per instance has exactly the same pickle problem as a raw Lock — the
# serve layer's RequestQueue is the canonical case.
_LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock",
               "threading.Condition", "Condition"}
_PICKLE_HOOKS = {"__getstate__", "__reduce__", "__reduce_ex__"}
_H3_EXEMPT_METHODS = {"__init__", "__post_init__", "__new__",
                      "__setstate__", "__getstate__"}


def _is_lock_ctor(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and _dotted(node.func) in _LOCK_CTORS)


def _instance_lock_attrs(cls: ast.ClassDef) -> List[Tuple[str, int]]:
    """``self.X = threading.Lock()`` assignments in methods, plus
    dataclass ``field(default_factory=threading.Lock)`` declarations —
    both become per-INSTANCE lock state that pickle chokes on (class-
    body ``_lock = Lock()`` attributes are class state and exempt)."""
    out: List[Tuple[str, int]] = []
    for item in cls.body:
        if isinstance(item, ast.AnnAssign) and isinstance(
                item.value, ast.Call):
            fn = _dotted(item.value.func)
            if fn in ("field", "dataclasses.field"):
                for kw in item.value.keywords:
                    if kw.arg == "default_factory" and \
                            _dotted(kw.value) in _LOCK_CTORS:
                        name = (item.target.id if isinstance(
                            item.target, ast.Name) else "?")
                        out.append((name, item.lineno))
        elif isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for node in ast.walk(item):
                if isinstance(node, ast.Assign) and _is_lock_ctor(
                        node.value):
                    for tgt in node.targets:
                        if (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            out.append((tgt.attr, node.lineno))
    return out


def _guarded_fields(cls: ast.ClassDef) -> Tuple[Set[str], str]:
    """The ``_lock_guards = ("field", ...)`` declaration: instance
    fields whose WRITES must hold ``self._lock``. Returns (fields,
    lock attr name) — the guarding lock is ``_lock`` by convention."""
    for item in cls.body:
        if isinstance(item, ast.Assign):
            for tgt in item.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "_lock_guards":
                    if isinstance(item.value, (ast.Tuple, ast.List)):
                        return ({e.value for e in item.value.elts
                                 if isinstance(e, ast.Constant)
                                 and isinstance(e.value, str)}, "_lock")
    return (set(), "_lock")


def _with_holds_lock(node: ast.With, lock_attr: str) -> bool:
    for item in node.items:
        ctx = item.context_expr
        if (isinstance(ctx, ast.Attribute) and ctx.attr == lock_attr
                and isinstance(ctx.value, ast.Name)
                and ctx.value.id == "self"):
            return True
    return False


class _H3Concurrency(_ScopedVisitor):
    def visit_ClassDef(self, node: ast.ClassDef):
        locks = _instance_lock_attrs(node)
        if locks:
            hooks = {item.name for item in node.body
                     if isinstance(item, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))}
            if not (hooks & _PICKLE_HOOKS):
                attrs = ", ".join(sorted({a for a, _ in locks}))
                self._stack.append(node.name)
                self.findings.append(Finding(
                    rule="H3", path=self.path, line=node.lineno,
                    col=node.col_offset, qualname=self.qualname,
                    message=(
                        f"class holds threading lock(s) [{attrs}] but "
                        "defines no __getstate__/__reduce__ — locks "
                        "don't pickle, and stage closures ship to "
                        "Spark executors (see "
                        "RunnerMetrics.__getstate__ for the drop-and-"
                        "recreate discipline); suppress: "
                        "`# sparkdl-lint: allow[H3] -- <why>`")))
                self._stack.pop()
        guards, lock_attr = _guarded_fields(node)
        if guards:
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and item.name not in _H3_EXEMPT_METHODS:
                    self._stack.append(node.name)
                    self._stack.append(item.name)
                    self._check_guarded(item, guards, lock_attr,
                                        in_lock=False)
                    self._stack.pop()
                    self._stack.pop()
        self._push(node.name, node)

    def _check_guarded(self, node: ast.AST, guards: Set[str],
                       lock_attr: str, in_lock: bool):
        for child in ast.iter_child_nodes(node):
            child_in_lock = in_lock
            if isinstance(child, ast.With) and _with_holds_lock(
                    child, lock_attr):
                child_in_lock = True
            if isinstance(child, (ast.Assign, ast.AugAssign)) \
                    and not child_in_lock:
                targets = (child.targets
                           if isinstance(child, ast.Assign)
                           else [child.target])
                for tgt in targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                            and tgt.attr in guards):
                        self.flag(
                            "H3", child,
                            f"write to `self.{tgt.attr}` — declared "
                            f"lock-guarded by `_lock_guards` — outside "
                            f"a `with self.{lock_attr}` block "
                            "(suppress: `# sparkdl-lint: allow[H3] "
                            "-- <why>`)")
            self._check_guarded(child, guards, lock_attr, child_in_lock)


def check_h3(tree: ast.AST, path: str) -> List[Finding]:
    v = _H3Concurrency(path)
    v.visit(tree)
    return v.findings


# ---------------------------------------------------------------------------
# H4 — quiesce hygiene

_CLEANUP_TOKENS = ("close", "cleanup", "quiesce", "shutdown", "stop",
                   "release", "teardown", "__exit__", "__del__",
                   "drain")


def _is_cleanup_name(name: str) -> bool:
    low = name.lower()
    return any(tok in low for tok in _CLEANUP_TOKENS)


def _swallows(handler: ast.ExceptHandler) -> bool:
    """Body is only ``pass`` / ``...`` — the exception vanishes."""
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(
                stmt.value, ast.Constant):
            continue  # docstring/ellipsis placeholder
        return False
    return True


class _H4Quiesce(_ScopedVisitor):
    def __init__(self, path: str):
        super().__init__(path)
        self._finally_depth = 0

    def visit_Try(self, node: ast.Try):
        for part in (node.body, node.orelse):
            for stmt in part:
                self.visit(stmt)
        for handler in node.handlers:
            self._check_handler(handler)
            self.visit(handler)
        self._finally_depth += 1
        for stmt in node.finalbody:
            self.visit(stmt)
        self._finally_depth -= 1

    def visit_TryStar(self, node):  # pragma: no cover - py3.11 syntax
        self.visit_Try(node)

    def _check_handler(self, handler: ast.ExceptHandler):
        if handler.type is None:
            self.flag(
                "H4", handler,
                "bare `except:` also swallows KeyboardInterrupt/"
                "SystemExit — a quiesce that can't be interrupted "
                "hangs the engine's drain on shutdown; catch "
                "`Exception` (and log it) instead (suppress: "
                "`# sparkdl-lint: allow[H4] -- <why>`)")
            return
        if _swallows(handler) and (self._finally_depth > 0
                                   or _is_cleanup_name(self.qualname)):
            self.flag(
                "H4", handler,
                "silently swallowed exception in a cleanup/quiesce "
                "path: a secondary failure here masks whether the "
                "drain actually ran (the effectful-source contract) — "
                "log it at debug level at minimum (suppress: "
                "`# sparkdl-lint: allow[H4] -- <why>`)")

    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        # reached only for handlers nested inside other visited bodies
        # (visit_Try dispatches its own handlers through _check_handler
        # before descending)
        self.generic_visit(node)


def check_h4(tree: ast.AST, path: str) -> List[Finding]:
    v = _H4Quiesce(path)
    v.visit(tree)
    return v.findings


# ---------------------------------------------------------------------------
# H5 — wall-clock reads in the observability/serving timing paths

# The tracer's whole premise is ONE clock (time.perf_counter from a
# single epoch): every span, latency reservoir sample, deadline, and
# watchdog beat in obs/ and serve/ must come off it. time.time() /
# datetime.now() are wall clocks — NTP steps them, they jump across
# suspend, and mixing them with perf_counter intervals silently skews
# exactly the numbers this layer exists to make trustworthy. The rule
# is PATH-scoped: wall-clock reads elsewhere (bench stamps, file
# mtimes) are fine.
_H5_BANNED = {
    "time.time": "time.perf_counter()",
    "datetime.now": "time.perf_counter()",
    "datetime.utcnow": "time.perf_counter()",
    "datetime.datetime.now": "time.perf_counter()",
    "datetime.datetime.utcnow": "time.perf_counter()",
}
_H5_PATHS = ("sparkdl_tpu/obs/", "sparkdl_tpu/serve/")


class _H5Clock(_ScopedVisitor):
    def visit_Call(self, node: ast.Call):
        name = _dotted(node.func)
        if name in _H5_BANNED:
            self.flag(
                "H5", node,
                f"`{name}()` in the obs/serve timing layer: span and "
                "latency math must share the tracer's monotonic clock "
                f"— use {_H5_BANNED[name]} (wall time jumps with NTP/"
                "suspend and silently skews the one timeline this "
                "layer exists to keep honest); a genuine wall-clock "
                "need (a human-readable artifact stamp) suppresses: "
                "`# sparkdl-lint: allow[H5] -- <why>`")
        self.generic_visit(node)


def check_h5(tree: ast.AST, path: str) -> List[Finding]:
    if not _path_in(path, _H5_PATHS):
        return []
    v = _H5Clock(path)
    v.visit(tree)
    return v.findings


# ---------------------------------------------------------------------------
# H6 — metric-name cardinality (request ids must never become keys)

# The registry is a name → metric table that lives for the process and
# renders every entry to /metricsz on each scrape. A metric NAME built
# from a per-request identifier therefore grows without bound (one
# request = one eternal registry entry + one Prometheus series) — the
# classic cardinality explosion that kills a metrics backend. The
# per-request layer has purpose-built homes for these values instead:
# the bounded RequestLog, reservoir exemplars, and span args
# (obs/request_log.py). The rule is lexical, matching this repo's
# idiom: a registry factory call whose name expression interpolates a
# request-shaped identifier.

_H6_METRIC_FACTORIES = {"counter", "gauge", "reservoir"}
_H6_REQUEST_NAMES = {"request_id", "req_id", "rid"}


def _h6_request_ident(expr: ast.AST) -> Optional[str]:
    """The first request-shaped identifier used inside a metric-name
    expression, or None. Matches bare names (``rid``), attribute tails
    (``req.rid``, ``record.request_id``), and anything whose name ends
    in ``request_id``."""
    for node in ast.walk(expr):
        name: Optional[str] = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name is None:
            continue
        low = name.lower()
        if low in _H6_REQUEST_NAMES or low.endswith("request_id"):
            return name
    return None


class _H6Cardinality(_ScopedVisitor):
    def visit_Call(self, node: ast.Call):
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _H6_METRIC_FACTORIES:
            # the metric name: first positional, or the name= kwarg —
            # the keyword spelling is just as legal a call form
            name_arg = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords
                 if kw.arg == "name"), None)
            if name_arg is not None \
                    and not isinstance(name_arg, ast.Constant):
                ident = _h6_request_ident(name_arg)
                if ident is not None:
                    self.flag(
                        "H6", node,
                        f"metric name built from `{ident}`: a "
                        "per-request id as a registry key grows one "
                        "eternal metric (and Prometheus series) PER "
                        "REQUEST — unbounded cardinality. Request ids "
                        "belong in the bounded RequestLog, reservoir "
                        "exemplars, or span args "
                        "(obs/request_log.py), never in metric names "
                        "(suppress: `# sparkdl-lint: allow[H6] -- "
                        "<why this key set is bounded>`)")
        self.generic_visit(node)


def check_h6(tree: ast.AST, path: str) -> List[Finding]:
    v = _H6Cardinality(path)
    v.visit(tree)
    return v.findings


# ---------------------------------------------------------------------------
# H12 — exception-flow accounting (serve/obs/runtime hot paths)

# PR 7's population-separation fix established the invariant: every
# failure on a serving/observability hot path must LAND somewhere an
# operator can see — a failure counter, an SLO outcome, a re-raise, a
# recorded error field. An `except` that swallows (pass, bare
# continue, or log-only: logs rotate away, counters don't) breaks the
# accounting chain that makes `serve.failures`, the availability burn
# rate, and the flight recorder's triggers trustworthy. The rule is
# PATH-scoped to the hot paths; swallows elsewhere stay H4's
# (cleanup-path) business.

_H12_PATHS = ("sparkdl_tpu/serve/", "sparkdl_tpu/obs/",
              "sparkdl_tpu/runtime/")
_H12_LOG_NAMES = {"print", "warn_once"}
_H12_LOG_METHODS = {"debug", "info", "warning", "error", "exception",
                    "critical", "log"}


def _h12_is_log_call(call: ast.Call) -> bool:
    name = _dotted(call.func)
    if name in _H12_LOG_NAMES or name == "warnings.warn":
        return True
    if name and name.startswith("logging."):
        return True
    if isinstance(call.func, ast.Attribute) and \
            call.func.attr in _H12_LOG_METHODS:
        recv = call.func.value
        # the chained form: logging.getLogger(__name__).warning(...) —
        # the receiver is a CALL, so _dotted() can't name it
        if isinstance(recv, ast.Call):
            recv_fn = _dotted(recv.func) or ""
            return recv_fn.rsplit(".", 1)[-1] == "getLogger"
        recv_name = (_dotted(recv) or "").lower()
        return "log" in recv_name or recv_name.startswith("warnings")
    return False


def _h12_swallows(handler: ast.ExceptHandler) -> bool:
    """True when every statement in the handler is accounting-free:
    pass / bare continue / docstring / import / a log-only call. Any
    raise, return, assignment (the error lands in state), counter
    ``.inc()``/``.add()``, ``record_failure``, ``set_exception`` —
    anything that BINDS the failure to an observable outcome — makes
    the handler accountable and clean."""
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Import,
                             ast.ImportFrom)):
            continue
        if isinstance(stmt, ast.Expr):
            if isinstance(stmt.value, ast.Constant):
                continue
            if isinstance(stmt.value, ast.Call) and \
                    _h12_is_log_call(stmt.value):
                continue
        return False
    return True


class _H12ExceptionFlow(_ScopedVisitor):
    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        if _h12_swallows(node):
            kind = ("bare `continue`" if any(
                isinstance(s, ast.Continue) for s in node.body)
                else "log-only" if any(
                    isinstance(s, ast.Expr)
                    and isinstance(s.value, ast.Call)
                    for s in node.body)
                else "`pass`")
            self.flag(
                "H12", node,
                f"{kind} exception handler on a serve/obs/runtime hot "
                "path: the failure reaches no counter, SLO outcome, "
                "or error state — the accounting chain (serve."
                "failures, availability burn, flight triggers) "
                "silently loses it; record a failure counter/SLO "
                "outcome on the handler path (the PR-7 population-"
                "separation contract), or suppress with "
                "`# sparkdl-lint: allow[H12] -- <why this failure "
                "needs no accounting>`")
        self.generic_visit(node)


def _path_in(path: str, prefixes) -> bool:
    """Is ``path`` inside one of the package-relative ``prefixes``?
    Checked against the path as given AND its absolute form — linting
    ``obs/`` from inside the package dir must not silently skip a
    path-scoped rule."""
    for cand in (path, os.path.abspath(path)):
        norm = cand.replace("\\", "/")
        if any(p in norm for p in prefixes):
            return True
    return False


def check_h12(tree: ast.AST, path: str) -> List[Finding]:
    if not _path_in(path, _H12_PATHS):
        return []
    v = _H12ExceptionFlow(path)
    v.visit(tree)
    return v.findings


# ---------------------------------------------------------------------------
# H13 — unbounded retry loops (serve/runtime/data/resilience paths)

# PR 11's resilience contract: every re-attempt on a hot path runs
# under the shared RetryPolicy — bounded attempts, exponential
# backoff, a retry budget (resilience/policy.py). The shape that
# breaks all three at once is the bare `while True: try/except` whose
# handler swallows AND continues: on sustained failure it spins
# forever, unthrottled, amplifying the load on the exact dependency
# that is already failing. The rule flags an unbounded-test loop
# (`while True` / `while 1`) containing an except handler with no
# escape (no raise/break/return reachable in the handler): on the
# exception path, nothing ever ends the loop. Loops whose handler
# re-raises, breaks, or returns — including RetryPolicy.call, whose
# handler re-raises on grant() refusal — are clean by construction.

_H13_PATHS = ("sparkdl_tpu/serve/", "sparkdl_tpu/runtime/",
              "sparkdl_tpu/data/", "sparkdl_tpu/resilience/")

_H13_SCOPE_STOPS = (ast.FunctionDef, ast.AsyncFunctionDef,
                    ast.ClassDef, ast.Lambda)


def _h13_unbounded(node: ast.While) -> bool:
    return isinstance(node.test, ast.Constant) \
        and node.test.value in (True, 1)


def _h13_handlers(stmts, out: List[ast.ExceptHandler]) -> None:
    """Except handlers whose swallow retries THIS unbounded loop:
    everything reachable in its body except nested defs (a callback's
    control flow is the callee's) and nested unbounded whiles (their
    own visit). Nested BOUNDED loops (for / `while cond`) descend —
    a per-iteration-bounded inner loop still re-enters the outer
    `while True` forever when its handler swallows."""
    for s in stmts:
        if isinstance(s, _H13_SCOPE_STOPS):
            continue
        if isinstance(s, ast.While) and _h13_unbounded(s):
            continue
        if isinstance(s, ast.Try):
            out.extend(s.handlers)
            _h13_handlers(s.body, out)
            _h13_handlers(s.orelse, out)
            _h13_handlers(s.finalbody, out)
            for h in s.handlers:
                _h13_handlers(h.body, out)
        elif isinstance(s, (ast.If, ast.While)):
            _h13_handlers(s.body, out)
            _h13_handlers(s.orelse, out)
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            _h13_handlers(s.body, out)
            _h13_handlers(s.orelse, out)
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            _h13_handlers(s.body, out)
        elif isinstance(s, ast.Match):
            for case in s.cases:
                _h13_handlers(case.body, out)


def _h13_escapes(stmts, loop_depth: int = 0) -> bool:
    """Does any raise/return — or a break that actually exits the
    flagged loop — sit on this handler's own paths? Nested defs are
    excluded (their control flow is the callee's), and ``loop_depth``
    tracks handler-internal loops so a `break` that only exits an
    inner for/while is NOT read as escaping the unbounded one."""
    for s in stmts:
        if isinstance(s, _H13_SCOPE_STOPS):
            continue
        if isinstance(s, (ast.Raise, ast.Return)):
            return True
        if isinstance(s, ast.Break) and loop_depth == 0:
            return True
        child_depth = loop_depth + 1 if isinstance(
            s, (ast.For, ast.AsyncFor, ast.While)) else loop_depth
        for child in ast.iter_child_nodes(s):
            if isinstance(child, _H13_SCOPE_STOPS):
                continue
            if _h13_escapes([child], child_depth):
                return True
    return False


class _H13RetryLoops(_ScopedVisitor):
    def visit_While(self, node: ast.While):
        if _h13_unbounded(node):
            handlers: List[ast.ExceptHandler] = []
            _h13_handlers(node.body, handlers)
            for handler in handlers:
                if not _h13_escapes(handler.body):
                    self.flag(
                        "H13", handler,
                        "retry-shaped `while True` on a serve/runtime"
                        "/data path: this except handler swallows and "
                        "loops again with no escape (raise/break/"
                        "return) — on sustained failure the loop "
                        "spins forever, unthrottled, amplifying load "
                        "on the failing dependency. Re-attempts must "
                        "be bounded and backed-off: run them under "
                        "resilience.RetryPolicy (attempts + "
                        "exponential backoff + retry budget, "
                        "docs/RESILIENCE.md), or suppress with "
                        "`# sparkdl-lint: allow[H13] -- <what bounds "
                        "and paces this loop>`")
        self.generic_visit(node)


def check_h13(tree: ast.AST, path: str) -> List[Finding]:
    if not _path_in(path, _H13_PATHS):
        return []
    v = _H13RetryLoops(path)
    v.visit(tree)
    return v.findings


# ---------------------------------------------------------------------------
# registry

RULES: Dict[str, Callable[[ast.AST, str], List[Finding]]] = {
    "H1": check_h1,
    "H2": check_h2,
    "H3": check_h3,
    "H4": check_h4,
    "H5": check_h5,
    "H6": check_h6,
    "H12": check_h12,
    "H13": check_h13,
}

_RULE_DOCS = {
    "H1": "implicit host transfers outside the allowlisted drain path "
          "(jax.device_get / .block_until_ready() / np.asarray over a "
          "jnp-producing call)",
    "H2": "jit/retrace hazards: trace-time side effects (time.*, "
          "print, stateful RNG, obs tracing spans) inside "
          "jit/pjit-compiled functions; mutable "
          "static_argnums/static_argnames literals",
    "H3": "concurrency discipline: lock-holding classes need "
          "__getstate__/__reduce__; writes to _lock_guards-declared "
          "fields must hold self._lock",
    "H4": "quiesce hygiene: bare except; silently swallowed "
          "exceptions in cleanup/finally paths",
    "H5": "clock discipline in sparkdl_tpu/obs/ and sparkdl_tpu/serve/"
          ": time.time()/datetime.now() banned — span/latency math "
          "shares the tracer's time.perf_counter clock",
    "H6": "metric-name cardinality: registry counter/gauge/reservoir "
          "names interpolating a request id (request_id/req_id/rid) "
          "banned — per-request values go to the RequestLog / "
          "exemplars / span args, never into metric names",
    "H7": "lock-order cycles (whole-program): the acquired-while-"
          "holding graph across every analyzed module must be acyclic "
          "— any cycle is a deadlock schedule, reported with its "
          "module-by-module witness path (the PR-2 collective-enqueue "
          "shape)",
    "H8": "blocking call under a lock (whole-program): device syncs, "
          "Condition/Event waits, queue.get, time.sleep, file/socket "
          "I/O, thread joins — direct or through any resolved call "
          "chain — while a lock is held",
    "H9": "contract drift: registry keys / span lanes / env vars / "
          "/statusz fields the code publishes vs the docs tables "
          "(docs/OBSERVABILITY.md, docs/SERVING.md, "
          "docs/PERFORMANCE.md), BOTH directions — undocumented "
          "publishes and documented-but-gone names both fail",
    "H10": "effectful call reachable from jit (whole-program): any "
           "effect — registry writes, spans, logging, clocks/RNG, "
           "transfers, I/O, lock acquires, mutation of captured "
           "state — transitively reachable from a jax.jit/pjit-traced "
           "body through resolved call edges, with the witness chain "
           "printed; plus mutable state (lists/dicts/instance attrs) "
           "captured into a jitted function — the stale-value/"
           "retrace hazard the lexical H2 cannot see",
    "H11": "resource lifecycle (whole-program): an object whose class "
           "defines close/quiesce/shutdown/disarm — plus open()/"
           "tempfile handles and obs-singleton arm()s — constructed "
           "in a scope must reach its terminator there or escape "
           "(returned, stored on self/a global, registered, passed "
           "on); a leaked lifecycle keeps threads/sockets/arm state "
           "alive past the scope",
    "H12": "exception-flow accounting (sparkdl_tpu/serve/, obs/, "
           "runtime/): an except that swallows — pass, bare "
           "continue, or log-only — must record a failure counter/"
           "SLO outcome on the handler path or carry an inline "
           "suppression (the PR-7 population-separation fix as a "
           "static invariant)",
    "H13": "unbounded retry loops (sparkdl_tpu/serve/, runtime/, "
           "data/, resilience/): a `while True` whose except handler "
           "swallows and loops again with no escape — re-attempts "
           "must be bounded and backed-off (resilience.RetryPolicy: "
           "attempts + exponential backoff + retry budget), never a "
           "bare spin on a failing dependency",
    "H14": "hot-path host sync (whole-program): a device-resident "
           "value materialized on host — np.asarray/np.array, "
           ".item()/.tolist(), float()/int()/bool()/len(), "
           "truthiness, iteration — inside a function transitively "
           "reachable from the runner dispatch/drain loops, the "
           "serve dispatcher, the engine stream/re-chunk path, or "
           "the estimator step loops (the watchdog-beating roots), "
           "anywhere except the sanctioned timed_device_get drain; "
           "the hot witness chain is printed module-by-module",
    "H15": "missing buffer donation (whole-program): a call of a "
           "jax.jit/ModelFunction.jitted()-compiled callable whose "
           "device-array argument is dead after the call (last "
           "lexical use, no escape, not loop-carried) but the "
           "compile site declares no donate_argnums — XLA keeps the "
           "input buffer alive instead of reusing its HBM for the "
           "outputs (the parallel/train.py donate_argnums=(0,) "
           "precedent)",
    "H16": "dtype widening on a hot path (whole-program): Python "
           "float / np.float64 scalars and dtype-less "
           "np.zeros/ones/arange/asarray mixed into arithmetic with "
           "a device-tracked value on a hot function — the promoted "
           "float64 payload is a silent 2x byte tax on a link-bound "
           "pipeline; pin the dtype at the producer",
    "H17": "unguarded access to a guarded attribute (whole-program): "
           "a read/write of a class attribute the guarded-by "
           "inference ties to a lock (majority of accesses hold it, "
           "or `_lock_guards` declares it), from a function >= 2 "
           "threads may execute (thread-topology reachability over "
           "the call graph), without the guard held — the witness "
           "names both thread roots, the lock, and the vote",
    "H18": "unsafe publication (whole-program): a mutable local "
           "handed across a thread boundary — Thread/Timer args, "
           "executor submit/map, a done-callback, or closure capture "
           "by the spawned def — then mutated on both sides with no "
           "common lock; hand over a snapshot or share a lock",
    "H19": "atomicity split (whole-program): check-then-act on a "
           "guarded attribute where the check's lock hold ends "
           "before the acting hold — both sides locked, decision "
           "stale (the TOCTOU on self._closed / queue-depth "
           "patterns); widen one hold over both",
}


def rule_doc(rule: str) -> str:
    return _RULE_DOCS[rule.upper()]
